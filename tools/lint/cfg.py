"""Intraprocedural control-flow graphs over Python ASTs (graft-lint 4.0).

Why a CFG layer
---------------
graft-lint 1.0-3.0 reason about *what* a function mentions (calls, locks,
globals) but not *in which order along which path*.  Exception-flow and
resource-ownership questions ("is every allocated KV page freed on every
path, including the path where the prefill program raises?") are inherently
path questions, so PR 18 adds this small, reusable CFG builder.  It is a
lint-grade CFG, not an interpreter:

- Every function body becomes a graph of :class:`Block`\\ s.  A block holds a
  list of ``ast.stmt`` nodes (compound statements appear in the block where
  their header/test evaluates; their suites get their own blocks).
- Edges carry a ``kind`` string: ``next``, ``true``/``false`` (branches),
  ``case`` (match arms), ``back`` (loop back-edge), ``break``/``continue``,
  ``except`` (a statement in the source block may raise and control lands at
  the target), ``raise`` (an explicit ``raise`` statement), ``return``
  (explicit *and* implicit fall-off-the-end return).
- Three synthetic blocks exist on every CFG: ``entry``, ``exit`` (normal
  return) and ``raise_exit`` (exception leaves the function).
- ``try``/``except``/``else`` is modelled with block-level ``except`` edges
  from every statement-bearing block of the protected suite to each handler
  entry; if no handler is a catch-all (bare / ``Exception`` /
  ``BaseException``) the exception may also propagate outward.
- A bare ``raise`` inside a handler re-raises exactly the types that handler
  caught, so its ``raise`` edges are *typed*: an enclosing handler naming one
  of those types exactly (or catching everything) definitely stops it, and
  the blind propagate-outward edge is dropped.  Handlers with other names
  stay targets (they may catch a subclass relation this layer cannot see).
- ``finally`` suites are *cloned* per continuation (normal, exceptional,
  and each ``return``/``break``/``continue`` that unwinds through them), the
  way compilers lower them.  This keeps paths real: a normal-path traversal
  never exits through the exceptional copy of a ``finally``.
- ``with`` bodies are ordinary blocks (``__exit__`` is assumed to re-raise);
  the ``with`` statement itself sits in the preceding block, so analyses can
  special-case context-managed acquisitions (all-paths release).

Invariant relied on by analyses: the enclosing frame stack (try/finally/
loop) is constant across all statements of any single block, so block-level
``except`` edges are sound for every statement in the block.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

__all__ = ["Block", "CFG", "build_cfg", "iter_cfgs"]

EDGE_KINDS = frozenset({
    "next", "true", "false", "case", "back",
    "break", "continue", "except", "raise", "return",
})

_CATCH_ALL_NAMES = ("Exception", "BaseException")


class Block:
    """A run of statements with a single frame context.

    ``stmts`` holds the original ``ast.stmt`` nodes (never copies), so every
    block keys straight back into the tree the caller parsed.
    """

    __slots__ = ("bid", "label", "stmts", "succs", "handler_types")

    def __init__(self, bid: int, label: str = "") -> None:
        self.bid = bid
        self.label = label
        self.stmts: List[ast.stmt] = []
        self.succs: List[Tuple[int, str]] = []
        #: for handler-entry blocks: the caught exception names (last
        #: dotted components; ("*",) for bare except). None elsewhere.
        #: Analyses use it to skip edges into handlers that can only
        #: catch exceptions the modelled state cannot be carrying.
        self.handler_types: Optional[Tuple[str, ...]] = None

    def edge(self, target: int, kind: str) -> None:
        assert kind in EDGE_KINDS, kind
        if (target, kind) not in self.succs:
            self.succs.append((target, kind))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        lines = [getattr(s, "lineno", "?") for s in self.stmts]
        return (f"Block({self.bid}{':' + self.label if self.label else ''}"
                f" lines={lines} succs={self.succs})")


class CFG:
    """Control-flow graph of one function body."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.blocks: Dict[int, Block] = {}
        self._next = 0
        self.entry = self.new_block("entry").bid
        self.exit = self.new_block("exit").bid
        self.raise_exit = self.new_block("raise").bid

    # -- construction --------------------------------------------------
    def new_block(self, label: str = "") -> Block:
        b = Block(self._next, label)
        self._next += 1
        self.blocks[b.bid] = b
        return b

    # -- queries -------------------------------------------------------
    def block(self, bid: int) -> Block:
        return self.blocks[bid]

    def edges(self) -> Iterator[Tuple[int, int, str]]:
        for b in self.blocks.values():
            for tgt, kind in b.succs:
                yield (b.bid, tgt, kind)

    def preds(self, bid: int) -> List[Tuple[int, str]]:
        return [(b.bid, kind) for b in self.blocks.values()
                for tgt, kind in b.succs if tgt == bid]

    def reachable(self) -> frozenset:
        """Block ids reachable from ``entry`` over any edge kind."""
        seen = {self.entry}
        stack = [self.entry]
        while stack:
            for tgt, _ in self.blocks[stack.pop()].succs:
                if tgt not in seen:
                    seen.add(tgt)
                    stack.append(tgt)
        return frozenset(seen)

    def orphan_blocks(self) -> List[Block]:
        """Blocks not reachable from entry (exit blocks excluded).

        A well-formed build of a function without dead code has none; this
        is the property pinned over ``paddle_tpu/serving/`` in tier-1.
        """
        live = self.reachable()
        return [b for b in self.blocks.values()
                if b.bid not in live
                and b.bid not in (self.exit, self.raise_exit)]

    def blocks_with(self, node: ast.stmt) -> List[Block]:
        """Blocks whose statement list contains ``node`` (clones included)."""
        return [b for b in self.blocks.values() if node in b.stmts]

    # -- cleanup -------------------------------------------------------
    def prune(self) -> None:
        """Drop empty, predecessor-less utility blocks (dead joins).

        Join/after blocks are created eagerly during the build; when both
        branches of an ``if`` return, or a ``while True`` has no ``break``,
        the join is never wired.  Statement-bearing blocks are never pruned
        (genuinely dead code stays visible as an orphan).
        """
        changed = True
        while changed:
            changed = False
            has_pred = {tgt for b in self.blocks.values() for tgt, _ in b.succs}
            for bid in list(self.blocks):
                b = self.blocks[bid]
                if bid in (self.entry, self.exit, self.raise_exit):
                    continue
                if not b.stmts and bid not in has_pred:
                    del self.blocks[bid]
                    changed = True


class _LoopFrame:
    __slots__ = ("cont", "brk")

    def __init__(self, cont: int, brk: int) -> None:
        self.cont = cont
        self.brk = brk


class _TryFrame:
    __slots__ = ("handler_bids", "catch_all")

    def __init__(self, handler_bids: List[int], catch_all: bool) -> None:
        self.handler_bids = handler_bids
        self.catch_all = catch_all


class _FinallyFrame:
    __slots__ = ("stmts", "exc_clone")

    def __init__(self, stmts: List[ast.stmt]) -> None:
        self.stmts = stmts
        self.exc_clone: Optional[int] = None


def _handler_type_names(handler: ast.ExceptHandler) -> Tuple[str, ...]:
    t = handler.type
    if t is None:
        return ("*",)
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    names = []
    # last dotted component is enough: analyses match simple names
    for e in elts:
        if isinstance(e, ast.Name):
            names.append(e.id)
        elif isinstance(e, ast.Attribute):
            names.append(e.attr)
        else:
            names.append("*")  # computed type: match anything
    return tuple(names)


def _is_catch_all(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name) and t.id in _CATCH_ALL_NAMES:
        return True
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _CATCH_ALL_NAMES
                   for e in t.elts)
    return False


class _Builder:
    def __init__(self, fn: ast.AST) -> None:
        self.cfg = CFG(getattr(fn, "name", "<lambda>"))
        self.frames: list = []
        #: caught-type names of the handler bodies currently being visited
        #: (innermost last); lets bare ``raise`` take typed targets
        self.handler_ctx: List[Tuple[str, ...]] = []
        first = self.cfg.new_block()
        self.cfg.block(self.cfg.entry).edge(first.bid, "next")
        self.cur: Optional[Block] = first

    # -- plumbing ------------------------------------------------------
    def _ensure_cur(self) -> Block:
        if self.cur is None:
            # dead code after an abrupt exit: give it a home so it stays
            # visible (it will show up as an orphan block).
            self.cur = self.cfg.new_block("dead")
        return self.cur

    def _append(self, node: ast.stmt) -> Block:
        b = self._ensure_cur()
        if not b.stmts:
            for tgt in self._exc_targets(len(self.frames) - 1):
                b.edge(tgt, "except")
        b.stmts.append(node)
        return b

    def _exc_targets(self, i: int) -> List[int]:
        """Where an exception raised under ``frames[:i+1]`` can land."""
        while i >= 0:
            f = self.frames[i]
            if isinstance(f, _TryFrame):
                out = list(f.handler_bids)
                if not f.catch_all:
                    out.extend(self._exc_targets(i - 1))
                return out
            if isinstance(f, _FinallyFrame):
                if f.exc_clone is None:
                    f.exc_clone = self._clone_suite(
                        f.stmts, i, self._exc_targets(i - 1), "raise")
                return [f.exc_clone]
            i -= 1
        return [self.cfg.raise_exit]

    def _typed_exc_targets(self, i: int, types: Tuple[str, ...]) -> List[int]:
        """Where a re-raise of exactly ``types`` can land.

        Used for a bare ``raise`` in a handler body, where the in-flight
        types are known.  An enclosing handler naming a type exactly — or
        catching everything — definitely stops that type.  A handler with a
        different name may still catch it through a subclass relation this
        layer cannot see, so it stays a target but propagation continues.
        """
        pending = list(types)
        out: List[int] = []
        while i >= 0 and pending:
            f = self.frames[i]
            if isinstance(f, _TryFrame):
                still: List[str] = []
                for t in pending:
                    stopped = False
                    for hb in f.handler_bids:
                        names = self.cfg.block(hb).handler_types or ("*",)
                        if ("*" in names or t in names or
                                any(n in _CATCH_ALL_NAMES for n in names)):
                            if hb not in out:
                                out.append(hb)
                            stopped = True
                            break
                        if hb not in out:  # possible subclass catch
                            out.append(hb)
                    if not stopped:
                        still.append(t)
                pending = still
            elif isinstance(f, _FinallyFrame):
                # type information does not survive a finally clone — the
                # clone's continuation was built with blind targets
                if f.exc_clone is None:
                    f.exc_clone = self._clone_suite(
                        f.stmts, i, self._exc_targets(i - 1), "raise")
                return out + [f.exc_clone]
            i -= 1
        if pending:
            out.append(self.cfg.raise_exit)
        return out

    def _clone_suite(self, stmts: List[ast.stmt], context_len: int,
                     targets: List[int], kind: str) -> int:
        """Build a copy of a ``finally`` suite for one continuation."""
        saved_cur, saved_frames = self.cur, self.frames
        self.frames = list(self.frames[:context_len])
        entry = self.cfg.new_block("finally")
        self.cur = entry
        for s in stmts:
            self._visit(s)
        if self.cur is not None:
            for t in targets:
                self.cur.edge(t, kind)
        self.cur, self.frames = saved_cur, saved_frames
        return entry.bid

    def _unwind(self, final_target: int, kind: str,
                stop_at_loop: bool = False) -> int:
        """Chain ``finally`` clones for an abrupt exit; return first hop."""
        lo = 0
        if stop_at_loop:
            for i in range(len(self.frames) - 1, -1, -1):
                if isinstance(self.frames[i], _LoopFrame):
                    lo = i + 1
                    break
        target = final_target
        for i in range(lo, len(self.frames)):
            f = self.frames[i]
            if isinstance(f, _FinallyFrame):
                target = self._clone_suite(f.stmts, i, [target], kind)
        return target

    # -- statement dispatch -------------------------------------------
    def _visit(self, node: ast.stmt) -> None:
        meth = getattr(self, "visit_" + type(node).__name__, None)
        if meth is not None:
            meth(node)
        else:
            self._append(node)

    def visit_body(self, stmts: List[ast.stmt]) -> None:
        for s in stmts:
            self._visit(s)

    # -- simple abrupt statements -------------------------------------
    def visit_Return(self, node: ast.Return) -> None:
        b = self._append(node)
        b.edge(self._unwind(self.cfg.exit, "return"), "return")
        self.cur = None

    def visit_Raise(self, node: ast.Raise) -> None:
        b = self._append(node)
        ctx = self.handler_ctx[-1] if self.handler_ctx else None
        if node.exc is None and ctx and "*" not in ctx:
            targets = self._typed_exc_targets(len(self.frames) - 1, ctx)
        else:
            targets = self._exc_targets(len(self.frames) - 1)
        for t in targets:
            b.edge(t, "raise")
        self.cur = None

    def visit_Break(self, node: ast.Break) -> None:
        b = self._append(node)
        brk = next((f.brk for f in reversed(self.frames)
                    if isinstance(f, _LoopFrame)), self.cfg.exit)
        b.edge(self._unwind(brk, "break", stop_at_loop=True), "break")
        self.cur = None

    def visit_Continue(self, node: ast.Continue) -> None:
        b = self._append(node)
        cont = next((f.cont for f in reversed(self.frames)
                     if isinstance(f, _LoopFrame)), self.cfg.exit)
        b.edge(self._unwind(cont, "continue", stop_at_loop=True), "continue")
        self.cur = None

    # -- branches ------------------------------------------------------
    def visit_If(self, node: ast.If) -> None:
        src = self._append(node)
        join = self.cfg.new_block()
        then_b = self.cfg.new_block()
        src.edge(then_b.bid, "true")
        self.cur = then_b
        self.visit_body(node.body)
        if self.cur is not None:
            self.cur.edge(join.bid, "next")
        if node.orelse:
            else_b = self.cfg.new_block()
            src.edge(else_b.bid, "false")
            self.cur = else_b
            self.visit_body(node.orelse)
            if self.cur is not None:
                self.cur.edge(join.bid, "next")
        else:
            src.edge(join.bid, "false")
        self.cur = join

    def visit_Match(self, node: ast.stmt) -> None:
        src = self._append(node)
        join = self.cfg.new_block()
        for case in node.cases:
            cb = self.cfg.new_block()
            src.edge(cb.bid, "case")
            self.cur = cb
            self.visit_body(case.body)
            if self.cur is not None:
                self.cur.edge(join.bid, "next")
        src.edge(join.bid, "false")  # no arm matched
        self.cur = join

    # -- loops ---------------------------------------------------------
    def _loop(self, node: ast.stmt, const_true: bool) -> None:
        header = self.cfg.new_block("loop")
        self._ensure_cur().edge(header.bid, "next")
        self.cur = header
        self._append(node)  # header/test evaluates here (wires except edges)
        after = self.cfg.new_block()
        body = self.cfg.new_block()
        header.edge(body.bid, "true")
        self.frames.append(_LoopFrame(header.bid, after.bid))
        self.cur = body
        self.visit_body(node.body)
        if self.cur is not None:
            self.cur.edge(header.bid, "back")
        self.frames.pop()
        if not const_true:
            if node.orelse:
                eb = self.cfg.new_block()
                header.edge(eb.bid, "false")
                self.cur = eb
                self.visit_body(node.orelse)
                if self.cur is not None:
                    self.cur.edge(after.bid, "next")
            else:
                header.edge(after.bid, "false")
        self.cur = after

    def visit_While(self, node: ast.While) -> None:
        const_true = isinstance(node.test, ast.Constant) and bool(node.test.value)
        self._loop(node, const_true)

    def visit_For(self, node: ast.For) -> None:
        self._loop(node, False)

    visit_AsyncFor = visit_For

    # -- with ----------------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        self._append(node)  # context managers evaluate here
        body = self.cfg.new_block()
        self._ensure_cur().edge(body.bid, "next")
        self.cur = body
        self.visit_body(node.body)
        if self.cur is not None:
            after = self.cfg.new_block()
            self.cur.edge(after.bid, "next")
            self.cur = after
        else:
            self.cur = None

    visit_AsyncWith = visit_With

    # -- try -----------------------------------------------------------
    def visit_Try(self, node: ast.Try) -> None:
        fin = _FinallyFrame(node.finalbody) if node.finalbody else None
        if fin is not None:
            self.frames.append(fin)
        h_blocks = [self.cfg.new_block("handler") for _ in node.handlers]
        for handler, hb in zip(node.handlers, h_blocks):
            hb.handler_types = _handler_type_names(handler)
        catch_all = any(_is_catch_all(h) for h in node.handlers)
        body_entry = self.cfg.new_block()
        self._ensure_cur().edge(body_entry.bid, "next")
        self.frames.append(_TryFrame([b.bid for b in h_blocks], catch_all))
        self.cur = body_entry
        self.visit_body(node.body)
        self.frames.pop()  # the handlers no longer cover else/handler suites
        if self.cur is not None and node.orelse:
            eb = self.cfg.new_block()
            self.cur.edge(eb.bid, "next")
            self.cur = eb
            self.visit_body(node.orelse)
        ends = [self.cur] if self.cur is not None else []
        for handler, hb in zip(node.handlers, h_blocks):
            self.cur = hb
            self.handler_ctx.append(hb.handler_types or ("*",))
            self.visit_body(handler.body)
            self.handler_ctx.pop()
            if self.cur is not None:
                ends.append(self.cur)
        join = self.cfg.new_block()
        for e in ends:
            e.edge(join.bid, "next")
        self.cur = join if ends else None
        if fin is not None:
            self.frames.pop()
            if self.cur is not None:
                # the normal-continuation copy of the finally suite runs
                # inline on the join path
                self.visit_body(node.finalbody)

    visit_TryStar = visit_Try


def build_cfg(fn: ast.AST) -> CFG:
    """Build the CFG of one ``FunctionDef``/``AsyncFunctionDef`` body.

    Nested function/class definitions are single statements of the enclosing
    graph (their bodies are separate CFGs via :func:`iter_cfgs`).
    """
    builder = _Builder(fn)
    builder.visit_body(fn.body)
    if builder.cur is not None:  # implicit `return None` off the end
        builder.cur.edge(builder.cfg.exit, "return")
    builder.cfg.prune()
    return builder.cfg


def iter_cfgs(tree: ast.AST) -> Iterator[Tuple[str, ast.AST, CFG]]:
    """Yield ``(qualname, fn_node, cfg)`` for every def in a module tree.

    Qualnames follow the summary layer's convention: ``Class.method`` for
    methods, ``outer.inner`` for nested defs.
    """
    def rec(node: ast.AST, prefix: str) -> Iterator[Tuple[str, ast.AST, CFG]]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                yield (qual, child, build_cfg(child))
                yield from rec(child, qual + ".")
            elif isinstance(child, ast.ClassDef):
                yield from rec(child, f"{prefix}{child.name}.")
    yield from rec(tree, "")
