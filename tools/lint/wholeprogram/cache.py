"""Content-hash keyed on-disk cache for summaries and per-file findings.

One JSON file holds, per repo-relative path:

* ``sha``      — sha256 of the file content the entry was built from;
* ``summary``  — the :class:`ModuleSummary` dict (project-graph input);
* ``findings`` — per-rule post-suppression finding dicts from the
  per-file rules, so a warm full run skips parsing entirely.

Self-invalidation, in decreasing blast radius:

* ``CACHE_FORMAT_VERSION`` — bump when the cache layout, the summary
  schema (``SUMMARY_FORMAT`` is folded in), or any rule's semantics
  change; a mismatch discards the whole file;
* config fingerprint — the engine config is hashed into the header, so a
  changed layer DAG / root list / rule option rebuilds everything;
* per-entry sha — an edited file rebuilds alone (the incremental path).

Entries whose file no longer exists are dropped at load so tmp-path runs
cannot grow the cache without bound. Saves go through a temp file +
``os.replace`` so a crashed run never leaves a torn cache.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Any, Dict, Optional

from .summary import SUMMARY_FORMAT

#: bump to invalidate every existing cache file (format/semantic changes)
#: (2: graft-lint 3.0 summary schema — call-site lock sets, access
#: records, spawn roots — and the shared-state-race rule)
#: (3: graft-lint 4.0 summary fields — raise-sets, resources)
CACHE_FORMAT_VERSION = 4  # 4: graft-lint 5.0 blocking events ("blk")


def default_cache_path() -> str:
    from ..engine import REPO_ROOT
    return os.path.join(REPO_ROOT, "tools", "lint", ".graft_lint_cache.json")


def content_sha(src: str) -> str:
    return hashlib.sha256(src.encode("utf-8")).hexdigest()


def config_fingerprint(config: Dict[str, Any], rule_names) -> str:
    blob = json.dumps({"config": config, "rules": sorted(rule_names),
                       "cache_format": CACHE_FORMAT_VERSION,
                       "summary_format": SUMMARY_FORMAT},
                      sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


class SummaryCache:
    def __init__(self, path: str, fingerprint: str,
                 entries: Optional[Dict[str, Dict[str, Any]]] = None):
        self.path = path
        self.fingerprint = fingerprint
        self.entries = entries or {}
        self.dirty = False

    @classmethod
    def load(cls, path: str, config: Dict[str, Any],
             rule_names, root: str) -> "SummaryCache":
        fp = config_fingerprint(config, rule_names)
        entries: Dict[str, Dict[str, Any]] = {}
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
            if data.get("format") == CACHE_FORMAT_VERSION and \
                    data.get("fingerprint") == fp:
                for rel, ent in data.get("entries", {}).items():
                    if os.path.exists(os.path.join(root, rel)):
                        entries[rel] = ent
        except (OSError, ValueError):
            pass  # missing or torn cache: start cold, first run rebuilds it
        return cls(path, fp, entries)

    def get(self, rel: str, sha: str) -> Optional[Dict[str, Any]]:
        ent = self.entries.get(rel)
        if ent is not None and ent.get("sha") == sha:
            return ent
        return None

    def _entry(self, rel: str, sha: str) -> Dict[str, Any]:
        ent = self.entries.get(rel)
        if ent is None or ent.get("sha") != sha:
            ent = {"sha": sha, "summary": None, "findings": {}}
            self.entries[rel] = ent
        return ent

    def put_summary(self, rel: str, sha: str,
                    summary_dict: Dict[str, Any]) -> None:
        self._entry(rel, sha)["summary"] = summary_dict
        self.dirty = True

    def put_findings(self, rel: str, sha: str,
                     per_rule: Dict[str, list]) -> None:
        self._entry(rel, sha)["findings"].update(per_rule)
        self.dirty = True

    def save(self) -> None:
        if not self.dirty:
            return
        data = {"format": CACHE_FORMAT_VERSION,
                "fingerprint": self.fingerprint,
                "entries": self.entries}
        d = os.path.dirname(self.path) or "."
        tmp = None
        try:
            os.makedirs(d, exist_ok=True)
            fd, tmp = tempfile.mkstemp(prefix=".graft_lint_cache.",
                                       dir=d, suffix=".tmp")
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(data, f, separators=(",", ":"))
            os.replace(tmp, self.path)
            tmp = None
            self.dirty = False  # only a successful write clears it
        except OSError:
            pass  # read-only checkout / disk full: run correctly, stay cold
        finally:
            if tmp is not None:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass  # best-effort cleanup of the torn temp file
