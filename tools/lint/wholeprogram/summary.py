"""Per-module summaries: everything the whole-program rules need, as JSON.

``build_summary`` walks one parsed module and extracts

* ``bindings``      — local name -> dotted import target (``import x.y as
  z``, ``from .m import f``; function-body imports included on purpose:
  the deferred-import idiom that breaks circular imports still creates
  call edges the trace/host-sync reachability must follow);
* ``module_imports``— module-scope import statements only (these run at
  import time and are what the layering/cycle rule constrains);
* ``functions``     — one record per def (methods carry their class):
  outgoing calls, impure reads, host-sync sites, and the lock structure
  (acquisitions, lexical lock nesting, calls made while holding a lock);
* ``locks`` / ``class_locks`` — module-level and ``self.<attr>`` lock
  objects with their ctor kind (Lock / RLock / Condition);
* ``trace_roots``   — the same root detection as the per-file
  trace-impurity rule (jax.jit, ``apply(name, fn, …)``, config extras,
  names called from inline traced lambdas);
* ``pragmas``       — the file's ``# graft-lint:`` suppression tables, so
  cached summaries can suppress project-rule findings without re-reading
  the file.

Everything is plain lists/dicts/strings → ``to_dict``/``from_dict`` are
trivial and the summary is exactly what ``SummaryCache`` persists.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from ..astutil import (IMPURE_MODULES, IMPURE_PREFIXES, MUTATORS,
                       dotted_name, lock_ctor_in, mentions_device_value,
                       module_lock_defs, module_mutable_globals,
                       path_matches, root_name, safe_ctor_in, snippet)

#: bump when the extracted shape changes so cached summaries self-invalidate
#: (2: graft-lint 3.0 — per-call held-lock sets, attribute-level access
#: records, and spawn-root discovery for the shared-state-race rule;
#: 3: graft-lint 4.0 — per-function raise-sets with enclosing catch sets,
#: caught-and-swallowed handler records, resource acquire/release events,
#: and per-module class base tables for exception-hierarchy matching;
#: 4: graft-lint 5.0 — per-function blocking events, kind-classified with
#: a timeout-boundedness bit, the lexical held-lock stack and
#: deadline_scope flag at each site, for the may-block rules)
SUMMARY_FORMAT = 4

_NP_CONVERTERS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array"}

# lock references are stored as small tagged lists (JSON-friendly):
#   ["mod", name]         — module-level lock of this module
#   ["self", Class, attr] — instance lock of a class in this module
#   ["ext", alias, attr]  — <import alias>.<attr>, resolved at project time


def module_name_for(path: str) -> str:
    """Dotted module name for a root-relative posix path."""
    p = path[:-3] if path.endswith(".py") else path
    parts = [x for x in p.split("/") if x not in (".", "")]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _resolve_relative(module: str, is_pkg: bool, level: int,
                      target: str) -> str:
    parts = module.split(".")
    if not is_pkg:
        parts = parts[:-1]
    drop = level - 1
    if drop:
        parts = parts[:-drop] if drop <= len(parts) else []
    base = ".".join(parts)
    if target:
        base = f"{base}.{target}" if base else target
    return base


@dataclass
class FunctionInfo:
    qualname: str                     # "Class.method" / "fn" / "fn.inner"
    name: str                         # simple name
    cls: Optional[str]                # enclosing class simple name
    line: int
    calls: List[Tuple[str, int]] = field(default_factory=list)
    impure: List[Tuple[str, str, int]] = field(default_factory=list)
    host_syncs: List[Tuple[str, int]] = field(default_factory=list)
    acquires: List[Tuple[list, int]] = field(default_factory=list)
    nest_edges: List[Tuple[list, list, int]] = field(default_factory=list)
    calls_under_lock: List[Tuple[list, str, int]] = field(
        default_factory=list)
    # graft-lint 3.0: one entry per call OCCURRENCE with the full lexical
    # held-lock stack at that site — (dotted name, [lockrefs], line). The
    # race rule intersects these per callee so a function called both
    # locked and unlocked propagates the conservative (empty) set.
    call_locks: List[Tuple[str, list, int]] = field(default_factory=list)
    # attribute-level shared-state accesses with the lexical lock set held
    # at each: ["self", Class, attr, "r"|"w", [lockrefs], line] for
    # ``self.<attr>`` fields, ["glob", name, "r"|"w", [lockrefs], line]
    # for module-level mutable globals (one-level alias tracked)
    accesses: List[list] = field(default_factory=list)
    # graft-lint 4.0 exception flow. ``raises``: one entry per explicit
    # ``raise`` statement — [resolved type name, catch context, line]. The
    # type name is resolved one level through imports/aliases ("QueueFull"
    # -> "paddle_tpu.serving.scheduler.QueueFull"). The catch context is a
    # list of enclosing try-groups, innermost first; each group is the
    # try's ordered handler list ``[[caught names], swallows]`` where
    # ``["*"]`` = bare except / Exception / BaseException and a handler
    # that re-raises (bare ``raise`` or ``raise <as-name>``) has
    # swallows=0 (transparent): it claims its types but lets them
    # propagate past the REST of its group, exactly like CPython handler
    # matching.
    raises: List[list] = field(default_factory=list)
    # one entry per call occurrence: [dotted callee, catch context, line]
    # (same context shape as ``raises``) — deduped on (callee, context).
    # The exception-contract rule filters the callee's transitive
    # raise-set through the context.
    call_catches: List[list] = field(default_factory=list)
    # caught-and-swallowed record per try/except handler:
    # [[caught names], swallows (0|1), line]
    handlers: List[list] = field(default_factory=list)
    # resource events for configured acquire/release pairs:
    # [kind ("acq"|"rel"|"esc"), pair name, detail, line]. These index which
    # functions the resource-discipline rule must CFG-analyze; the rule
    # re-walks the AST of acquiring functions for path precision.
    resources: List[list] = field(default_factory=list)
    # graft-lint 5.0 may-block events, one per call occurrence:
    # [kind, detail, bounded (0|1), ds (0|1), [held lockrefs], recv, line]
    # where ``kind`` is one of BLOCKING_KINDS, ``bounded`` comes from local
    # constant reasoning over the timeout argument (literal number /
    # env_float-derived / block=False -> 1; absent / literal-None-derived
    # -> 0), ``ds`` marks sites lexically under resilience.deadline_scope,
    # the lockref list is the lexical held-lock stack at the site, and
    # ``recv`` is the receiver's lockref when it resolves to a known lock/
    # condition object (Condition.wait-releases-its-own-lock exemption).
    blocking: List[list] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {"q": self.qualname, "n": self.name, "c": self.cls,
                "l": self.line, "calls": [list(x) for x in self.calls],
                "impure": [list(x) for x in self.impure],
                "sync": [list(x) for x in self.host_syncs],
                "acq": [list(x) for x in self.acquires],
                "nest": [list(x) for x in self.nest_edges],
                "cul": [list(x) for x in self.calls_under_lock],
                "cl": [list(x) for x in self.call_locks],
                "acc": [list(x) for x in self.accesses],
                "rs": [list(x) for x in self.raises],
                "cc": [list(x) for x in self.call_catches],
                "hx": [list(x) for x in self.handlers],
                "res": [list(x) for x in self.resources],
                "blk": [list(x) for x in self.blocking]}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FunctionInfo":
        return cls(qualname=d["q"], name=d["n"], cls=d["c"], line=d["l"],
                   calls=[tuple(x) for x in d["calls"]],
                   impure=[tuple(x) for x in d["impure"]],
                   host_syncs=[tuple(x) for x in d["sync"]],
                   acquires=[(list(x[0]), x[1]) for x in d["acq"]],
                   nest_edges=[(list(x[0]), list(x[1]), x[2])
                               for x in d["nest"]],
                   calls_under_lock=[(list(x[0]), x[1], x[2])
                                     for x in d["cul"]],
                   call_locks=[(x[0], [list(lr) for lr in x[1]], x[2])
                               for x in d["cl"]],
                   accesses=[list(x) for x in d["acc"]],
                   raises=[[x[0], list(x[1]), x[2]] for x in d["rs"]],
                   call_catches=[[x[0], list(x[1]), x[2]]
                                 for x in d["cc"]],
                   handlers=[[list(x[0]), x[1], x[2]] for x in d["hx"]],
                   resources=[list(x) for x in d["res"]],
                   blocking=[[x[0], x[1], x[2], x[3],
                              [list(lr) for lr in x[4]],
                              list(x[5]) if x[5] else None, x[6]]
                             for x in d["blk"]])


@dataclass
class ModuleSummary:
    path: str
    module: str
    bindings: Dict[str, str] = field(default_factory=dict)
    module_imports: List[Dict[str, Any]] = field(default_factory=list)
    functions: List[FunctionInfo] = field(default_factory=list)
    mutable_globals: List[str] = field(default_factory=list)
    locks: Dict[str, str] = field(default_factory=dict)
    class_locks: Dict[str, Dict[str, str]] = field(default_factory=dict)
    trace_roots: List[str] = field(default_factory=list)
    # graft-lint 3.0 thread-root discovery: ["thread", target, cls, line]
    # for ``threading.Thread(target=…)`` spawns (``cls`` = enclosing class,
    # so ``self._loop`` targets resolve), ["httpd", HandlerClass, None,
    # line] for ``ThreadingHTTPServer((…), Handler)`` — the handler's
    # ``do_*`` methods run on per-request server threads
    spawn_roots: List[list] = field(default_factory=list)
    # graft-lint 4.0: class -> resolved base names (one level through
    # bindings), so the exception-contract rule can match a raised subclass
    # against a contract/handler naming its base (DrainTimeout -> EngineStopped)
    class_bases: Dict[str, List[str]] = field(default_factory=dict)
    pragmas: Dict[str, List[str]] = field(default_factory=dict)  # line -> names
    file_pragmas: List[str] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {"path": self.path, "module": self.module,
                "bindings": self.bindings,
                "module_imports": self.module_imports,
                "functions": [f.to_dict() for f in self.functions],
                "mutable_globals": self.mutable_globals,
                "locks": self.locks, "class_locks": self.class_locks,
                "trace_roots": self.trace_roots,
                "spawn_roots": [list(x) for x in self.spawn_roots],
                "class_bases": self.class_bases,
                "pragmas": self.pragmas,
                "file_pragmas": self.file_pragmas}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ModuleSummary":
        return cls(path=d["path"], module=d["module"],
                   bindings=dict(d["bindings"]),
                   module_imports=list(d["module_imports"]),
                   functions=[FunctionInfo.from_dict(x)
                              for x in d["functions"]],
                   mutable_globals=list(d["mutable_globals"]),
                   locks=dict(d["locks"]),
                   class_locks={k: dict(v)
                                for k, v in d["class_locks"].items()},
                   trace_roots=list(d["trace_roots"]),
                   spawn_roots=[list(x) for x in d["spawn_roots"]],
                   class_bases={k: list(v)
                                for k, v in d["class_bases"].items()},
                   pragmas={k: list(v) for k, v in d["pragmas"].items()},
                   file_pragmas=list(d["file_pragmas"]))

    def suppressed(self, rule: str, line: int) -> bool:
        names = set(self.pragmas.get(str(line), ())) | set(self.file_pragmas)
        return rule in names or "all" in names


# ---------------------------------------------------------------------------
# extraction
# ---------------------------------------------------------------------------


def _collect_bindings(tree: ast.Module, module: str, is_pkg: bool
                      ) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    out[a.asname] = a.name
                else:
                    # `import a.b.c` binds `a` — attribute chains resolve
                    # through the qualified walk at project time
                    out[a.name.split(".")[0]] = a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            base = _resolve_relative(module, is_pkg, node.level,
                                     node.module or "") \
                if node.level else (node.module or "")
            if not base:
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{base}.{a.name}"
    return out


def _module_scope_imports(tree: ast.Module, module: str, is_pkg: bool
                          ) -> List[Dict[str, Any]]:
    """Import statements that execute at import time: top-level statements
    plus those nested in top-level If/Try/With (version guards), but NOT
    inside function or class bodies."""
    out: List[Dict[str, Any]] = []
    stack: List[ast.AST] = list(tree.body)
    while stack:
        node = stack.pop(0)
        if isinstance(node, ast.Import):
            for a in node.names:
                out.append({"module": a.name, "names": None,
                            "line": node.lineno})
        elif isinstance(node, ast.ImportFrom):
            base = _resolve_relative(module, is_pkg, node.level,
                                     node.module or "") \
                if node.level else (node.module or "")
            if base:
                out.append({"module": base,
                            "names": [a.name for a in node.names],
                            "line": node.lineno})
        elif isinstance(node, (ast.If, ast.Try, ast.With)):
            for fld in ("body", "orelse", "finalbody", "handlers"):
                for sub in getattr(node, fld, []) or []:
                    stack.append(sub)
        elif isinstance(node, ast.ExceptHandler):
            stack.extend(node.body)
    return out


def _self_assignments(node: ast.AST):
    """Yield ``(attr, value)`` for every ``self.<attr> = value`` /
    annotated-with-value assignment in ``node``'s subtree."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Assign):
            targets, value = sub.targets, sub.value
        elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
            targets, value = [sub.target], sub.value
        else:
            continue
        for t in targets:
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self":
                yield t.attr, value


def _class_lock_table(tree: ast.Module) -> Dict[str, Dict[str, str]]:
    out: Dict[str, Dict[str, str]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        d: Dict[str, str] = {}
        for attr, value in _self_assignments(node):
            kind = lock_ctor_in(value)
            if kind:
                d[attr] = kind
        if d:
            out.setdefault(node.name, {}).update(d)
    return out


def _class_safe_attr_table(tree: ast.Module) -> Dict[str, Set[str]]:
    """Per class: ``self.<attr>`` fields ONLY ever assigned an internally-
    synchronized object (Event/Queue/…) — out of scope for the race rule.
    An attr that is ALSO assigned something else anywhere in the class
    (e.g. rebound to None on teardown) stays in scope."""
    safe: Dict[str, Set[str]] = {}
    unsafe: Dict[str, Set[str]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        s = safe.setdefault(node.name, set())
        u = unsafe.setdefault(node.name, set())
        for attr, value in _self_assignments(node):
            if safe_ctor_in(value) or lock_ctor_in(value):
                s.add(attr)
            else:
                u.add(attr)
    return {cls: attrs - unsafe.get(cls, set())
            for cls, attrs in safe.items()}


_THREAD_CTORS = ("Thread", "Timer")
_HTTPD_CTORS = ("HTTPServer", "TCPServer", "UDPServer")


def _spawn_sites(tree: ast.Module) -> List[list]:
    """Thread-root spawn sites, with the enclosing class tracked so
    ``target=self._loop`` resolves at project time."""
    out: List[list] = []

    def scan_call(node: ast.Call, cls: Optional[str]) -> None:
        dn = dotted_name(node.func)
        if not dn:
            return
        last = dn.split(".")[-1]
        if last in _THREAD_CTORS:
            target = None
            for kw in node.keywords:
                if kw.arg == "target":
                    target = dotted_name(kw.value)
            if last == "Timer" and target is None and len(node.args) >= 2:
                target = dotted_name(node.args[1])
            if target:
                out.append(["thread", target, cls, node.lineno])
        elif last.endswith(_HTTPD_CTORS) and len(node.args) >= 2:
            # full dotted name: the handler class may live in another
            # module (resolved through bindings at project time)
            handler = dotted_name(node.args[1])
            if handler:
                out.append(["httpd", handler, None, node.lineno])

    def rec(node: ast.AST, cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            c2 = child.name if isinstance(child, ast.ClassDef) else cls
            if isinstance(child, ast.Call):
                scan_call(child, cls)
            rec(child, c2)

    rec(tree, None)
    return out


def _trace_root_names(tree: ast.Module, path: str,
                      config: Dict[str, Any]) -> Set[str]:
    """Same pragmatics as the per-file trace-impurity rule, collapsed to a
    set of simple names (names called from inline traced lambdas become
    roots themselves)."""
    names: Set[str] = set()
    lambdas: List[ast.Lambda] = []

    def grab(arg):
        if isinstance(arg, ast.Name):
            names.add(arg.id)
        elif isinstance(arg, ast.Lambda):
            lambdas.append(arg)

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            if (isinstance(fn, ast.Attribute) and fn.attr == "jit") or \
                    (isinstance(fn, ast.Name) and fn.id == "jit"):
                if node.args:
                    grab(node.args[0])
            elif isinstance(fn, ast.Name) and fn.id == "apply" \
                    and len(node.args) >= 2:
                grab(node.args[1])
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if "jax.jit" in ast.unparse(dec):
                    names.add(node.name)
    for lam in lambdas:
        for sub in ast.walk(lam):
            if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name):
                names.add(sub.func.id)
    for cfg_path, extra in config.get("trace_roots", {}).items():
        if path_matches(path, [cfg_path]):
            names.update(extra)
    return names


def _walk_functions(tree: ast.Module):
    """Yield (qualname, simple name, class, node) for every def."""
    out = []

    def rec(node, cls, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                rec(child, child.name, prefix + child.name + ".")
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append((prefix + child.name, child.name, cls, child))
                rec(child, cls, prefix + child.name + ".")

    rec(tree, None, "")
    return out


def _own_nodes(fn: ast.AST):
    """Nodes of ``fn``'s body excluding nested def/class bodies (those are
    summarized as their own functions). Lambdas stay — they execute inline."""
    def rec(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            yield child
            yield from rec(child)
    yield from rec(fn)


def _local_names(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    args = getattr(fn, "args", None)
    if args is not None:
        for a in (args.posonlyargs + args.args + args.kwonlyargs
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            out.add(a.arg)
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
            out.add(sub.id)
    return out


# ---------------------------------------------------------------------------
# graft-lint 5.0: may-block events
# ---------------------------------------------------------------------------

#: every kind a blocking event may carry (pinned by tests; rules subset it)
BLOCKING_KINDS = ("sleep", "lock-acquire", "condition-wait", "queue",
                  "future-wait", "thread-join", "rpc", "subprocess",
                  "device-sync", "jit-compile", "file-io")

_SOCKET_ATTRS = {"recv", "recvfrom", "recv_into", "accept", "sendall",
                 "connect", "makefile"}
_SUBPROCESS_FNS = {"run", "call", "check_call", "check_output"}
_PATH_MODULES = {"os.path", "posixpath", "ntpath", "path", "osp"}
_FILE_IO_CALLS = {"open", "os.replace", "os.fsync", "os.rename"}
#: receiver names that mark a bare ``.get()`` as a queue, not a dict
_QUEUE_NAME_RE = re.compile(r"(^|_)(q\d*|queue|queues|events|jobs|inbox|"
                            r"outbox|work|results?)$")


def _blocking_consts(fn: ast.AST) -> Dict[str, str]:
    """One-level local constant kinds for timeout reasoning: name ->
    "unbounded" when the binding is known literal-None-derived (a ``None``
    default or an assignment whose value can be ``None``), else "bounded".
    Conflicting rebinds resolve to "unbounded" — flagging a maybe-untimed
    wait costs a baseline entry, missing one costs a wedge."""
    def kind_of(expr) -> str:
        if isinstance(expr, ast.Constant):
            return "unbounded" if expr.value is None else "bounded"
        if isinstance(expr, ast.IfExp):
            if "unbounded" in (kind_of(expr.body), kind_of(expr.orelse)):
                return "unbounded"
            return "bounded"
        # calls (env_float(...), max(...)), names, arithmetic: the author
        # computed a bound — trust it
        return "bounded"

    out: Dict[str, str] = {}
    args = getattr(fn, "args", None)
    if args is not None:
        pos = args.posonlyargs + args.args
        for a, d in zip(pos[len(pos) - len(args.defaults):], args.defaults):
            out[a.arg] = kind_of(d)
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if d is not None:
                out[a.arg] = kind_of(d)
    for sub in _own_nodes(fn):
        if isinstance(sub, ast.Assign) and len(sub.targets) == 1 and \
                isinstance(sub.targets[0], ast.Name):
            name, k = sub.targets[0].id, kind_of(sub.value)
            out[name] = "unbounded" if out.get(name, k) != k else k
    return out


def _timeout_kind(expr, consts: Dict[str, str]) -> str:
    """"bounded" | "unbounded" for a timeout argument expression. Absent
    (None node) and literal ``None`` are unbounded; literal numbers,
    ``env_float``/``env_int``-derived values, and any computed expression
    are bounded; a plain name resolves through ``_blocking_consts``."""
    if expr is None:
        return "unbounded"
    if isinstance(expr, ast.Constant):
        return "unbounded" if expr.value is None else "bounded"
    if isinstance(expr, ast.Name):
        return consts.get(expr.id, "bounded")
    if isinstance(expr, ast.IfExp):
        if "unbounded" in (_timeout_kind(expr.body, consts),
                           _timeout_kind(expr.orelse, consts)):
            return "unbounded"
        return "bounded"
    return "bounded"


def _classify_blocking(node: ast.Call, dn: str, consts: Dict[str, str],
                       sock_bounded: bool
                       ) -> Optional[Tuple[str, bool]]:
    """``(kind, bounded)`` when the call may block, else ``None``.

    ``dn`` is the dotted callee name ("" when the callee is not a plain
    dotted chain). Boundedness is one-level constant reasoning over the
    timeout argument; for every kind that accepts a timeout, absence
    means unbounded. ``block=False``/``blocking=False`` count as bounded.
    ``sock_bounded`` marks functions that call ``.settimeout(<non-None>)``
    somewhere — their raw socket ops inherit the deadline.
    """
    f = node.func
    last = dn.split(".")[-1] if dn else (
        f.attr if isinstance(f, ast.Attribute) else "")

    def kw(name):
        for k in node.keywords:
            if k.arg == name:
                return k.value
        return None

    def bounded(expr) -> bool:
        return _timeout_kind(expr, consts) == "bounded"

    def false_const(expr) -> bool:
        return isinstance(expr, ast.Constant) and expr.value is False

    tmo = kw("timeout")

    if dn in ("time.sleep", "sleep") or \
            last in ("jitter_sleep", "_jitter_sleep"):
        return "sleep", True
    if isinstance(f, ast.Attribute):
        recv = f.value
        if last == "acquire":
            if false_const(kw("blocking")) or false_const(kw("block")) or \
                    (node.args and false_const(node.args[0])):
                return "lock-acquire", True
            return "lock-acquire", tmo is not None and bounded(tmo)
        if last in ("wait", "wait_for"):
            arg = tmo
            if arg is None:
                if last == "wait" and node.args:
                    arg = node.args[0]
                elif last == "wait_for" and len(node.args) > 1:
                    arg = node.args[1]
            return "condition-wait", arg is not None and bounded(arg)
        if last == "join":
            base = dotted_name(recv) or ""
            if base in _PATH_MODULES or isinstance(recv, ast.Constant) or \
                    len(node.args) >= 2:
                return None                       # path/str join
            if node.args:
                a = node.args[0]
                if isinstance(a, ast.Constant):
                    if a.value is None:
                        return "thread-join", False
                    if isinstance(a.value, (int, float)) and \
                            not isinstance(a.value, bool):
                        return "thread-join", True
                    return None                   # "sep".join-style
                if not isinstance(a, ast.Name):
                    return None                   # iterable arg: str.join
                return "thread-join", bounded(a)
            return "thread-join", tmo is not None and bounded(tmo)
        if last in ("get", "put"):
            blk = kw("block")
            nm = recv.attr if isinstance(recv, ast.Attribute) else (
                recv.id if isinstance(recv, ast.Name) else "")
            queue_like = bool(_QUEUE_NAME_RE.search(nm.lower()))
            if last == "put":
                if tmo is None and blk is None:
                    return None    # unbounded-capacity put never blocks
            else:
                if tmo is None and blk is None and not queue_like:
                    return None    # dict.get(...)
                if node.args and not isinstance(node.args[0], ast.Constant):
                    return None    # positional key: dict.get(key, default)
                if node.args and not isinstance(node.args[0].value, bool):
                    return None
            if false_const(blk) or \
                    (node.args and false_const(node.args[0])):
                return "queue", True
            return "queue", tmo is not None and bounded(tmo)
        if last == "result":
            arg = tmo if tmo is not None else (
                node.args[0] if node.args else None)
            return "future-wait", arg is not None and bounded(arg)
        if last in _SOCKET_ATTRS:
            if tmo is not None:
                return "rpc", bounded(tmo)
            return "rpc", sock_bounded
        if last == "communicate":
            return "subprocess", tmo is not None and bounded(tmo)
        if last == "block_until_ready":
            return "device-sync", True
        if last in ("item", "numpy") and not node.args:
            return "device-sync", True
    if dn.startswith("subprocess.") and last in _SUBPROCESS_FNS:
        return "subprocess", tmo is not None and bounded(tmo)
    if dn in ("socket.create_connection", "urllib.request.urlopen",
              "urlopen"):
        return "rpc", tmo is not None and bounded(tmo)
    if dn in ("jax.jit", "jax.pmap"):
        return "jit-compile", True
    if dn in _FILE_IO_CALLS:
        return "file-io", True
    return None


def _scan_function(fn: ast.AST, cls: Optional[str],
                   mutables: Set[str], bindings: Dict[str, str],
                   module_locks: Dict[str, str],
                   class_locks: Dict[str, Dict[str, str]],
                   safe_attrs: Optional[Dict[str, Set[str]]] = None
                   ) -> Dict[str, list]:
    calls: List[Tuple[str, int]] = []
    seen_calls: Set[str] = set()
    impure: List[Tuple[str, str, int]] = []
    seen_impure: Set[Tuple[str, str]] = set()
    host_syncs: List[Tuple[str, int]] = []
    sync_lines: Set[int] = set()
    locals_ = _local_names(fn)

    def add_impure(kind, detail, line):
        if (kind, detail) not in seen_impure:
            seen_impure.add((kind, detail))
            impure.append((kind, detail, line))

    def add_sync(node, what):
        if node.lineno not in sync_lines:
            sync_lines.add(node.lineno)
            host_syncs.append((what, node.lineno))

    for sub in _own_nodes(fn):
        if isinstance(sub, ast.Call):
            dn = dotted_name(sub.func)
            if dn and dn not in seen_calls:
                seen_calls.add(dn)
                calls.append((dn, sub.lineno))
            base = dn.split(".")[0] if dn else ""
            if "." in dn and base in IMPURE_MODULES:
                add_impure("call", dn, sub.lineno)
            elif dn.startswith(IMPURE_PREFIXES) or dn == "os.getenv":
                add_impure("call", dn, sub.lineno)
            # host-sync shapes (anywhere in the body, not only loops —
            # the fast-path rule decides whether the location matters)
            f = sub.func
            if isinstance(f, ast.Attribute) and not sub.args and \
                    f.attr in ("item", "numpy"):
                add_sync(sub, f"`{snippet(sub)}`")
            elif isinstance(f, ast.Name) and f.id in ("bool", "float",
                                                      "int") and \
                    len(sub.args) == 1:
                arg = sub.args[0]
                if mentions_device_value(arg) or (
                        f.id == "bool" and isinstance(arg, ast.Call)
                        and isinstance(arg.func, ast.Attribute)
                        and arg.func.attr in ("all", "any")):
                    add_sync(sub, f"`{snippet(sub)}`")
            elif dn in _NP_CONVERTERS and sub.args and \
                    mentions_device_value(sub.args[0]):
                add_sync(sub, f"`{snippet(sub)}`")
        elif isinstance(sub, ast.Attribute):
            dn = dotted_name(sub)
            if dn == "os.environ":
                add_impure("environ", "os.environ", sub.lineno)
            elif isinstance(sub.ctx, ast.Load) and \
                    isinstance(sub.value, ast.Name) and \
                    sub.value.id in bindings and \
                    sub.value.id not in locals_:
                # candidate cross-module global read; the project resolves
                # whether the target is a mutable module global
                add_impure("attr", f"{sub.value.id}.{sub.attr}", sub.lineno)
        elif isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load) \
                and sub.id in mutables and sub.id not in locals_:
            add_impure("global", sub.id, sub.lineno)

    # lock structure: recursive walk tracking the held-lock stack
    acquires: List[Tuple[list, int]] = []
    nest_edges: List[Tuple[list, list, int]] = []
    calls_under_lock: List[Tuple[list, str, int]] = []
    call_locks: List[Tuple[str, list, int]] = []
    accesses: List[list] = []
    blocking: List[list] = []
    consts = _blocking_consts(fn)
    sock_bounded = any(
        isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute)
        and sub.func.attr == "settimeout" and sub.args
        and not (isinstance(sub.args[0], ast.Constant)
                 and sub.args[0].value is None)
        for sub in _own_nodes(fn))
    # shared-state access tracking (graft-lint 3.0): which self.<attr>
    # fields are in scope (not locks, not Event/Queue-style primitives),
    # and one-level aliases of module mutable globals
    skip_attrs: Set[str] = set()
    if cls is not None:
        skip_attrs |= set(class_locks.get(cls, {}))
        skip_attrs |= (safe_attrs or {}).get(cls, set())
    galias = {g: g for g in mutables}
    gdecls: Set[str] = set()   # `global X` names: rebinds hit the module
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Global):
            gdecls.update(sub.names)
        elif isinstance(sub, ast.Assign) and len(sub.targets) == 1 and \
                isinstance(sub.targets[0], ast.Name) and \
                isinstance(sub.value, (ast.Name, ast.Subscript,
                                       ast.Attribute)):
            src = root_name(sub.value)
            if src in galias and sub.targets[0].id not in mutables:
                galias[sub.targets[0].id] = galias[src]

    def self_attr(expr) -> Optional[str]:
        """The first attribute of a ``self.<attr>…`` chain, else None."""
        node = expr
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "self":
                return node.attr
            node = node.value
        return None

    def add_access(expr, rw: str, held: list, line: int) -> None:
        attr = self_attr(expr) if cls is not None else None
        if attr is not None:
            if attr not in skip_attrs:
                accesses.append(["self", cls, attr, rw,
                                 [list(h) for h in held], line])
            return
        root = root_name(expr)
        if root is None or root not in galias:
            return
        if root in mutables and root in locals_ and root not in gdecls:
            return  # the global name is shadowed by a local here
        accesses.append(["glob", galias[root], rw,
                         [list(h) for h in held], line])

    def lockref(expr):
        if isinstance(expr, ast.Name):
            if expr.id in module_locks:
                return ["mod", expr.id]
        elif isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            base, attr = expr.value.id, expr.attr
            if base in ("self", "cls") and cls and \
                    attr in class_locks.get(cls, {}):
                return ["self", cls, attr]
            if base in bindings:
                return ["ext", base, attr]
        return None

    def rec(node, held, ds):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new, nds = held, ds
            for item in node.items:
                ce = item.context_expr
                lr = lockref(ce)
                if lr is not None:
                    line = ce.lineno
                    acquires.append((lr, line))
                    # a ``with <lock>:`` IS a blocking acquire (no timeout
                    # form exists) — recorded for the hot-path rule; the
                    # under-lock and unbounded-wait rules skip this kind
                    blocking.append(["lock-acquire",
                                     dotted_name(ce) or "lock", 0,
                                     1 if nds else 0,
                                     [list(h) for h in new], list(lr),
                                     line])
                    for h in new:
                        nest_edges.append((h, lr, line))
                    new = new + [lr]
                elif isinstance(ce, ast.Call):
                    cdn = dotted_name(ce.func) or ""
                    if cdn.split(".")[-1] == "deadline_scope":
                        nds = True
            for child in node.body:
                rec(child, new, nds)
            return
        if isinstance(node, ast.Call):
            dn = dotted_name(node.func)
            if dn:
                call_locks.append((dn, [list(h) for h in held],
                                   node.lineno))
                for h in held:
                    calls_under_lock.append((h, dn, node.lineno))
            blk = _classify_blocking(node, dn or "", consts, sock_bounded)
            if blk is not None:
                kind, bnd = blk
                recv = lockref(node.func.value) \
                    if isinstance(node.func, ast.Attribute) else None
                detail = dn or (node.func.attr
                                if isinstance(node.func, ast.Attribute)
                                else "")
                blocking.append([kind, detail, 1 if bnd else 0,
                                 1 if ds else 0, [list(h) for h in held],
                                 list(recv) if recv else None,
                                 node.lineno])
            # in-place mutation through a method: self.attr.append(...)
            # or GLOBAL.setdefault(...) — a WRITE to the container
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in MUTATORS:
                add_access(node.func.value, "w", held, node.lineno)
        elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            if isinstance(node, ast.AnnAssign):
                # annotation WITHOUT a value binds nothing — not a write
                targets = [node.target] if node.value is not None else []
            else:
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
            for t in targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    add_access(t, "w", held, node.lineno)
                elif isinstance(t, ast.Name) and t.id in gdecls and \
                        t.id in mutables:
                    # `global X; X = ...` — the classic global-swap write
                    add_access(t, "w", held, node.lineno)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    for el in t.elts:
                        if isinstance(el, (ast.Attribute, ast.Subscript)):
                            add_access(el, "w", held, node.lineno)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    add_access(t, "w", held, node.lineno)
                elif isinstance(t, ast.Name) and t.id in gdecls and \
                        t.id in mutables:
                    add_access(t, "w", held, node.lineno)
        elif isinstance(node, ast.Attribute) and \
                isinstance(node.ctx, ast.Load) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self":
            add_access(node, "r", held, node.lineno)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                and node.id in galias:
            add_access(node, "r", held, node.lineno)
        for child in ast.iter_child_nodes(node):
            rec(child, held, ds)

    for child in ast.iter_child_nodes(fn):
        rec(child, [], False)

    return {"calls": calls, "impure": impure, "host_syncs": host_syncs,
            "acquires": acquires, "nest_edges": nest_edges,
            "calls_under_lock": calls_under_lock,
            "call_locks": call_locks, "accesses": accesses,
            "blocking": blocking}


# ---------------------------------------------------------------------------
# graft-lint 4.0: exception flow + resource events
# ---------------------------------------------------------------------------

_WIDE_CATCHES = ("Exception", "BaseException")


def _class_bases_table(tree: ast.Module, bindings: Dict[str, str],
                       module: str) -> Dict[str, List[str]]:
    """class name -> resolved base names (``object`` and keywords dropped)."""
    local_classes = {n.name for n in ast.walk(tree)
                     if isinstance(n, ast.ClassDef)}
    out: Dict[str, List[str]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        bases = []
        for b in node.bases:
            dn = dotted_name(b)
            if not dn or dn == "object":
                continue
            bases.append(_resolve_exc_name(dn, bindings, module,
                                           local_classes))
        if bases:
            out[node.name] = bases
    return out


def _resolve_exc_name(dotted: str, bindings: Dict[str, str], module: str,
                      local_classes: Set[str]) -> str:
    """One-level alias/import resolution of an exception (or base) name."""
    first, _, rest = dotted.partition(".")
    if first in bindings:
        target = bindings[first]
        return f"{target}.{rest}" if rest else target
    if first in local_classes:
        return f"{module}.{dotted}"
    return dotted


def _handler_names(handler: ast.ExceptHandler, bindings: Dict[str, str],
                   module: str, local_classes: Set[str]) -> List[str]:
    """Caught type names of one handler; ``["*"]`` when it catches
    everything (bare ``except``, ``Exception``, ``BaseException``)."""
    t = handler.type
    if t is None:
        return ["*"]
    exprs = t.elts if isinstance(t, ast.Tuple) else [t]
    names: List[str] = []
    for e in exprs:
        dn = dotted_name(e)
        if not dn:
            continue
        if dn.split(".")[-1] in _WIDE_CATCHES:
            return ["*"]
        names.append(_resolve_exc_name(dn, bindings, module, local_classes))
    return sorted(set(names))


def _handler_reraises(handler: ast.ExceptHandler) -> bool:
    """True when the handler re-raises what it caught (bare ``raise`` or
    ``raise <as-name>`` anywhere in its body, nested defs excluded) —
    such a handler is transparent: it swallows nothing."""
    for sub in _own_nodes(handler):
        if isinstance(sub, ast.Raise):
            if sub.exc is None:
                return True
            if handler.name and isinstance(sub.exc, ast.Name) and \
                    sub.exc.id == handler.name:
                return True
    return False


def _scan_exceptions(fn: ast.AST, bindings: Dict[str, str], module: str,
                     local_classes: Set[str]) -> Dict[str, list]:
    """Per-function raise-set, per-call catch sets, and handler records."""
    raises: List[list] = []
    call_catches: List[list] = []
    seen_calls: Set[Tuple[str, tuple]] = set()
    handlers_out: List[list] = []

    # one-level local exception variables: `exc = QueueFull(...)` followed
    # by `raise exc` resolves to QueueFull
    var_types: Dict[str, str] = {}
    for sub in _own_nodes(fn):
        if isinstance(sub, ast.Assign) and len(sub.targets) == 1 and \
                isinstance(sub.targets[0], ast.Name) and \
                isinstance(sub.value, ast.Call):
            dn = dotted_name(sub.value.func)
            if dn and dn.split(".")[-1][:1].isupper():
                var_types[sub.targets[0].id] = _resolve_exc_name(
                    dn, bindings, module, local_classes)

    def scan(node: ast.AST, catches: List[list],
             as_names: frozenset) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(node, ast.Try):
            group: List[list] = []
            for h in node.handlers:
                names = _handler_names(h, bindings, module, local_classes)
                sw = not _handler_reraises(h)
                handlers_out.append([names, 1 if sw else 0, h.lineno])
                group.append([names, 1 if sw else 0])
            body_catches = ([group] + catches) if group else catches
            for s in node.body:
                scan(s, body_catches, as_names)
            for s in node.orelse:
                scan(s, catches, as_names)
            for h in node.handlers:
                inner = as_names | {h.name} if h.name else as_names
                for s in h.body:
                    scan(s, catches, inner)
            for s in node.finalbody:
                scan(s, catches, as_names)
            return
        if isinstance(node, ast.Raise):
            exc = node.exc
            name: Optional[str] = None
            if exc is None:
                name = None            # bare re-raise: transparent handler
            elif isinstance(exc, ast.Name):
                if exc.id in as_names:
                    name = None        # `raise exc` re-raise of the caught
                else:
                    name = var_types.get(exc.id)
            else:
                target = exc.func if isinstance(exc, ast.Call) else exc
                dn = dotted_name(target)
                if dn:
                    name = _resolve_exc_name(dn, bindings, module,
                                             local_classes)
            if name is not None:
                raises.append([name, list(catches), node.lineno])
        elif isinstance(node, ast.Call):
            dn = dotted_name(node.func)
            if dn:
                key = (dn, repr(catches))
                if key not in seen_calls:
                    seen_calls.add(key)
                    call_catches.append([dn, list(catches), node.lineno])
        for child in ast.iter_child_nodes(node):
            scan(child, catches, as_names)

    for child in ast.iter_child_nodes(fn):
        scan(child, [], frozenset())
    return {"raises": raises, "call_catches": call_catches,
            "handlers": handlers_out}


def _scan_resources(fn: ast.AST, config: Dict[str, Any]) -> List[list]:
    """Acquire/release/escape events for the configured resource pairs.

    Matching is by the call's last dotted component ("free" matches
    ``self.kv.free``); the class part of a configured
    ``"PagedKVCache.alloc"`` spec is documentation. Escape events are the
    naive ownership transfers (return / attribute store / argument pass of
    a name bound straight from an acquire call); the resource-discipline
    rule re-derives the precise per-path story from the CFG.
    """
    pairs = config.get("resource_pairs", ())
    if not pairs:
        return []
    acq: Dict[str, str] = {}
    rel: Dict[str, str] = {}
    for p in pairs:
        for spec in p.get("acquire", ()):
            acq[spec.split(".")[-1]] = p["name"]
        for spec in p.get("release", ()):
            rel[spec.split(".")[-1]] = p["name"]

    events: List[list] = []
    owned: Dict[str, str] = {}   # name -> pair, bound straight from acquire

    def acquire_call_in(expr) -> Optional[str]:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                dn = dotted_name(sub.func)
                if dn and dn.split(".")[-1] in acq:
                    return acq[dn.split(".")[-1]]
        return None

    for sub in _own_nodes(fn):
        if isinstance(sub, ast.Call):
            dn = dotted_name(sub.func)
            last = dn.split(".")[-1] if dn else ""
            if last in acq:
                events.append(["acq", acq[last], dn, sub.lineno])
            elif last in rel:
                events.append(["rel", rel[last], dn, sub.lineno])
                continue
            for a in list(sub.args) + [kw.value for kw in sub.keywords]:
                for n in ast.walk(a):
                    if isinstance(n, ast.Name) and n.id in owned:
                        events.append(["esc", owned[n.id],
                                       f"arg {n.id}", sub.lineno])
        elif isinstance(sub, ast.Assign):
            pair = acquire_call_in(sub.value)
            for t in sub.targets:
                if pair and isinstance(t, ast.Name):
                    owned[t.id] = pair
                elif isinstance(t, (ast.Attribute, ast.Subscript)):
                    for n in ast.walk(sub.value):
                        if isinstance(n, ast.Name) and n.id in owned:
                            events.append(["esc", owned[n.id],
                                           f"store {n.id}", sub.lineno])
        elif isinstance(sub, ast.Return) and sub.value is not None:
            for n in ast.walk(sub.value):
                if isinstance(n, ast.Name) and n.id in owned:
                    events.append(["esc", owned[n.id], f"return {n.id}",
                                   sub.lineno])
    return events


def build_summary(path: str, tree: ast.Module, lines: List[str],
                  config: Dict[str, Any]) -> ModuleSummary:
    """Distill one parsed module into its JSON-serializable summary."""
    # imported here (not at module top) to avoid an import cycle:
    # engine -> wholeprogram (at run time) -> engine (pragma parsing)
    from ..engine import _pragma_tables  # graft-lint: disable=hot-path-import

    is_pkg = path.endswith("__init__.py")
    module = module_name_for(path)
    bindings = _collect_bindings(tree, module, is_pkg)
    mutables = module_mutable_globals(tree)
    module_locks = module_lock_defs(tree)
    class_locks = _class_lock_table(tree)
    safe_attrs = _class_safe_attr_table(tree)
    per_line, file_level = _pragma_tables(lines)

    local_classes = {n.name for n in ast.walk(tree)
                     if isinstance(n, ast.ClassDef)}

    functions: List[FunctionInfo] = []
    for qualname, name, cls, node in _walk_functions(tree):
        data = _scan_function(node, cls, mutables, bindings, module_locks,
                              class_locks, safe_attrs)
        exc = _scan_exceptions(node, bindings, module, local_classes)
        functions.append(FunctionInfo(
            qualname=qualname, name=name, cls=cls, line=node.lineno,
            calls=data["calls"], impure=data["impure"],
            host_syncs=data["host_syncs"], acquires=data["acquires"],
            nest_edges=data["nest_edges"],
            calls_under_lock=data["calls_under_lock"],
            call_locks=data["call_locks"], accesses=data["accesses"],
            raises=exc["raises"], call_catches=exc["call_catches"],
            handlers=exc["handlers"],
            resources=_scan_resources(node, config),
            blocking=data["blocking"]))

    return ModuleSummary(
        path=path, module=module, bindings=bindings,
        module_imports=_module_scope_imports(tree, module, is_pkg),
        functions=functions,
        mutable_globals=sorted(mutables),
        locks=module_locks, class_locks=class_locks,
        trace_roots=sorted(_trace_root_names(tree, path, config)),
        spawn_roots=_spawn_sites(tree),
        class_bases=_class_bases_table(tree, bindings, module),
        pragmas={str(k): sorted(v) for k, v in per_line.items()},
        file_pragmas=sorted(file_level))
