"""Project: module summaries assembled into queryable whole-program graphs.

Name resolution is the heart of this module and follows the same
pragmatics as the per-file rules, extended across files:

* a dotted call ``a.b.c`` first matches locals of the calling module
  (functions, classes), then the module's import bindings, then walks the
  qualified name module-prefix-first;
* ``from pkg import f`` where ``pkg/__init__`` itself binds ``f`` from a
  submodule (a re-export) is followed through the ``__init__`` binding
  table, depth-limited so cyclic re-exports terminate;
* ``self.m()`` / ``cls.m()`` resolve to same-class methods first, then any
  same-module function of that simple name (the intra-module
  over-approximation the per-file rules already accept);
* anything else (parameters, dynamic attributes, star imports) resolves to
  nothing and drops out of the graph.

Calls to classes resolve to ``__init__`` so constructor side effects stay
on the graph.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from ..astutil import path_matches
from .summary import FunctionInfo, ModuleSummary

#: function node identity in the project call graph
Node = Tuple[str, str]  # (module dotted name, qualname)

_RESOLVE_DEPTH = 6  # max re-export hops before giving up


class Project:
    def __init__(self, summaries: Dict[str, ModuleSummary],
                 config: Dict[str, Any], root: Optional[str] = None):
        self.config = config
        #: filesystem root the summary paths are relative to — rules that
        #: need per-path precision (resource-discipline re-walks the AST of
        #: acquiring functions) resolve files through it
        self.root = root
        self.by_path: Dict[str, ModuleSummary] = dict(summaries)
        self.modules: Dict[str, ModuleSummary] = {}
        for s in summaries.values():
            self.modules[s.module] = s

        # indexes
        self.fn_by_simple: Dict[Tuple[str, str], List[FunctionInfo]] = {}
        self.fn_by_qual: Dict[Node, FunctionInfo] = {}
        self.methods: Dict[Tuple[str, str, str], FunctionInfo] = {}
        self.classes: Dict[str, Set[str]] = {}
        self.lock_kinds: Dict[str, str] = {}
        for mod, s in self.modules.items():
            cls_names: Set[str] = set(s.class_locks)
            for fi in s.functions:
                self.fn_by_simple.setdefault((mod, fi.name), []).append(fi)
                self.fn_by_qual[(mod, fi.qualname)] = fi
                if fi.cls:
                    cls_names.add(fi.cls)
                    self.methods.setdefault((mod, fi.cls, fi.name), fi)
            self.classes[mod] = cls_names
            for name, kind in s.locks.items():
                self.lock_kinds[f"{mod}.{name}"] = kind
            for cls, attrs in s.class_locks.items():
                for attr, kind in attrs.items():
                    self.lock_kinds[f"{mod}.{cls}.{attr}"] = kind

        # class -> resolved base names, merged over modules (simple-name
        # keyed; exception hierarchies are simple-name unique in practice)
        self.class_bases: Dict[str, List[str]] = {}
        for s in self.modules.values():
            for cls_name, bases in s.class_bases.items():
                self.class_bases.setdefault(cls_name, [])
                for b in bases:
                    if b not in self.class_bases[cls_name]:
                        self.class_bases[cls_name].append(b)

    def exc_ancestry(self, type_name: str) -> Set[str]:
        """Transitive base SIMPLE names of an exception type (project
        classes only; builtin bases are the rule's concern), including the
        type itself."""
        out: Set[str] = set()
        stack = [type_name.split(".")[-1]]
        while stack:
            n = stack.pop()
            if n in out:
                continue
            out.add(n)
            for b in self.class_bases.get(n, ()):
                stack.append(b.split(".")[-1])
        return out

    # -- resolution ---------------------------------------------------------

    def _in_module(self, mod: str, head: str,
                   tail: List[str]) -> List[Tuple[str, FunctionInfo]]:
        if head in self.classes.get(mod, ()):
            meth = tail[0] if tail else "__init__"
            fi = self.methods.get((mod, head, meth))
            return [(mod, fi)] if fi is not None else []
        fns = self.fn_by_simple.get((mod, head))
        if fns:
            return [(mod, fi) for fi in fns]
        return []

    def resolve_qualified(self, dotted: str, depth: int = 0
                          ) -> List[Tuple[str, FunctionInfo]]:
        """Resolve a fully-qualified dotted name to function records."""
        if depth > _RESOLVE_DEPTH:
            return []
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:i])
            if mod in self.modules:
                rest = parts[i:]
                head, tail = rest[0], rest[1:]
                hit = self._in_module(mod, head, tail)
                if hit:
                    return hit
                target = self.modules[mod].bindings.get(head)
                if target:  # re-export: follow the __init__ binding
                    return self.resolve_qualified(
                        ".".join([target] + tail), depth + 1)
                return []
        return []

    def resolve_call(self, mod: str, cls: Optional[str], dotted: str
                     ) -> List[Tuple[str, FunctionInfo]]:
        """Resolve one call site's dotted name from inside ``mod``."""
        s = self.modules.get(mod)
        if s is None:
            return []
        parts = dotted.split(".")
        head, tail = parts[0], parts[1:]
        if head in ("self", "cls") and tail:
            name = tail[0]
            if cls is not None:
                fi = self.methods.get((mod, cls, name))
                if fi is not None:
                    return [(mod, fi)]
            return [(mod, fi)
                    for fi in self.fn_by_simple.get((mod, name), [])]
        hit = self._in_module(mod, head, tail)
        if hit:
            return hit
        target = s.bindings.get(head)
        if target and target != head:
            return self.resolve_qualified(".".join([target] + tail))
        if target:  # plain `import pkg` style binding: head == target
            return self.resolve_qualified(dotted)
        return []

    # -- call-graph queries -------------------------------------------------

    def callees(self, mod: str, fi: FunctionInfo) -> List[Node]:
        out: List[Node] = []
        seen: Set[Node] = set()
        for dn, _line in fi.calls:
            for m2, f2 in self.resolve_call(mod, fi.cls, dn):
                node = (m2, f2.qualname)
                if node not in seen:
                    seen.add(node)
                    out.append(node)
        return sorted(out)

    def reachable_from(self, roots: Iterable[Tuple[str, FunctionInfo, Any]]
                       ) -> Dict[Node, Any]:
        """BFS over the call graph; each reached node keeps the label of
        the first root that reached it (deterministic: roots in given
        order, sorted callees)."""
        seen: Dict[Node, Any] = {}
        queue: List[Tuple[str, FunctionInfo, Any]] = []
        for mod, fi, label in roots:
            node = (mod, fi.qualname)
            if node not in seen:
                seen[node] = label
                queue.append((mod, fi, label))
        i = 0
        while i < len(queue):
            mod, fi, label = queue[i]
            i += 1
            for m2, qn in self.callees(mod, fi):
                node = (m2, qn)
                if node not in seen:
                    seen[node] = label
                    queue.append((m2, self.fn_by_qual[node], label))
        return seen

    # -- locks --------------------------------------------------------------

    def lock_id(self, mod: str, lockref: list) -> Optional[str]:
        """Canonical project-wide lock id for a summary lockref, or None
        when the reference does not resolve to a known lock object."""
        tag = lockref[0]
        s = self.modules.get(mod)
        if s is None:
            return None
        if tag == "mod":
            name = lockref[1]
            return f"{mod}.{name}" if name in s.locks else None
        if tag == "self":
            _, cls, attr = lockref
            if attr in s.class_locks.get(cls, {}):
                return f"{mod}.{cls}.{attr}"
            return None
        if tag == "ext":
            _, alias, attr = lockref
            target = s.bindings.get(alias)
            if target and target in self.modules and \
                    attr in self.modules[target].locks:
                return f"{target}.{attr}"
            return None
        return None

    # -- thread roots + lock domination (graft-lint 3.0) --------------------

    def resolve_class(self, mod: str, dotted: str, depth: int = 0
                      ) -> Optional[Tuple[str, str]]:
        """Resolve a possibly-imported class reference from inside ``mod``
        to ``(defining module, class name)`` — the same binding walk as
        :meth:`resolve_call`, stopping at a CLASS instead of a function
        (handler classes handed to server ctors may have no ``__init__``
        of their own, so the call-resolution path cannot find them)."""
        if depth > _RESOLVE_DEPTH:
            return None
        s = self.modules.get(mod)
        if s is None:
            return None
        parts = dotted.split(".")
        head, tail = parts[0], parts[1:]
        if not tail and head in self.classes.get(mod, ()):
            return (mod, head)
        target = s.bindings.get(head)
        if target and target != head:
            return self._resolve_class_qualified(
                ".".join([target] + tail), depth + 1)
        if target:  # plain `import pkg` binding: head == target
            return self._resolve_class_qualified(dotted, depth + 1)
        return None

    def _resolve_class_qualified(self, dotted: str, depth: int
                                 ) -> Optional[Tuple[str, str]]:
        if depth > _RESOLVE_DEPTH:
            return None
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:i])
            if mod in self.modules:
                rest = parts[i:]
                if len(rest) == 1 and rest[0] in self.classes.get(mod, ()):
                    return (mod, rest[0])
                target = self.modules[mod].bindings.get(rest[0])
                if target:  # re-export: follow the __init__ binding
                    return self._resolve_class_qualified(
                        ".".join([target] + rest[1:]), depth + 1)
                return None
        return None

    def thread_roots(self) -> List[Tuple[str, "FunctionInfo", str]]:
        """Every discovered or configured thread entry point, as
        ``(module, function, label)`` — one per distinct function.

        Discovered roots: ``threading.Thread(target=…)`` /
        ``threading.Timer`` targets resolved through the normal call
        resolution (``self.<m>`` included), and the ``do_*`` methods of
        any class handed to a ``ThreadingHTTPServer``-style ctor (each
        request runs them on a server thread). Configured roots (the
        ``thread_roots`` config table, path -> ["Class.method", "fn"])
        name the callback seams discovery cannot see: public entry points
        that run on CALLER threads, stream callbacks, Future resolution.
        First label wins for a function reachable both ways."""
        out: List[Tuple[str, FunctionInfo, str]] = []
        seen: Set[Node] = set()

        def add(m2: str, fi: FunctionInfo, label: str) -> None:
            node = (m2, fi.qualname)
            if node not in seen:
                seen.add(node)
                out.append((m2, fi, label))

        for mod in sorted(self.modules):
            s = self.modules[mod]
            for kind, target, cls, _line in s.spawn_roots:
                if kind == "thread":
                    for m2, fi in self.resolve_call(mod, cls, target):
                        add(m2, fi, f"thread '{m2}.{fi.qualname}'")
                elif kind == "httpd":
                    hit = self.resolve_class(mod, target)
                    if hit is None:
                        continue
                    hm, hc = hit
                    for (m2, c2, name), fi in sorted(self.methods.items()):
                        if m2 == hm and c2 == hc and \
                                name.startswith("do_"):
                            add(m2, fi,
                                f"http handler '{m2}.{fi.qualname}'")
        cfg = self.config.get("thread_roots", {})
        for cfg_path in sorted(cfg):
            for mod in sorted(self.modules):
                s = self.modules[mod]
                if not path_matches(s.path, [cfg_path]):
                    continue
                for spec in cfg[cfg_path]:
                    if "." in spec:
                        c2, meth = spec.split(".", 1)
                        fi = self.methods.get((mod, c2, meth))
                        if fi is not None:
                            add(mod, fi, f"entry '{mod}.{spec}'")
                    else:
                        for fi in self.fn_by_simple.get((mod, spec), []):
                            add(mod, fi, f"entry '{mod}.{spec}'")
        return out

    def reachable_with_locks(self, mod: str, fi: "FunctionInfo"
                             ) -> Tuple[Dict[Node, frozenset],
                                        Dict[Node, Optional[Node]]]:
        """Call-graph reachability from one thread root carrying the
        MUST-HOLD lock set: ``held[node]`` is the set of locks provably
        held on EVERY discovered path from the root to ``node`` (meet =
        intersection, so a callee reached both locked and unlocked gets
        the conservative empty set). ``parent`` keeps the first-discovery
        edge for witness-path reconstruction (deterministic: sorted
        callee iteration, FIFO worklist). Per-function call-site locks
        come from ``call_locks`` — intersected per callee NAME first, so
        a function calling ``g()`` under the lock and again outside it
        propagates the unlocked set."""
        if not hasattr(self, "_resolve_memo"):
            self._resolve_memo: Dict[Tuple[str, str, str], list] = {}
        if not hasattr(self, "_site_memo"):
            self._site_memo: Dict[Node, List[Tuple[str, frozenset]]] = {}
        # the three may-block rules and the race rule all walk from the
        # same roots — memoize the full result per root so the warm-cache
        # runtime does not scale with the rule count
        if not hasattr(self, "_rwl_memo"):
            self._rwl_memo: Dict[Node, tuple] = {}
        cached = self._rwl_memo.get((mod, fi.qualname))
        if cached is not None:
            return cached
        memo = self._resolve_memo

        def resolve(m: str, f: FunctionInfo, dn: str):
            key = (m, f.cls or "", dn)
            hit = memo.get(key)
            if hit is None:
                hit = self.resolve_call(m, f.cls, dn)
                memo[key] = hit
            return hit

        start: Node = (mod, fi.qualname)
        held: Dict[Node, frozenset] = {start: frozenset()}
        parent: Dict[Node, Optional[Node]] = {start: None}
        work: List[Node] = [start]
        i = 0
        while i < len(work):
            node = work[i]
            i += 1
            m, _qn = node
            f = self.fn_by_qual[node]
            sites = self._site_memo.get(node)
            if sites is None:
                per_dn: Dict[str, frozenset] = {}
                for dn, lrs, _line in f.call_locks:
                    ids = frozenset(
                        x for x in (self.lock_id(m, lr) for lr in lrs)
                        if x is not None)
                    per_dn[dn] = ids if dn not in per_dn \
                        else (per_dn[dn] & ids)
                sites = sorted(per_dn.items())
                self._site_memo[node] = sites
            cur = held[node]
            for dn, site_locks in sites:
                out_held = cur | site_locks
                for m2, f2 in resolve(m, f, dn):
                    n2 = (m2, f2.qualname)
                    if n2 not in held:
                        held[n2] = out_held
                        parent[n2] = node
                        work.append(n2)
                    else:
                        narrowed = held[n2] & out_held
                        if narrowed != held[n2]:
                            held[n2] = narrowed
                            work.append(n2)
        self._rwl_memo[start] = (held, parent)
        return held, parent

    # -- import graph -------------------------------------------------------

    def import_edges(self) -> List[Tuple[str, str, int]]:
        """(src module, dst module, line) for module-scope imports between
        project modules. ``from pkg import name`` targets ``pkg.name``
        when that is itself a project module, else ``pkg``."""
        edges: Dict[Tuple[str, str], int] = {}
        for mod in sorted(self.modules):
            s = self.modules[mod]
            for imp in s.module_imports:
                targets: List[str] = []
                base = imp["module"]
                if imp["names"] is None:
                    t = self._project_prefix(base)
                    if t:
                        targets.append(t)
                else:
                    for name in imp["names"]:
                        child = f"{base}.{name}"
                        if child in self.modules:
                            targets.append(child)
                        else:
                            t = self._project_prefix(base)
                            if t:
                                targets.append(t)
                for t in targets:
                    if t != mod:
                        edges.setdefault((mod, t), imp["line"])
        return sorted((a, b, line) for (a, b), line in edges.items())

    def _project_prefix(self, dotted: str) -> Optional[str]:
        parts = dotted.split(".")
        for i in range(len(parts), 0, -1):
            cand = ".".join(parts[:i])
            if cand in self.modules:
                return cand
        return None


def strongly_connected(nodes: Iterable[str],
                       edges: Dict[str, Set[str]]) -> List[List[str]]:
    """Iterative Tarjan; returns SCCs with >= 2 nodes, each sorted, the
    list sorted by first element (deterministic)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    for start in sorted(nodes):
        if start in index:
            continue
        work: List[Tuple[str, int]] = [(start, 0)]
        while work:
            v, pi = work[-1]
            if pi == 0:
                index[v] = low[v] = counter[0]
                counter[0] += 1
                stack.append(v)
                on_stack.add(v)
            advanced = False
            succs = sorted(edges.get(v, ()))
            while pi < len(succs):
                w = succs[pi]
                pi += 1
                if w not in index:
                    work[-1] = (v, pi)
                    work.append((w, 0))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                if len(scc) > 1:
                    sccs.append(sorted(scc))
    return sorted(sccs)
