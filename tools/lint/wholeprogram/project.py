"""Project: module summaries assembled into queryable whole-program graphs.

Name resolution is the heart of this module and follows the same
pragmatics as the per-file rules, extended across files:

* a dotted call ``a.b.c`` first matches locals of the calling module
  (functions, classes), then the module's import bindings, then walks the
  qualified name module-prefix-first;
* ``from pkg import f`` where ``pkg/__init__`` itself binds ``f`` from a
  submodule (a re-export) is followed through the ``__init__`` binding
  table, depth-limited so cyclic re-exports terminate;
* ``self.m()`` / ``cls.m()`` resolve to same-class methods first, then any
  same-module function of that simple name (the intra-module
  over-approximation the per-file rules already accept);
* anything else (parameters, dynamic attributes, star imports) resolves to
  nothing and drops out of the graph.

Calls to classes resolve to ``__init__`` so constructor side effects stay
on the graph.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from .summary import FunctionInfo, ModuleSummary

#: function node identity in the project call graph
Node = Tuple[str, str]  # (module dotted name, qualname)

_RESOLVE_DEPTH = 6  # max re-export hops before giving up


class Project:
    def __init__(self, summaries: Dict[str, ModuleSummary],
                 config: Dict[str, Any]):
        self.config = config
        self.by_path: Dict[str, ModuleSummary] = dict(summaries)
        self.modules: Dict[str, ModuleSummary] = {}
        for s in summaries.values():
            self.modules[s.module] = s

        # indexes
        self.fn_by_simple: Dict[Tuple[str, str], List[FunctionInfo]] = {}
        self.fn_by_qual: Dict[Node, FunctionInfo] = {}
        self.methods: Dict[Tuple[str, str, str], FunctionInfo] = {}
        self.classes: Dict[str, Set[str]] = {}
        self.lock_kinds: Dict[str, str] = {}
        for mod, s in self.modules.items():
            cls_names: Set[str] = set(s.class_locks)
            for fi in s.functions:
                self.fn_by_simple.setdefault((mod, fi.name), []).append(fi)
                self.fn_by_qual[(mod, fi.qualname)] = fi
                if fi.cls:
                    cls_names.add(fi.cls)
                    self.methods.setdefault((mod, fi.cls, fi.name), fi)
            self.classes[mod] = cls_names
            for name, kind in s.locks.items():
                self.lock_kinds[f"{mod}.{name}"] = kind
            for cls, attrs in s.class_locks.items():
                for attr, kind in attrs.items():
                    self.lock_kinds[f"{mod}.{cls}.{attr}"] = kind

    # -- resolution ---------------------------------------------------------

    def _in_module(self, mod: str, head: str,
                   tail: List[str]) -> List[Tuple[str, FunctionInfo]]:
        if head in self.classes.get(mod, ()):
            meth = tail[0] if tail else "__init__"
            fi = self.methods.get((mod, head, meth))
            return [(mod, fi)] if fi is not None else []
        fns = self.fn_by_simple.get((mod, head))
        if fns:
            return [(mod, fi) for fi in fns]
        return []

    def resolve_qualified(self, dotted: str, depth: int = 0
                          ) -> List[Tuple[str, FunctionInfo]]:
        """Resolve a fully-qualified dotted name to function records."""
        if depth > _RESOLVE_DEPTH:
            return []
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:i])
            if mod in self.modules:
                rest = parts[i:]
                head, tail = rest[0], rest[1:]
                hit = self._in_module(mod, head, tail)
                if hit:
                    return hit
                target = self.modules[mod].bindings.get(head)
                if target:  # re-export: follow the __init__ binding
                    return self.resolve_qualified(
                        ".".join([target] + tail), depth + 1)
                return []
        return []

    def resolve_call(self, mod: str, cls: Optional[str], dotted: str
                     ) -> List[Tuple[str, FunctionInfo]]:
        """Resolve one call site's dotted name from inside ``mod``."""
        s = self.modules.get(mod)
        if s is None:
            return []
        parts = dotted.split(".")
        head, tail = parts[0], parts[1:]
        if head in ("self", "cls") and tail:
            name = tail[0]
            if cls is not None:
                fi = self.methods.get((mod, cls, name))
                if fi is not None:
                    return [(mod, fi)]
            return [(mod, fi)
                    for fi in self.fn_by_simple.get((mod, name), [])]
        hit = self._in_module(mod, head, tail)
        if hit:
            return hit
        target = s.bindings.get(head)
        if target and target != head:
            return self.resolve_qualified(".".join([target] + tail))
        if target:  # plain `import pkg` style binding: head == target
            return self.resolve_qualified(dotted)
        return []

    # -- call-graph queries -------------------------------------------------

    def callees(self, mod: str, fi: FunctionInfo) -> List[Node]:
        out: List[Node] = []
        seen: Set[Node] = set()
        for dn, _line in fi.calls:
            for m2, f2 in self.resolve_call(mod, fi.cls, dn):
                node = (m2, f2.qualname)
                if node not in seen:
                    seen.add(node)
                    out.append(node)
        return sorted(out)

    def reachable_from(self, roots: Iterable[Tuple[str, FunctionInfo, Any]]
                       ) -> Dict[Node, Any]:
        """BFS over the call graph; each reached node keeps the label of
        the first root that reached it (deterministic: roots in given
        order, sorted callees)."""
        seen: Dict[Node, Any] = {}
        queue: List[Tuple[str, FunctionInfo, Any]] = []
        for mod, fi, label in roots:
            node = (mod, fi.qualname)
            if node not in seen:
                seen[node] = label
                queue.append((mod, fi, label))
        i = 0
        while i < len(queue):
            mod, fi, label = queue[i]
            i += 1
            for m2, qn in self.callees(mod, fi):
                node = (m2, qn)
                if node not in seen:
                    seen[node] = label
                    queue.append((m2, self.fn_by_qual[node], label))
        return seen

    # -- locks --------------------------------------------------------------

    def lock_id(self, mod: str, lockref: list) -> Optional[str]:
        """Canonical project-wide lock id for a summary lockref, or None
        when the reference does not resolve to a known lock object."""
        tag = lockref[0]
        s = self.modules.get(mod)
        if s is None:
            return None
        if tag == "mod":
            name = lockref[1]
            return f"{mod}.{name}" if name in s.locks else None
        if tag == "self":
            _, cls, attr = lockref
            if attr in s.class_locks.get(cls, {}):
                return f"{mod}.{cls}.{attr}"
            return None
        if tag == "ext":
            _, alias, attr = lockref
            target = s.bindings.get(alias)
            if target and target in self.modules and \
                    attr in self.modules[target].locks:
                return f"{target}.{attr}"
            return None
        return None

    # -- import graph -------------------------------------------------------

    def import_edges(self) -> List[Tuple[str, str, int]]:
        """(src module, dst module, line) for module-scope imports between
        project modules. ``from pkg import name`` targets ``pkg.name``
        when that is itself a project module, else ``pkg``."""
        edges: Dict[Tuple[str, str], int] = {}
        for mod in sorted(self.modules):
            s = self.modules[mod]
            for imp in s.module_imports:
                targets: List[str] = []
                base = imp["module"]
                if imp["names"] is None:
                    t = self._project_prefix(base)
                    if t:
                        targets.append(t)
                else:
                    for name in imp["names"]:
                        child = f"{base}.{name}"
                        if child in self.modules:
                            targets.append(child)
                        else:
                            t = self._project_prefix(base)
                            if t:
                                targets.append(t)
                for t in targets:
                    if t != mod:
                        edges.setdefault((mod, t), imp["line"])
        return sorted((a, b, line) for (a, b), line in edges.items())

    def _project_prefix(self, dotted: str) -> Optional[str]:
        parts = dotted.split(".")
        for i in range(len(parts), 0, -1):
            cand = ".".join(parts[:i])
            if cand in self.modules:
                return cand
        return None


def strongly_connected(nodes: Iterable[str],
                       edges: Dict[str, Set[str]]) -> List[List[str]]:
    """Iterative Tarjan; returns SCCs with >= 2 nodes, each sorted, the
    list sorted by first element (deterministic)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    for start in sorted(nodes):
        if start in index:
            continue
        work: List[Tuple[str, int]] = [(start, 0)]
        while work:
            v, pi = work[-1]
            if pi == 0:
                index[v] = low[v] = counter[0]
                counter[0] += 1
                stack.append(v)
                on_stack.add(v)
            advanced = False
            succs = sorted(edges.get(v, ()))
            while pi < len(succs):
                w = succs[pi]
                pi += 1
                if w not in index:
                    work[-1] = (v, pi)
                    work.append((w, 0))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                if len(scc) > 1:
                    sccs.append(sorted(scc))
    return sorted(sccs)
