"""Whole-program analysis substrate for graft-lint 2.0.

Every module in the scanned tree is distilled ONCE into a
:class:`~tools.lint.wholeprogram.summary.ModuleSummary` — import bindings,
module-scope import edges, per-function call lists, impure reads, host
syncs, and ``with <lock>:`` structure. Summaries are plain-JSON values, so
they cache on disk keyed by file content hash (``cache.SummaryCache``) and
a warm run rebuilds the project graphs without re-parsing a single file.

:class:`~tools.lint.wholeprogram.project.Project` assembles the summaries
into the two graphs the interprocedural rules query:

* the **import graph** (module-scope imports between project modules) for
  ``import-layering``;
* the **call graph** (module-qualified function nodes; ``import`` /
  ``from-import`` aliases and one-hop re-exports resolved) for
  ``cross-trace-impurity``, ``cross-host-sync``, and ``lock-order``;
* the **thread-root partition** (graft-lint 3.0): discovered spawn sites
  + configured entry points, with per-root reachability carrying the
  must-hold lock set (meet-over-paths intersection) for
  ``shared-state-race``.

Resolution is deliberately pragmatic — the same one-level alias tracking
as the per-file rules, extended across files.  Unresolvable calls (params,
dynamic attributes, star imports) are dropped, making reachability an
under-approximation across dynamic seams and an over-approximation within
resolved names (simple-name matching inside a module).
"""

from .summary import ModuleSummary, build_summary, module_name_for  # noqa: F401
from .cache import CACHE_FORMAT_VERSION, SummaryCache, default_cache_path  # noqa: F401
from .project import Project  # noqa: F401
