"""unguarded-global: lock discipline for module-level mutable state.

Applies to modules that BOTH define a module-level ``threading.Lock``/
``RLock`` and hold module-level mutable containers (the metrics registry,
the dispatch-cache LRU, the PS table maps): such a module has already
declared its state is shared across threads, so every mutation of those
containers from function code must happen lexically inside a
``with <lock>:`` block. Escape hatches, in order of preference:

* name the helper ``*_locked`` (configurable suffixes) — the convention
  used across core/ for "caller holds the lock";
* a ``# graft-lint: disable=unguarded-global`` pragma for a mutation that
  is deliberately racy (document why on the same line);
* a baseline entry with a reason.

Module-scope statements are exempt (imports execute single-threaded), and
aliases are followed one level (``b = _STATS["x"]; b[k] = v`` is still a
mutation of ``_STATS``).
"""

from __future__ import annotations

import ast
from typing import Set

from ..astutil import (MUTATORS, module_lock_names, module_mutable_globals,
                       root_name)
from ..engine import FileContext, Rule, register_rule


def _is_lock_expr(node: ast.AST, locks: Set[str]) -> bool:
    if isinstance(node, ast.Name):
        return node.id in locks
    if isinstance(node, ast.Attribute):
        return node.attr in locks
    return False


@register_rule
class UnguardedGlobalRule(Rule):
    name = "unguarded-global"
    description = ("module-level mutable containers in threading modules "
                   "must only be mutated under the module lock")

    def check(self, ctx: FileContext):
        locks = module_lock_names(ctx.tree)
        mutables = module_mutable_globals(ctx.tree)
        if not locks or not mutables:
            return
        suffixes = tuple(ctx.config.get("lock_held_suffixes", ["_locked"]))
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name.endswith(suffixes):
                continue
            yield from self._scan_fn(ctx, node, locks, mutables)

    def _scan_fn(self, ctx, fn, locks, mutables):
        # one-level alias tracking: locals bound from a tracked global
        # (or a sub-container of one) still reference the shared object;
        # map every alias back to the module global it came from
        tracked = {g: g for g in mutables}
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 and \
                    isinstance(sub.targets[0], ast.Name) and \
                    isinstance(sub.value, (ast.Name, ast.Subscript,
                                           ast.Attribute)):
                src = root_name(sub.value)
                if src in tracked:
                    tracked[sub.targets[0].id] = tracked[src]

        findings = []

        def visit(node, locked):
            if isinstance(node, ast.With):
                if any(_is_lock_expr(item.context_expr, locks)
                       for item in node.items):
                    locked = True
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                return  # nested defs are scanned as their own functions
            elif not locked:
                hit = None
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in targets:
                        if isinstance(t, (ast.Subscript, ast.Attribute)) and \
                                root_name(t) in tracked:
                            hit = root_name(t)
                elif isinstance(node, ast.Delete):
                    for t in node.targets:
                        if isinstance(t, (ast.Subscript, ast.Attribute)) and \
                                root_name(t) in tracked:
                            hit = root_name(t)
                elif isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr in MUTATORS and \
                        root_name(node.func.value) in tracked:
                    hit = root_name(node.func.value)
                if hit is not None:
                    # report against the module global, not the alias
                    findings.append(ctx.finding(
                        node, self.name,
                        f"mutation of module-level mutable state "
                        f"('{tracked[hit]}') in '{fn.name}' outside `with "
                        f"<module lock>:` (guard it, or rename the helper "
                        f"*_locked if the caller holds the lock)"))
            for child in ast.iter_child_nodes(node):
                visit(child, locked)

        for child in ast.iter_child_nodes(fn):
            visit(child, False)
        return findings
