"""blocking-under-lock: no blocking event on a path from a thread root
that holds a module / ``self.<attr>`` lock across it.

The lock-order rule sees acquisition ORDER; the race rule sees lock
DOMINATION. Neither answers the latency question: a lock held across an
RPC / sleep / queue wait / device sync serializes every thread behind
one slow call — the replica that stalls all its siblings, the metrics
scrape that blocks dispatch. This rule propagates the graft-lint 5.0
may-block events (``FunctionInfo.blocking``) through PR 14's per-call-
site held-lock reachability: from each thread root, any function reached
with a non-empty MUST-HOLD lock set whose body blocks is a finding, with
the full root → … → blocking-site witness chain.

Precision trades (all err toward staying quiet on disciplined code):

* ``lock-acquire`` events are skipped — nested acquisition order is
  lock-order's domain, and acquiring B under A is only a stall if B is
  itself held across something slow (which fires at B's site);
* ``Condition.wait`` RELEASES its own lock while waiting — the waited
  condition's lock id is subtracted from the held set before judging;
* bounded sleeps (``jitter_sleep``/``time.sleep`` with a literal) under
  a lock are flagged only when the held lock is not the sleeping
  function's own shutdown/poll jitter — concretely: a bounded ``sleep``
  event is exempt, an unbounded one never is;
* ``*_locked`` helpers (``lock_held_suffixes``) blocking by design are
  the CALLER's finding: the event is attributed where the lock was
  actually taken, so the helper itself is skipped only when nothing in
  the chain holds a resolvable lock;
* ``__init__``/``__del__``-style construction/teardown is excluded.

Suppression: pragma on the blocking line, or a baseline entry whose
reason says why holding across the block is the semantics (e.g. the
ps_service push lock that serializes RPCs by design).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from ..engine import Finding, ProjectRule, register_rule
from .shared_state_race import (_EXCLUDED_FNS, _chain, _chain_text,
                                _locks_text)

#: kinds that count as "blocking" while a lock is held. lock-acquire is
#: lock-order's domain; file-io is only a hot-path concern; bounded
#: sleeps are the shutdown/poll jitter idiom (exempt, see module doc).
_KINDS = ("sleep", "condition-wait", "queue", "future-wait", "thread-join",
          "rpc", "subprocess", "device-sync", "jit-compile")


def _acquire_site(project, chain, lock_ids):
    """(module, line) of the first acquisition of any of ``lock_ids``
    along the witness chain, for the root→acquire→…→site narrative."""
    for node in chain:
        m, _qn = node
        fi = project.fn_by_qual[node]
        for lr, line in fi.acquires:
            if project.lock_id(m, list(lr)) in lock_ids:
                return m, line
    return None


@register_rule
class BlockingUnderLockRule(ProjectRule):
    name = "blocking-under-lock"
    description = ("no sleep/RPC/wait/device-sync reachable from a thread "
                   "root while a module or self.<attr> lock is held")

    def check_project(self, project):
        suffixes = tuple(project.config.get("lock_held_suffixes",
                                            ["_locked"]))
        roots = project.thread_roots()
        seen: set = set()
        for mod, rfi, label in roots:
            held, parent = project.reachable_with_locks(mod, rfi)
            chain_memo: Dict[Tuple[str, str], List] = {}
            for node in sorted(held):
                m, _qn = node
                fi = project.fn_by_qual[node]
                if fi.name in _EXCLUDED_FNS or not fi.blocking:
                    continue
                caller_holds = fi.name.endswith(suffixes)
                for ev in fi.blocking:
                    kind, detail, bounded, _ds, lrs, recv, line = ev
                    if kind not in _KINDS:
                        continue
                    if kind == "sleep" and bounded:
                        continue
                    lex = frozenset(
                        x for x in (project.lock_id(m, list(lr))
                                    for lr in lrs) if x is not None)
                    eff = held[node] | lex
                    if kind == "condition-wait" and recv is not None:
                        cid = project.lock_id(m, list(recv))
                        if cid is not None:
                            # Condition.wait releases its own lock — and
                            # the Condition IS that lock when built from
                            # one (threading.Condition(self._lock) shares
                            # the id only in source, so drop both names)
                            eff = eff - {cid}
                    if not eff and caller_holds:
                        # the *_locked convention: the caller provably
                        # holds A lock we cannot resolve here — still a
                        # blocking call under it
                        eff = frozenset(["<caller-held lock>"])
                    if not eff:
                        continue
                    key = (m, fi.qualname, line, kind)
                    if key in seen:
                        continue
                    seen.add(key)
                    s = project.modules[m]
                    if s.suppressed(self.name, line):
                        continue
                    chain = chain_memo.get(node)
                    if chain is None:
                        chain = _chain(parent, node)
                        chain_memo[node] = chain
                    related = [
                        {"path": project.modules[cm].path,
                         "line": project.fn_by_qual[(cm, cq)].line,
                         "message": f"witness: '{cq}'"}
                        for cm, cq in chain]
                    acq = _acquire_site(project, chain, eff)
                    if acq is not None:
                        am, aline = acq
                        related.append(
                            {"path": project.modules[am].path,
                             "line": aline,
                             "message": f"acquires {_locks_text(eff)}"})
                    related.append({"path": s.path, "line": line,
                                    "message": f"blocks: {kind} "
                                               f"'{detail}'"})
                    bnd = "bounded" if bounded else "unbounded"
                    yield Finding(
                        s.path, line, self.name,
                        f"{bnd} {kind} '{detail}' in '{fi.qualname}' runs "
                        f"while holding {_locks_text(eff)} [{label}: "
                        f"{_chain_text(chain)}] — every thread taking "
                        f"that lock stalls behind this call; move the "
                        f"blocking work outside the critical section, "
                        f"snapshot state under the lock and block after "
                        f"releasing it, or baseline with the reason "
                        f"holding across the block IS the semantics",
                        related=tuple(related))
