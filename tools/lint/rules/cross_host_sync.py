"""cross-host-sync: no device→host transfer reachable from the dispatch
fast path, through any call chain.

The per-file ``host-sync`` rule flags syncs lexically inside loops; the
dispatch fast path is a different budget — ``apply()`` runs once per op,
so a ``.item()`` / ``.numpy()`` / ``np.asarray(x._data)`` ANYWHERE in its
transitive callees stalls every eager op, even with no loop in sight
(PR 2 bought ~10× per-op exactly by deleting such stalls). Roots come
from the engine config (``fast_path_roots``: ``"<path>::<fn>"``) and the
reachability is the whole-program call graph, so a helper three modules
away is still attributed to the dispatch root that reaches it.

Deliberate syncs (the fused check_nan_inf verdict, debug paths) carry a
baseline entry whose reason says the sync IS the semantics.
"""

from __future__ import annotations

from ..astutil import path_matches
from ..engine import Finding, ProjectRule, register_rule


@register_rule
class CrossHostSyncRule(ProjectRule):
    name = "cross-host-sync"
    description = ("no .item()/.numpy()/host-forcing conversions reachable "
                   "from the dispatch fast path (any call chain)")

    def check_project(self, project):
        specs = project.config.get("fast_path_roots", [])
        roots = []
        for spec in specs:
            path, _, fname = spec.partition("::")
            for mod in sorted(project.modules):
                s = project.modules[mod]
                if not path_matches(s.path, [path]):
                    continue
                for fi in project.fn_by_simple.get((mod, fname), []):
                    roots.append((mod, fi, f"{mod}.{fname}"))
        if not roots:
            return
        reached = project.reachable_from(roots)
        for (mod, qualname) in sorted(reached):
            root_label = reached[(mod, qualname)]
            fi = project.fn_by_qual[(mod, qualname)]
            s = project.modules[mod]
            for what, line in fi.host_syncs:
                yield Finding(
                    s.path, line, self.name,
                    f"host sync {what} in '{fi.qualname}' is reachable "
                    f"from the dispatch fast path (root '{root_label}'): "
                    f"every eager op dispatch can pay this device "
                    f"round-trip (move it off the fast path, or baseline "
                    f"with the reason the sync IS the semantics)")
