"""shared-state-race: whole-program lock-domination over shared state.

The per-file ``unguarded-global`` rule sees one module and one lock; the
``lock-order`` rule sees acquisition ORDER. Neither answers the question
every review keeps re-asking: is this field, written by the engine step
thread and read by the watchdog poll thread, actually guarded by a
COMMON lock on both sides? This rule does, whole-program:

1. **Thread roots** — every ``threading.Thread(target=…)`` /
   ``threading.Timer`` spawn site, the ``do_*`` methods of classes handed
   to a ``ThreadingHTTPServer``-style ctor, plus the ``thread_roots``
   config table for the seams discovery cannot see (public entry points
   running on caller threads, stream callbacks, Future resolution).
2. **Lock domination** — from each root, reachability carries the set of
   locks provably held on EVERY path (meet = intersection, propagated
   through call edges from the lexical ``with <lock>:`` structure), so
   ``with self._lock: self._evict()`` guards the callee's accesses too.
3. **Conflict** — a ``self.<attr>`` field or module-level mutable global
   accessed from ≥ 2 roots, at least one access a write, where the two
   sides' guarding lock sets do not intersect. The finding prints both
   witness paths (root → … → access).

Out of scope by design (the precision trades that keep this signal):

* accesses in ``__init__``/``__post_init__``/``__new__``/``__del__`` —
  construction happens-before any spawn, teardown after joins;
* accesses in ``*_locked`` helpers (configurable suffixes) — the
  caller-holds convention, same trust as the other lock rules;
* fields only ever assigned an internally-synchronized object
  (Event/Queue/Semaphore/…) — their methods synchronize themselves;
* per-instance reasoning: all instances of a class share one node, and a
  root that CAN reach an access is assumed to run concurrently with any
  other root — both over-approximations that err toward reporting.

Suppression: the usual ``# graft-lint: disable=shared-state-race`` pragma
on the WRITE line, or a baseline entry whose reason says why the race is
benign (GIL-atomic flag, single-consumer protocol, monotonic latch).
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Tuple

from ..engine import Finding, ProjectRule, register_rule

#: construction / teardown functions: happens-before (after) the threads
_EXCLUDED_FNS = {"__init__", "__post_init__", "__new__", "__del__"}

#: witness chains longer than this elide their middle
_CHAIN_CAP = 6


def _chain(parent: Dict, node: Tuple[str, str]) -> List[Tuple[str, str]]:
    out = [node]
    while parent.get(node) is not None:
        node = parent[node]
        out.append(node)
    out.reverse()
    return out


def _chain_text(chain: List[Tuple[str, str]]) -> str:
    names = [qn for _m, qn in chain]
    if len(names) > _CHAIN_CAP:
        names = names[:3] + ["…"] + names[-2:]
    return " -> ".join(names)


def _locks_text(guard: FrozenSet[str]) -> str:
    return ", ".join(sorted(guard)) if guard else "no lock"


class _Access:
    __slots__ = ("root", "rw", "guard", "mod", "qual", "line", "chain")

    def __init__(self, root, rw, guard, mod, qual, line, chain):
        self.root, self.rw, self.guard = root, rw, guard
        self.mod, self.qual, self.line = mod, qual, line
        self.chain = chain

    def sort_key(self):
        return (self.rw != "w", self.root, self.mod, self.qual, self.line)


@register_rule
class SharedStateRaceRule(ProjectRule):
    name = "shared-state-race"
    description = ("shared mutable state reachable from two thread roots "
                   "must be lock-dominated (common lock on every side "
                   "that writes)")

    def check_project(self, project):
        suffixes = tuple(project.config.get("lock_held_suffixes",
                                            ["_locked"]))
        roots = project.thread_roots()
        if len(roots) < 2:
            return

        # targets: ("self", mod, cls, attr) | ("glob", mod, name)
        targets: Dict[tuple, List[_Access]] = {}
        for mod, fi, label in roots:
            held, parent = project.reachable_with_locks(mod, fi)
            chain_memo: Dict[Tuple[str, str], List] = {}
            for node in sorted(held):
                m, _qn = node
                f = project.fn_by_qual[node]
                if f.name in _EXCLUDED_FNS or f.name.endswith(suffixes):
                    continue
                if not f.accesses:
                    continue
                chain = chain_memo.get(node)
                if chain is None:
                    chain = _chain(parent, node)
                    chain_memo[node] = chain
                for acc in f.accesses:
                    if acc[0] == "self":
                        _tag, cls, attr, rw, lrs, line = acc
                        key = ("self", m, cls, attr)
                    else:
                        _tag, gname, rw, lrs, line = acc
                        key = ("glob", m, gname)
                    lex = frozenset(
                        x for x in (project.lock_id(m, list(lr))
                                    for lr in lrs) if x is not None)
                    targets.setdefault(key, []).append(_Access(
                        label, rw, held[node] | lex, m, f.qualname,
                        line, chain))

        for key in sorted(targets):
            recs = sorted(targets[key], key=_Access.sort_key)
            if len({r.root for r in recs}) < 2:
                continue
            pair = None
            for w in recs:
                if w.rw != "w":
                    break  # sorted writes-first: no write, no race
                # a pragma on THIS write's line acknowledges this write
                # only — anchor the finding on the next conflicting
                # write instead of letting one pragma silence the target
                if project.modules[w.mod].suppressed(self.name, w.line):
                    continue
                for o in recs:
                    if o.root != w.root and not (w.guard & o.guard):
                        pair = (w, o)
                        break
                if pair is not None:
                    break
            if pair is None:
                continue
            w, o = pair
            if key[0] == "self":
                _k, m, cls, attr = key
                what = f"'self.{attr}' of class '{cls}' ({m})"
            else:
                _k, m, gname = key
                what = f"module global '{gname}' ({m})"
            overb = "written" if o.rw == "w" else "read"
            s = project.modules[w.mod]
            related = tuple(
                {"path": project.modules[cm].path,
                 "line": project.fn_by_qual[(cm, cq)].line,
                 "message": f"witness: '{cq}'"}
                for cm, cq in (w.chain + o.chain))
            yield Finding(
                s.path, w.line, self.name,
                f"possible data race on {what}: written in '{w.qual}' "
                f"under {_locks_text(w.guard)} [{w.root}: "
                f"{_chain_text(w.chain)}] and {overb} in '{o.qual}' "
                f"under {_locks_text(o.guard)} [{o.root}: "
                f"{_chain_text(o.chain)}] — no common lock dominates "
                f"both sides; guard them with one lock, route the "
                f"access through a *_locked helper, or baseline with "
                f"the reason the race is benign",
                related=related)
