"""span-discipline: spans are context-managed and near-free on the fast path.

Two invariants of the ISSUE 12 tracing layer:

* **Spans exist only as context managers.** The chaos suites prove every
  trace is balanced (each start has exactly one end on every exit path,
  including KillPoint unwinds) — a property that holds structurally for
  ``with trace.span(...):`` and cannot be proven for manual begin/end
  pairs. The rule flags any call to a manual pairing API
  (``begin_span``/``end_span`` — deliberately not exported by ``trace``,
  so a finding means someone re-grew one) and any ``trace.span(...)`` /
  ``span(...)`` call that is not the context expression of a ``with``
  item (assigning the manager and entering it by hand re-opens the
  unbalanced-on-exception hole).

* **The dispatch fast path pays nothing for disabled tracing.** Inside
  the modules hosting the ``fast_path_roots`` (``span_hot_modules``
  config: core/tensor.py, dispatch_cache.py, autograd.py,
  step_capture.py) even the disabled-mode probe — a call returning the
  shared no-op manager — is too much per op. Span/instant construction
  there must sit lexically under an ``if ...enabled():`` guard, the same
  discipline ``_op_metrics_hook`` established in PR 1 (hooks are None
  when off; the hot path pays one is-None probe).

``span_impl_paths`` (default ``paddle_tpu/observability/trace.py``) is
exempt — it IS the implementation.
"""

from __future__ import annotations

import ast

from ..astutil import path_matches
from ..engine import FileContext, Rule, register_rule

#: manual begin/end pairing APIs — trace deliberately does not export
#: these; a call site means someone rebuilt manual pairing
_MANUAL_NAMES = {"begin_span", "end_span"}

#: trace-layer constructors that must be guarded in hot modules
_GUARDED_NAMES = {"span", "instant", "new_trace", "record"}


def _trace_aliases(tree: ast.Module):
    """(names bound to the trace module, directly-imported span-layer
    names) across every import in the file — module-scope and deferred."""
    mod_aliases, direct = set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod == "observability" or mod.endswith(".observability") \
                    or mod == "paddle_tpu.observability":
                for a in node.names:
                    if a.name == "trace":
                        mod_aliases.add(a.asname or "trace")
            elif mod.endswith("observability.trace") or mod == "trace":
                for a in node.names:
                    if a.name in _GUARDED_NAMES | _MANUAL_NAMES:
                        direct.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.endswith("observability.trace"):
                    mod_aliases.add(a.asname or a.name.split(".")[0])
    return mod_aliases, direct


def _call_kind(call: ast.Call, mod_aliases, direct):
    """The trace-layer function a call targets ("span", "begin_span", ...)
    or None when the call is unrelated."""
    f = call.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id in mod_aliases:
        return f.attr if f.attr in _GUARDED_NAMES | _MANUAL_NAMES else None
    if isinstance(f, ast.Name) and f.id in direct:
        return f.id
    # manual pairing is flagged by bare name too: trace does not export
    # begin_span/end_span, so ANY spelling of them is a re-grown pair
    if isinstance(f, ast.Attribute) and f.attr in _MANUAL_NAMES:
        return f.attr
    if isinstance(f, ast.Name) and f.id in _MANUAL_NAMES:
        return f.id
    return None


def _is_enabled_guard(test: ast.AST) -> bool:
    """True when an ``if`` test consults the tracing enabled-probe
    (``...enabled()`` / ``...mode() != "off"``-style calls)."""
    for n in ast.walk(test):
        if isinstance(n, ast.Call):
            f = n.func
            name = f.attr if isinstance(f, ast.Attribute) else \
                getattr(f, "id", "")
            if name in ("enabled", "mode"):
                return True
    return False


@register_rule
class SpanDisciplineRule(Rule):
    name = "span-discipline"
    description = ("spans only via the span() context manager; no span "
                   "construction on the dispatch fast path outside an "
                   "enabled() guard")

    def check(self, ctx: FileContext):
        if path_matches(ctx.path, ctx.config.get(
                "span_impl_paths", ["paddle_tpu/observability/trace.py"])):
            return
        mod_aliases, direct = _trace_aliases(ctx.tree)
        hot = path_matches(ctx.path, ctx.config.get("span_hot_modules", []))
        findings = []

        # every span(...) call that IS a with-item context expression
        with_items = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    with_items.add(id(item.context_expr))

        def visit(node, guarded):
            if isinstance(node, ast.If):
                g = guarded or _is_enabled_guard(node.test)
                for child in node.body:
                    visit(child, g)
                for child in node.orelse:
                    visit(child, guarded)
                return
            if isinstance(node, ast.Call):
                kind = _call_kind(node, mod_aliases, direct)
                if kind in _MANUAL_NAMES:
                    findings.append(ctx.finding(
                        node, self.name,
                        f"manual span pairing `{kind}(...)`: spans exist "
                        f"only as `with trace.span(...):` context managers "
                        f"— balanced begin/end on every exit path is the "
                        f"flight recorder's structural guarantee"))
                elif kind == "span" and id(node) not in with_items:
                    findings.append(ctx.finding(
                        node, self.name,
                        "`span(...)` used outside a `with` item: entering "
                        "the manager by hand re-opens the unbalanced-on-"
                        "exception hole — write `with trace.span(...):`"))
                elif kind is not None and hot and not guarded:
                    findings.append(ctx.finding(
                        node, self.name,
                        f"`{kind}(...)` on the dispatch fast path without "
                        f"an enabled() guard: this module hosts "
                        f"fast_path_roots, where even the disabled-mode "
                        f"probe is per-op overhead — wrap in "
                        f"`if ...enabled():` (the _op_metrics_hook "
                        f"discipline)"))
            for child in ast.iter_child_nodes(node):
                visit(child, guarded)

        visit(ctx.tree, False)
        return findings
