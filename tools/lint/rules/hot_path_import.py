"""hot-path-import: no ``import`` statements inside function bodies of the
configured hot-path modules.

The eager dispatch fast path (``apply`` → ``_apply_impl``/``_apply_cached``
→ tape record) runs once per op; a function-body ``import`` there pays a
sys.modules lookup plus name binding on every call — PR 2 hoisted one by
hand and pinned three functions, this rule covers the whole module set
(``hot_path_modules`` in the engine config). Deferred imports that exist
to break genuine circular-import cycles belong in the baseline with a
reason, not silently in the code.
"""

from __future__ import annotations

import ast

from ..astutil import path_matches
from ..engine import FileContext, Rule, register_rule


@register_rule
class HotPathImportRule(Rule):
    name = "hot-path-import"
    description = ("function-body imports are banned in hot-path modules "
                   "(hoist to module scope)")

    def check(self, ctx: FileContext):
        if not path_matches(ctx.path, ctx.config.get("hot_path_modules", [])):
            return
        rule = self.name
        findings = []

        def visit(node, fn_name):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn_name = node.name  # attribute imports to the INNERMOST fn
            elif fn_name and isinstance(node, (ast.Import, ast.ImportFrom)):
                mod = node.module if isinstance(node, ast.ImportFrom) \
                    else ",".join(a.name for a in node.names)
                findings.append(ctx.finding(
                    node, rule,
                    f"per-call import of '{mod or '.'}' inside hot-path "
                    f"function '{fn_name}' (hoist to module scope, or "
                    f"baseline with the circular-import reason)"))
            for child in ast.iter_child_nodes(node):
                visit(child, fn_name)

        visit(ctx.tree, None)
        return findings
