"""import-layering: the declared layer DAG plus import-cycle detection.

The engine config declares the intended layering (``import_layers``: base
layers first — foundation → core → api → distributed → apps for the
shipped tree). A module may module-scope-import modules of its own or
LOWER layers only; a lower layer importing a higher one is a back-edge
that inverts the architecture (``core`` silently depending on
``distributed`` is how god-modules happen). Matching is by most-specific
dotted prefix; modules matching no prefix are unconstrained.

Separately, any strongly-connected component in the module-scope import
graph is reported as an import cycle: such modules only import because
somebody currently imports them in a lucky order. Function-body deferred
imports are the sanctioned cycle-breaker and are deliberately NOT part of
this graph (the hot-path-import rule prices them where they cost).
"""

from __future__ import annotations

from typing import Dict, Optional, Set

from ..engine import Finding, ProjectRule, register_rule
from ..wholeprogram.project import strongly_connected


def _layer_of(module: str, layers) -> Optional[int]:
    """Most-specific prefix match wins; None = unconstrained."""
    best: Optional[int] = None
    best_len = -1
    for i, layer in enumerate(layers):
        for p in layer.get("prefixes", []):
            if (module == p or module.startswith(p + ".")) and \
                    len(p) > best_len:
                best, best_len = i, len(p)
    return best


@register_rule
class ImportLayeringRule(ProjectRule):
    name = "import-layering"
    description = ("module-scope imports must follow the declared layer "
                   "DAG and form no cycles")

    def check_project(self, project):
        layers = project.config.get("import_layers", [])
        order = " -> ".join(l["name"] for l in layers)
        edges = project.import_edges()

        for src, dst, line in edges:
            ls, ld = _layer_of(src, layers), _layer_of(dst, layers)
            if ls is not None and ld is not None and ls < ld:
                yield Finding(
                    project.modules[src].path, line, self.name,
                    f"layering violation: '{src}' (layer "
                    f"'{layers[ls]['name']}') imports '{dst}' from the "
                    f"higher layer '{layers[ld]['name']}' at module scope "
                    f"(declared order: {order}; defer the import into the "
                    f"function that needs it, or move the shared piece "
                    f"down a layer)")

        graph: Dict[str, Set[str]] = {}
        for src, dst, _line in edges:
            graph.setdefault(src, set()).add(dst)
        nodes = set(graph)
        for tgts in graph.values():
            nodes |= tgts
        for scc in strongly_connected(nodes, graph):
            first = scc[0]
            line = min((ln for s, d, ln in edges
                        if s == first and d in scc), default=1)
            cycle = " -> ".join(scc + [first])
            yield Finding(
                project.modules[first].path, line, self.name,
                f"import cycle (module-scope): {cycle} — import order is "
                f"load-bearing; break the cycle with a function-body "
                f"import or by moving the shared names to a leaf module")
