"""lock-order: static deadlock detection across the whole lock plane.

Builds the lock-acquisition graph: an edge A → B means some code path
acquires lock B while holding lock A, either lexically (``with A: …
with B:``) or through a call chain (``with A: f()`` where ``f`` — in any
module — transitively acquires B). Lock identity is static: module-level
``threading.Lock/RLock/Condition`` objects and ``self.<attr>`` instance
locks, named ``<module>.<name>`` / ``<module>.<Class>.<attr>`` (all
instances of a class share one node — an over-approximation that errs
toward reporting).

Findings:

* a cycle through ≥ 2 locks — two threads taking the locks in opposing
  orders can deadlock (the PS/store/communicator failover class);
* a self-edge on a NON-reentrant ``Lock`` — the thread re-acquiring it
  deadlocks against itself (RLock/Condition self-edges are fine and
  skipped).

The ``*_locked`` caller-holds convention is honored: calls to functions
whose name carries a configured suffix (``lock_held_suffixes``) do not
propagate acquisitions — the convention promises the callee runs under
the caller's lock and takes none of its own, so a defensive re-acquire
pattern behind the suffix is not reported as a self-deadlock.
"""

from __future__ import annotations

from typing import Dict, List, Set, Tuple

from ..engine import Finding, ProjectRule, register_rule
from ..wholeprogram.project import strongly_connected


@register_rule
class LockOrderRule(ProjectRule):
    name = "lock-order"
    description = ("no cycles in the static lock-acquisition order "
                   "(potential deadlocks), across call chains")

    def check_project(self, project):
        suffixes = tuple(project.config.get("lock_held_suffixes",
                                            ["_locked"]))

        def is_locked_call(dotted: str) -> bool:
            return dotted.split(".")[-1].endswith(suffixes)

        # direct lock sets + resolved callee edges, computed ONCE per node
        # (resolution results never change across fixpoint iterations)
        direct: Dict[Tuple[str, str], Set[str]] = {}
        nodes: List[Tuple[str, object]] = []
        callee_nodes: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
        resolve_memo: Dict[Tuple[str, str, str],
                           List[Tuple[str, object]]] = {}

        def resolve(mod, fi, dn):
            key = (mod, fi.cls or "", dn)
            hit = resolve_memo.get(key)
            if hit is None:
                hit = project.resolve_call(mod, fi.cls, dn)
                resolve_memo[key] = hit
            return hit

        for mod in sorted(project.modules):
            for fi in project.modules[mod].functions:
                nodes.append((mod, fi))
                d = set()
                for lr, _line in fi.acquires:
                    lid = project.lock_id(mod, lr)
                    if lid is not None:
                        d.add(lid)
                direct[(mod, fi.qualname)] = d
                outs: Dict[Tuple[str, str], None] = {}
                for dn, _line in fi.calls:
                    if is_locked_call(dn):
                        continue
                    for m2, f2 in resolve(mod, fi, dn):
                        outs[(m2, f2.qualname)] = None
                callee_nodes[(mod, fi.qualname)] = list(outs)

        trans = {k: set(v) for k, v in direct.items()}
        changed = True
        while changed:  # fixpoint is now pure set arithmetic over edges
            changed = False
            for mod, fi in nodes:
                cur = trans[(mod, fi.qualname)]
                for node in callee_nodes[(mod, fi.qualname)]:
                    extra = trans[node] - cur
                    if extra:
                        cur |= extra
                        changed = True

        # edge set with one witness per (A, B)
        edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

        def add_edge(a: str, b: str, path: str, line: int, desc: str):
            edges.setdefault((a, b), (path, line, desc))

        for mod, fi in nodes:
            path = project.modules[mod].path
            for lr_out, lr_in, line in fi.nest_edges:
                a = project.lock_id(mod, lr_out)
                b = project.lock_id(mod, lr_in)
                if a and b:
                    add_edge(a, b, path, line,
                             f"'{fi.qualname}' nests `with` blocks")
            for lr, dn, line in fi.calls_under_lock:
                if is_locked_call(dn):
                    continue
                a = project.lock_id(mod, lr)
                if a is None:
                    continue
                for m2, f2 in resolve(mod, fi, dn):
                    for b in sorted(trans[(m2, f2.qualname)]):
                        add_edge(a, b, path, line,
                                 f"'{fi.qualname}' calls "
                                 f"'{m2}.{f2.qualname}' (which acquires "
                                 f"'{b}') while holding '{a}'")

        # self-deadlocks: A -> A on a non-reentrant Lock
        for (a, b), (path, line, desc) in sorted(edges.items()):
            if a == b and project.lock_kinds.get(a) == "Lock":
                yield Finding(
                    path, line, self.name,
                    f"potential self-deadlock: non-reentrant lock '{a}' "
                    f"can be re-acquired while held — {desc} (make the "
                    f"callee *_locked, or split the lock-free inner)")

        # multi-lock cycles: SCCs of the acquisition graph
        graph: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            if a != b:
                graph.setdefault(a, set()).add(b)
        lock_nodes = set(graph)
        for tgts in graph.values():
            lock_nodes |= tgts
        for scc in strongly_connected(lock_nodes, graph):
            witnesses = sorted(
                (p, ln, d) for (a, b), (p, ln, d) in edges.items()
                if a in scc and b in scc and a != b)
            descs = "; ".join(d for _p, _l, d in witnesses[:3])
            path, line = witnesses[0][0], witnesses[0][1]
            yield Finding(
                path, line, self.name,
                f"potential deadlock: lock-order cycle between "
                f"{', '.join(scc)} — threads can acquire them in opposing "
                f"orders ({descs}); pick one global order and baseline it "
                f"in MIGRATING.md, or drop a lock before the cross-call")
