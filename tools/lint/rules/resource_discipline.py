"""resource-discipline: acquire/release pairing verified across exception edges.

The serving tier hands real resources around: KV pages come out of
``PagedKVCache.alloc``/``acquire_prefix`` and must go back through ``free``
(or move into a ``_Slot``/the prefix index), scheduler admissions popped by
``next_admissions`` must be requeued or resolved, and a circuit breaker's
half-open probe taken by ``before_call`` is only returned by
``record_success``/``record_failure`` — leak that one and the breaker wedges
half-open forever. PRs 7/8/17 police these only at runtime (double-free
counters, chaos ``outstanding_pages == 0`` pins); this rule checks the
discipline statically, per path.

For every function that calls a configured acquire (``resource_pairs`` in
the lint config; the whole-program summaries index which files acquire so a
warm-cache run re-parses only those), the rule builds the function's CFG
(:mod:`tools.lint.cfg`) and searches for a path from the acquire site to a
function exit — the ``raise`` exit especially — on which the handle neither
reaches a release call nor escapes ownership. Ownership escapes are:
``return`` of the handle, storing it into an attribute/subscript, passing
it to a constructor (capitalized callee) or a configured ``transfer``
callee, appending it into a container (mutator methods), or capture by a
nested ``def``. Aliases propagate through assignment/concatenation/
``for``-targets; ``if h is None``-style guards kill the obligation on the
branch where nothing was acquired. ``with ... as h`` acquisitions and
``finally``-based releases are all-paths by construction (the CFG clones
``finally`` suites per continuation). Functions matching
``resource_caller_owns_suffixes`` (the ``*_locked`` convention) hand the
obligation to their caller and are skipped, as are the methods of the
classes that implement the pairs themselves.

Pairs with ``"handleless": true`` (the breaker probe) have no handle
variable; acquire and release are matched by receiver expression text
(``rep.breaker.before_call()`` ... ``rep.breaker.record_failure()``).

Witness paths come out as ``Finding.related`` (SARIF relatedLocations):
the acquire site, the statement whose exception starts the leaking path,
and the frontier where the path leaves the function.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from ..astutil import MUTATORS, dotted_name
from ..cfg import CFG, iter_cfgs
from ..engine import REPO_ROOT, Finding, ProjectRule, register_rule
from ..wholeprogram.project import Project

_PATH_CAP = 6
#: calls through which a value keeps referring to the same elements
_ALIAS_CALLS = ("list", "sorted", "tuple", "reversed")


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _alias_sources(v: ast.AST) -> Set[str]:
    """Names whose value flows wholesale into ``v`` (alias-extending forms
    only — ``len(h)`` is NOT an alias of ``h``, ``h + extra`` is)."""
    if isinstance(v, ast.Name):
        return {v.id}
    if isinstance(v, ast.BinOp):
        return _alias_sources(v.left) | _alias_sources(v.right)
    if isinstance(v, (ast.List, ast.Tuple, ast.Set)):
        out: Set[str] = set()
        for e in v.elts:
            out |= _alias_sources(e)
        return out
    if isinstance(v, ast.IfExp):
        return _alias_sources(v.body) | _alias_sources(v.orelse)
    if isinstance(v, (ast.Subscript, ast.Starred)):
        return _alias_sources(v.value)
    if isinstance(v, ast.Call) and isinstance(v.func, ast.Name) and \
            v.func.id in _ALIAS_CALLS:
        out = set()
        for a in v.args:
            out |= _alias_sources(a)
        return out
    return set()


def _headers(st: ast.stmt) -> List[ast.AST]:
    """The expressions of ``st`` that execute in the block holding it.

    Compound statements sit in the block where their header/test evaluates;
    their suites live in other blocks, so only the header may have effects
    here.
    """
    if isinstance(st, (ast.If, ast.While)):
        return [st.test]
    if isinstance(st, (ast.For, ast.AsyncFor)):
        return [st.iter]
    if isinstance(st, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in st.items]
    if isinstance(st, ast.Match):
        return [st.subject]
    if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
        return []
    return [st]


def _may_raise(st: ast.stmt) -> bool:
    """Can executing ``st``'s header realistically raise?  Calls (the
    dominant case), subscripts (KeyError/IndexError) and awaits; pure
    name/arithmetic shuffling is treated as non-raising so that e.g.
    ``pages = shared + pages`` between an acquire and its guarded region
    does not manufacture an unfixable leak path."""
    if isinstance(st, (ast.Raise, ast.Assert)):
        return True
    if isinstance(st, (ast.Pass, ast.Break, ast.Continue, ast.Global,
                       ast.Nonlocal, ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef)):
        return False
    for e in _headers(st):
        for n in ast.walk(e):
            if isinstance(n, (ast.Call, ast.Subscript, ast.Await)):
                return True
    return False


def _last_comp(func: ast.AST) -> Optional[str]:
    dn = dotted_name(func)
    if dn:
        return dn.split(".")[-1]
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _expr_text(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on real trees
        return ""


def _guard(test: ast.AST) -> Tuple[Optional[str], bool]:
    """(guarded name, is-held-on-true-branch) for None/truthiness guards."""
    if isinstance(test, ast.Name):
        return test.id, True
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) and \
            isinstance(test.operand, ast.Name):
        return test.operand.id, False
    if isinstance(test, ast.Compare) and isinstance(test.left, ast.Name) \
            and len(test.ops) == 1 and \
            isinstance(test.comparators[0], ast.Constant) and \
            test.comparators[0].value is None:
        if isinstance(test.ops[0], ast.Is):
            return test.left.id, False
        if isinstance(test.ops[0], ast.IsNot):
            return test.left.id, True
    return None, True


class _Site:
    __slots__ = ("pair", "bid", "idx", "line", "aliases", "receiver", "acq")

    def __init__(self, pair: dict, bid: int, idx: int, line: int,
                 aliases: FrozenSet[str], receiver: Optional[str],
                 acq: str) -> None:
        self.pair = pair
        self.bid = bid
        self.idx = idx
        self.line = line
        self.aliases = aliases
        self.receiver = receiver
        self.acq = acq  # last component of the acquiring call, for messages


def _apply(st: ast.stmt, aliases: FrozenSet[str], receiver: Optional[str],
           pair: dict) -> Tuple[FrozenSet[str], bool, bool]:
    """Effect of one statement: (new alias set, obligation discharged?,
    discharged by a fork_transfers callee?).

    The third flag marks discharges through callees configured as taking
    ownership only on SUCCESSFUL return — the caller still forks the
    held state down the statement's exception edge. Releases, plain
    transfers, constructors and container stores are atomic: attempting
    them discharges the obligation on every outcome.
    """
    rel = pair["_rel_last"]
    transfer = pair.get("transfer", ())
    fork_transfer = pair.get("fork_transfers", ())
    handleless = pair.get("handleless", False)
    for e in _headers(st):
        for c in (n for n in ast.walk(e) if isinstance(n, ast.Call)):
            last = _last_comp(c.func)
            if last is None:
                continue
            if handleless:
                if last in rel and isinstance(c.func, ast.Attribute) and \
                        _expr_text(c.func.value) == receiver:
                    return aliases, True, False
                continue
            arg_names: Set[str] = set()
            for a in list(c.args) + [kw.value for kw in c.keywords]:
                arg_names |= _names_in(a)
            if last in rel and (arg_names & aliases):
                return aliases, True, False
            if last in transfer and (_names_in(c) & aliases):
                return aliases, True, False
            if last in fork_transfer and (_names_in(c) & aliases):
                return aliases, True, True
            if last.lstrip("_")[:1].isupper() and (arg_names & aliases):
                return aliases, True, False  # constructor takes ownership
            if last in MUTATORS and isinstance(c.func, ast.Attribute) and \
                    (arg_names & aliases):
                return aliases, True, False  # stored into a container
    if handleless:
        return aliases, False, False
    if isinstance(st, ast.Return):
        if st.value is not None and (_names_in(st.value) & aliases):
            return aliases, True, False
    if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
        if _names_in(st) & aliases:
            return aliases, True, False  # closure capture escapes ownership
    if isinstance(st, ast.Assign):
        vnames = _names_in(st.value)
        for t in st.targets:
            if isinstance(t, (ast.Attribute, ast.Subscript)) and \
                    (vnames & aliases):
                return aliases, True, False  # stored on an object: escapes
        src = _alias_sources(st.value)
        new = set(aliases)
        for t in st.targets:
            elts = [t] if isinstance(t, ast.Name) else (
                t.elts if isinstance(t, (ast.Tuple, ast.List)) else [])
            for nt in elts:
                if isinstance(nt, ast.Name):
                    if src & aliases:
                        new.add(nt.id)
                    else:
                        new.discard(nt.id)  # rebound away from the handle
        aliases = frozenset(new)
    elif isinstance(st, ast.AugAssign):
        if isinstance(st.target, (ast.Attribute, ast.Subscript)) and \
                (_names_in(st.value) & aliases):
            return aliases, True, False
        if isinstance(st.target, ast.Name) and \
                (_alias_sources(st.value) & aliases):
            aliases = aliases | {st.target.id}
    elif isinstance(st, ast.AnnAssign) and st.value is not None:
        if isinstance(st.target, (ast.Attribute, ast.Subscript)) and \
                (_names_in(st.value) & aliases):
            return aliases, True, False
        if isinstance(st.target, ast.Name):
            new = set(aliases)
            if _alias_sources(st.value) & aliases:
                new.add(st.target.id)
            else:
                new.discard(st.target.id)
            aliases = frozenset(new)
    elif isinstance(st, (ast.For, ast.AsyncFor)):
        if isinstance(st.target, ast.Name) and \
                (_alias_sources(st.iter) & aliases):
            aliases = aliases | {st.target.id}
    elif isinstance(st, ast.Delete):
        new = set(aliases)
        for t in st.targets:
            if isinstance(t, ast.Name):
                new.discard(t.id)
        aliases = frozenset(new)
    return aliases, False, False


def _find_leak(cfg: CFG, site: _Site
               ) -> Optional[Tuple[str, List[Tuple[int, str]]]]:
    """BFS from just after the acquire; first path reaching an exit while
    the obligation is still live wins (shortest witness). Returns
    (exit kind, [(line, note), ...]) or None."""
    seen: Set[Tuple[int, int, FrozenSet[str]]] = set()
    queue: List[Tuple[int, int, FrozenSet[str], tuple]] = [
        (site.bid, site.idx + 1, site.aliases, ())]
    qi = 0
    while qi < len(queue):
        bid, idx, aliases, path = queue[qi]
        qi += 1
        key = (bid, idx, aliases)
        if key in seen:
            continue
        seen.add(key)
        if bid == cfg.raise_exit:
            return "an exception path", list(path)
        if bid == cfg.exit:
            return "a normal path", list(path)
        b = cfg.blocks[bid]
        acq_raises = set(site.pair.get("acquire_raises", ()))

        def infeasible(tgt: int) -> bool:
            # a handler catching ONLY the exception the acquire itself
            # raises on failure can never be entered with the resource
            # held (the acquire raising means nothing was acquired)
            ht = cfg.blocks[tgt].handler_types
            return bool(acq_raises) and ht is not None and \
                all(t.split(".")[-1] in acq_raises for t in ht)

        discharged = False
        i = idx
        while i < len(b.stmts):
            st = b.stmts[i]
            pre = aliases
            aliases, discharged, risky = _apply(st, aliases, site.receiver,
                                                site.pair)
            if _may_raise(st) and (not discharged or risky) and \
                    not isinstance(st, ast.Raise):
                # a Raise statement's flow is the block-end ``raise``
                # edges (typed for a bare re-raise), not the blind
                # block-level except wiring — forking both would send
                # the held state straight past handlers that do catch
                note = (getattr(st, "lineno", site.line),
                        "still held if this statement raises")
                for tgt, kind in b.succs:
                    if kind == "except" and not infeasible(tgt):
                        queue.append((tgt, 0, pre, path + (note,)))
            if discharged:
                break
            i += 1
        if discharged:
            continue
        if b.stmts:
            note = (getattr(b.stmts[-1], "lineno", site.line),
                    "path continues past here")
            out_path = path + (note,)
        else:
            out_path = path
        for tgt, kind in b.succs:
            if kind == "except":
                continue  # mid-statement forks were taken above
            if kind == "raise" and infeasible(tgt):
                continue
            refined = aliases
            if b.stmts and kind in ("true", "false"):
                last = b.stmts[-1]
                if isinstance(last, (ast.If, ast.While)):
                    name, held_on_true = _guard(last.test)
                    if name is not None and name in aliases:
                        if (kind == "true") != held_on_true:
                            continue  # guard proves nothing was acquired
                if kind == "false" and \
                        isinstance(last, (ast.For, ast.AsyncFor)):
                    # loop exit: a loop over the handle has dispensed its
                    # elements to the loop target (per-element obligations
                    # were checked along the body's paths); an empty
                    # collection never held anything
                    srcs = _alias_sources(last.iter) & aliases
                    tnames = {last.target.id} \
                        if isinstance(last.target, ast.Name) else set()
                    if srcs or (tnames & aliases):
                        refined = aliases - srcs - tnames
                        if not refined:
                            continue  # fully dispensed
            queue.append((tgt, 0, refined, out_path))
    return None


def _acquire_pair(expr: ast.AST, acq_last: Dict[str, dict]
                  ) -> Optional[Tuple[ast.Call, dict]]:
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            last = _last_comp(n.func)
            if last is not None and last in acq_last:
                return n, acq_last[last]
    return None


def _collect_sites(cfg: CFG, acq_last: Dict[str, dict]) -> List[_Site]:
    sites: List[_Site] = []
    seen: Set[Tuple[str, int]] = set()

    def add(site: _Site) -> None:
        k = (site.pair["name"], site.line)
        if k not in seen:
            seen.add(k)
            sites.append(site)

    for b in cfg.blocks.values():
        for i, st in enumerate(b.stmts):
            if isinstance(st, (ast.With, ast.AsyncWith)):
                continue  # context-managed: released on all paths
            if isinstance(st, ast.Return):
                continue  # acquired-and-returned: caller owns
            hit = None
            for e in _headers(st):
                hit = _acquire_pair(e, acq_last)
                if hit:
                    break
            if not hit:
                continue
            call, pair = hit
            line = getattr(call, "lineno", getattr(st, "lineno", 1))
            acq = _last_comp(call.func) or "?"
            if pair.get("handleless"):
                if isinstance(call.func, ast.Attribute):
                    add(_Site(pair, b.bid, i, line, frozenset(),
                              _expr_text(call.func.value), acq))
                continue
            if isinstance(st, (ast.For, ast.AsyncFor)):
                if isinstance(st.target, ast.Name):
                    # ``for h in acquire():`` dispenses the collection to
                    # the loop target one element at a time
                    add(_Site(pair, b.bid, i, line,
                              frozenset({st.target.id}), None, acq))
                continue
            if isinstance(st, ast.Assign):
                names: Set[str] = set()
                stored = False
                for t in st.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        names |= {e.id for e in t.elts
                                  if isinstance(e, ast.Name)}
                    else:
                        stored = True  # self.x = alloc(): escapes at birth
                if names and not stored:
                    add(_Site(pair, b.bid, i, line, frozenset(names),
                              None, acq))
                continue
            if isinstance(st, ast.AnnAssign) and \
                    isinstance(st.target, ast.Name):
                add(_Site(pair, b.bid, i, line,
                          frozenset({st.target.id}), None, acq))
                continue
            if isinstance(st, ast.Expr) and st.value is hit[0]:
                # handle-producing acquire whose result is discarded:
                # nothing can ever free it
                add(_Site(pair, b.bid, i, line, frozenset(), None, acq))
            # acquire nested in another call/expression: the surrounding
            # expression takes ownership (argument-pass escape)
    return sites


@register_rule
class ResourceDisciplineRule(ProjectRule):
    name = "resource-discipline"
    description = ("a path (usually an exception edge) on which an acquired "
                   "resource neither reaches its release nor escapes "
                   "ownership")

    def check_project(self, project: Project) -> Iterator[Finding]:
        pairs = [dict(p) for p in project.config.get("resource_pairs", [])]
        if not pairs:
            return
        suffixes = tuple(project.config.get(
            "resource_caller_owns_suffixes", []))
        acq_last: Dict[str, dict] = {}
        exempt_quals: Set[str] = set()
        exempt_classes: Set[str] = set()
        for p in pairs:
            p["_rel_last"] = {s.split(".")[-1] for s in p["release"]}
            for spec in list(p["acquire"]) + list(p["release"]):
                exempt_quals.add(spec)
                if "." in spec:
                    exempt_classes.add(spec.split(".")[0])
            for s in p["acquire"]:
                acq_last[s.split(".")[-1]] = p

        root = project.root or REPO_ROOT
        for s in sorted(project.by_path.values(), key=lambda s: s.path):
            if not any(ev[0] == "acq"
                       for fi in s.functions for ev in fi.resources):
                continue
            path = s.path if os.path.isabs(s.path) else \
                os.path.join(root, s.path)
            try:
                with open(path, encoding="utf-8") as fh:
                    tree = ast.parse(fh.read())
            except (OSError, SyntaxError):
                continue
            for qual, fn_node, cfg in iter_cfgs(tree):
                name = qual.split(".")[-1]
                if suffixes and name.endswith(suffixes):
                    continue  # *_locked convention: caller owns the handle
                if qual in exempt_quals or \
                        qual.split(".")[0] in exempt_classes:
                    continue  # implements the pair itself
                for site in _collect_sites(cfg, acq_last):
                    if s.suppressed(self.name, site.line):
                        continue
                    leak = _find_leak(cfg, site)
                    if leak is None:
                        continue
                    kind, steps = leak
                    related = [{"path": s.path, "line": site.line,
                                "message": f"witness: '{site.acq}()' "
                                           f"acquired here"}]
                    shown = steps if len(steps) <= _PATH_CAP - 2 else \
                        steps[:_PATH_CAP - 3] + [steps[-1]]
                    prev = site.line
                    for line, note in shown:
                        if line != prev:
                            related.append({"path": s.path, "line": line,
                                            "message": f"witness: {note}"})
                            prev = line
                    rel_names = "/".join(sorted(site.pair["_rel_last"]))
                    yield Finding(
                        path=s.path, line=site.line, rule=self.name,
                        message=(
                            f"'{site.pair['name']}' resource acquired via "
                            f"'{site.acq}()' in '{qual}' can reach {kind} "
                            f"out of the function without release "
                            f"('{rel_names}') or ownership transfer — "
                            f"release in a finally/handler or hand the "
                            f"handle off before the path escapes"),
                        related=tuple(related))
