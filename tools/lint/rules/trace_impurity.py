"""trace-impurity: code reachable from a jax trace must be pure.

Functions that jax traces — anything passed to ``jax.jit``, decorated with
it, handed to the eager dispatcher as the op body (second argument of
``apply(name, fn, ...)``), or named in the engine config's ``trace_roots``
— execute ONCE at trace time; whatever they read is baked into the
compiled executable and silently served stale forever after (the exact
class PR 2's flags-epoch fix patched by hand). Inside the trace-reachable
set this rule flags:

* wall-clock / process-state reads: ``time.*``, ``datetime.*``, ``uuid.*``
* unkeyed host randomness: stdlib ``random.*`` and ``np.random.*``
  (``jax.random`` is keyed and trace-safe — not flagged)
* environment reads: ``os.environ`` / ``os.getenv``
* loads of module-level MUTABLE globals (dicts/lists/sets): a mutation
  after compile would not invalidate the baked value. Immutable module
  constants are fine; runtime-settable knobs must go through the
  epoch-keyed flags accessor (``flags.flag()`` — every ``set_flags`` bumps
  ``flags.epoch()``, which the dispatch cache folds into its keys).

Reachability is intra-module by simple name: from each trace root, every
same-module function it calls is scanned too (an over-approximation — a
name shared by a traced and an untraced helper is treated as traced).

Since graft-lint 2.0 this rule is the SAME-MODULE half of the invariant:
the whole-program ``cross-trace-impurity`` rule follows call edges across
module boundaries (import/from-import aliases resolved through the project
call graph) and reports impure reads that only become trace-reachable
through another module. This rule stays registered as the fallback that
needs no project graph — it works on a single file, so scoped runs and
files whose imports cannot be resolved keep their intra-module coverage.
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..astutil import (IMPURE_MODULES, IMPURE_PREFIXES, dotted_name,
                       function_table, module_mutable_globals, path_matches)
from ..engine import FileContext, Rule, register_rule


def _trace_roots(ctx: FileContext):
    """(root function names, inline traced lambdas) for one module."""
    names: Set[str] = set()
    lambdas: List[ast.Lambda] = []

    def grab(arg):
        if isinstance(arg, ast.Name):
            names.add(arg.id)
        elif isinstance(arg, ast.Lambda):
            lambdas.append(arg)

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            fn = node.func
            # jax.jit(fn, ...) / jit(fn, ...)
            if (isinstance(fn, ast.Attribute) and fn.attr == "jit") or \
                    (isinstance(fn, ast.Name) and fn.id == "jit"):
                if node.args:
                    grab(node.args[0])
            # apply("op", fn, ...): the eager dispatcher traces arg 2
            elif isinstance(fn, ast.Name) and fn.id == "apply" \
                    and len(node.args) >= 2:
                grab(node.args[1])
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if "jax.jit" in ast.unparse(dec):
                    names.add(node.name)
    for cfg_path, extra in ctx.config.get("trace_roots", {}).items():
        if path_matches(ctx.path, [cfg_path]):
            names.update(extra)
    return names, lambdas


@register_rule
class TraceImpurityRule(Rule):
    name = "trace-impurity"
    description = ("no clock/randomness/env/mutable-global reads in "
                   "functions jax can trace")

    def check(self, ctx: FileContext):
        roots, lambdas = _trace_roots(ctx)
        if not roots and not lambdas:
            return
        fns = function_table(ctx.tree)
        mutables = module_mutable_globals(ctx.tree)

        reachable: Set[str] = set()
        work = [r for r in roots if r in fns]
        for lam in lambdas:  # helpers called from inline traced lambdas
            for sub in ast.walk(lam):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Name) and sub.func.id in fns:
                    work.append(sub.func.id)
        while work:
            name = work.pop()
            if name in reachable:
                continue
            reachable.add(name)
            for fn in fns[name]:
                for sub in ast.walk(fn):
                    if isinstance(sub, ast.Call) and \
                            isinstance(sub.func, ast.Name) and \
                            sub.func.id in fns:
                        work.append(sub.func.id)

        bodies = [(fn, name) for name in sorted(reachable)
                  for fn in fns[name]]
        bodies += [(lam, "<lambda>") for lam in lambdas]
        for body, name in bodies:
            yield from self._scan_body(ctx, body, name, mutables)

    def _scan_body(self, ctx: FileContext, body, name: str,
                   mutables: Set[str]):
        # locals shadow module globals: a parameter or local assignment
        # named like a mutable global is NOT a global read
        local_names: Set[str] = set()
        args = getattr(body, "args", None)
        if args is not None:
            for a in (args.posonlyargs + args.args + args.kwonlyargs
                      + ([args.vararg] if args.vararg else [])
                      + ([args.kwarg] if args.kwarg else [])):
                local_names.add(a.arg)
        for sub in ast.walk(body):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
                local_names.add(sub.id)

        for sub in ast.walk(body):
            if isinstance(sub, ast.Call):
                dn = dotted_name(sub.func)
                base = dn.split(".")[0]
                if "." in dn and base in IMPURE_MODULES:
                    yield ctx.finding(
                        sub, self.name,
                        f"'{dn}(...)' in trace-reachable '{name}': the "
                        f"result is baked in at trace time (pass it in as "
                        f"an argument, or use jax.random for randomness)")
                elif dn.startswith(IMPURE_PREFIXES) or dn == "os.getenv":
                    yield ctx.finding(
                        sub, self.name,
                        f"'{dn}(...)' in trace-reachable '{name}': the "
                        f"result is baked in at trace time (pass it in as "
                        f"an argument, or use jax.random for randomness)")
            elif isinstance(sub, ast.Attribute) and \
                    dotted_name(sub) == "os.environ":
                yield ctx.finding(
                    sub, self.name,
                    f"'os.environ' read in trace-reachable '{name}': the "
                    f"value is baked in at trace time (read it before the "
                    f"traced call and pass it in)")
            elif isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load) \
                    and sub.id in mutables and sub.id not in local_names:
                yield ctx.finding(
                    sub, self.name,
                    f"module-level mutable global '{sub.id}' read in "
                    f"trace-reachable '{name}': later mutations are "
                    f"silently ignored by compiled executables (make it "
                    f"immutable, pass it as an argument, or route the knob "
                    f"through the epoch-keyed flags accessor)")
