"""host-sync: device→host transfers inside loops.

Each ``.item()``, ``.numpy()``, ``float(tensor)``/``bool(tensor)``/
``int(tensor)`` or ``np.asarray(device_value)`` blocks the Python thread
until the device catches up — inside a loop that serializes every
iteration against the accelerator pipeline (the classic "GPU-bound
training loop that is actually host-bound" bug). This rule flags, inside
``for``/``while`` bodies in library code:

* ``<expr>.item()`` and ``<expr>.numpy()`` calls;
* ``bool/float/int(X)`` and ``np.asarray/np.array(X)`` where ``X``
  mentions a device value — a ``._data`` read (Tensor's backing
  ``jax.Array``) that is not just shape/dtype metadata, or a ``jnp.*``
  call;
* ``bool(X.all())`` / ``bool(X.any())`` — the reduce-then-branch idiom.

Intentional syncs (early-exit decode loops, debug-flag nan checks) get a
pragma or a baseline entry with the reason stating why the sync is the
semantics, not an accident.
"""

from __future__ import annotations

import ast
from typing import List

from ..astutil import dotted_name, mentions_device_value, snippet
from ..engine import FileContext, Rule, register_rule

_NP_CONVERTERS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array"}
_mentions_device_value = mentions_device_value


@register_rule
class HostSyncRule(Rule):
    name = "host-sync"
    description = ("no .item()/.numpy()/float(Tensor)/np.asarray(device "
                   "value) inside loops")

    def check(self, ctx: FileContext):
        findings: List = []
        seen_lines = set()  # one finding per line: bool(np.asarray(x._data)
        #                     .all()) matches two patterns but is one sync

        def flag(node, what):
            if node.lineno in seen_lines:
                return
            seen_lines.add(node.lineno)
            findings.append(ctx.finding(
                node, self.name,
                f"host sync inside a loop: {what} blocks on the device "
                f"every iteration (hoist/batch it, or baseline with the "
                f"reason the sync IS the semantics)"))

        def visit(node, in_loop):
            if isinstance(node, (ast.For, ast.While)):
                in_loop = True
            elif in_loop and isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and not node.args and \
                        f.attr in ("item", "numpy"):
                    flag(node, f"`{snippet(node)}`")
                elif isinstance(f, ast.Name) and f.id in ("bool", "float",
                                                          "int") and \
                        len(node.args) == 1:
                    arg = node.args[0]
                    if _mentions_device_value(arg) or (
                            f.id == "bool" and isinstance(arg, ast.Call)
                            and isinstance(arg.func, ast.Attribute)
                            and arg.func.attr in ("all", "any")):
                        flag(node, f"`{snippet(node)}`")
                elif dotted_name(f) in _NP_CONVERTERS and node.args and \
                        _mentions_device_value(node.args[0]):
                    flag(node, f"`{snippet(node)}`")
            for child in ast.iter_child_nodes(node):
                visit(child, in_loop)

        visit(ctx.tree, False)
        return findings
