"""naked-retry: ad-hoc ``time.sleep`` retry/poll loops belong in
``paddle_tpu/resilience``.

PR 5 centralized failure handling: retry loops ride
``resilience.RetryPolicy`` (jittered backoff, attempt caps, deadline
propagation, counted retries) and poll loops ride
``resilience.jitter_sleep`` (stampede-free cadence). A loop that both
catches exceptions and sleeps is the hand-rolled version of one of those
— invisible to the retry metrics, fixed-cadence (thundering-herd bait),
and deadline-free. The rule flags every ``time.sleep`` call lexically
inside a ``While``/``For`` whose body also contains a ``try/except``,
outside the allowed paths (``retry_allowed_paths`` config, default
``paddle_tpu/resilience``). Deliberate survivors go in the baseline with
a written reason, per the PR-3 convention.

Modules listed in ``poll_loop_paths`` (ISSUE 8: ``paddle_tpu/serving``
— the watchdog poll thread and the drain wait loop; ISSUE 10:
``paddle_tpu/resilience/watchdog.py`` + ``trainer.py``, where the
extracted watchdog and the training supervisor now live) get the STRICT
tier: ANY in-loop ``time.sleep`` is flagged, try/except or not — and
strict OUTRANKS the ``retry_allowed_paths`` exemption, so the watchdog
stays strict inside the resilience package itself. A poll thread that
sleeps on a fixed cadence beats in phase across a fleet of
engines/trainers; ``resilience.jitter_sleep`` is the only sanctioned
poll primitive there.
"""

from __future__ import annotations

import ast

from ..astutil import path_matches
from ..engine import FileContext, Rule, register_rule


def _time_sleep_names(tree: ast.Module):
    """(module-alias names for ``time``, direct names for ``time.sleep``)
    collected from every import in the file (function-body deferred
    imports included — the PS client's ``import time as _time`` idiom)."""
    aliases, sleeps = set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    aliases.add(a.asname or "time")
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name == "sleep":
                    sleeps.add(a.asname or "sleep")
    return aliases, sleeps


def _loop_has_try(loop: ast.AST) -> bool:
    return any(isinstance(n, ast.Try) and n.handlers
               for n in ast.walk(loop))


@register_rule
class NakedRetryRule(Rule):
    name = "naked-retry"
    description = ("time.sleep inside a try/except loop outside "
                   "paddle_tpu/resilience (use RetryPolicy / jitter_sleep)")

    def check(self, ctx: FileContext):
        def _in(paths):
            return any(ctx.path == p or ctx.path.startswith(p + "/")
                       or path_matches(ctx.path, [p]) for p in paths)

        # the strict tier OUTRANKS the retry_allowed exemption: a module in
        # poll_loop_paths stays strict even inside paddle_tpu/resilience
        # (ISSUE 10 — the extracted watchdog and the training supervisor
        # live there, and their poll threads must still ride jitter_sleep)
        strict = _in(ctx.config.get("poll_loop_paths", []))
        if not strict and _in(ctx.config.get("retry_allowed_paths",
                                             ["paddle_tpu/resilience"])):
            return
        aliases, sleeps = _time_sleep_names(ctx.tree)
        if not aliases and not sleeps:
            return
        rule = self.name
        findings = []

        def is_sleep(call: ast.Call) -> bool:
            f = call.func
            if isinstance(f, ast.Attribute) and f.attr == "sleep" and \
                    isinstance(f.value, ast.Name) and f.value.id in aliases:
                return True
            return isinstance(f, ast.Name) and f.id in sleeps

        def visit(node, fn_name, loops):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn_name = node.name
                loops = ()  # a nested def starts its own loop context
            elif isinstance(node, (ast.While, ast.For)):
                loops = loops + (node,)
            elif isinstance(node, ast.Call) and loops and is_sleep(node):
                if any(_loop_has_try(lp) for lp in loops):
                    findings.append(ctx.finding(
                        node, rule,
                        f"ad-hoc `time.sleep` retry/poll loop in "
                        f"'{fn_name or '<module>'}': sleeps inside a "
                        f"try/except loop — use resilience.RetryPolicy "
                        f"for retries or resilience.jitter_sleep for "
                        f"polls (or baseline with the written reason the "
                        f"cadence is deliberate)"))
                elif strict:
                    findings.append(ctx.finding(
                        node, rule,
                        f"fixed-cadence `time.sleep` poll loop in "
                        f"'{fn_name or '<module>'}': this module is in "
                        f"poll_loop_paths — serving-side threads must "
                        f"poll via resilience.jitter_sleep so a fleet of "
                        f"engines never beats in phase"))
            for child in ast.iter_child_nodes(node):
                visit(child, fn_name, loops)

        visit(ctx.tree, None, ())
        return findings
