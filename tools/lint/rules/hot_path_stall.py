"""hot-path-stall: the dispatch fast path must not sleep, take contended
locks, do file/socket IO, or trigger non-warmup jit compiles.

cross-host-sync already rejects device→host transfers reachable from
``fast_path_roots`` — the per-op budget PR 2 bought. This rule extends
the same reachability to the rest of the stall taxonomy carried by the
graft-lint 5.0 blocking events:

* ``sleep`` — any sleep on a dispatch chain is a per-op latency cliff;
* ``lock-acquire`` — only when the lock is CONTENDED (acquired in ≥ 2
  distinct functions project-wide) and not in ``hot_path_lock_exempt``
  (the reviewed short-critical-section locks: program-cache lookups,
  cost-hook bookkeeping);
* ``file-io`` / ``rpc`` / ``subprocess`` — the OS round-trip classes;
* ``jit-compile`` — unless a function named ``*warmup*`` is on the
  chain: deliberate pre-compilation is the point of warmup paths.

Waits (queue/future/condition) are unbounded-wait's domain and locks
held ACROSS blocking work are blocking-under-lock's; this rule is about
what the fast path does at all, not how long it could block.

Suppression: pragma on the stalling line, or a baseline entry whose
reason says the stall is the semantics (debug/bypass seams).
"""

from __future__ import annotations

from typing import Dict, Set

from ..astutil import path_matches
from ..engine import Finding, ProjectRule, register_rule
from .shared_state_race import _chain, _chain_text

_KINDS = ("sleep", "lock-acquire", "file-io", "rpc", "subprocess",
          "jit-compile")


def _contended_locks(project) -> Set[str]:
    """Lock ids acquired (``with <lock>:``) in ≥ 2 distinct functions
    anywhere in the project — the locks a fast-path acquisition can
    actually queue behind."""
    holders: Dict[str, Set] = {}
    for mod in sorted(project.modules):
        for fi in project.modules[mod].functions:
            for lr, _line in fi.acquires:
                lid = project.lock_id(mod, list(lr))
                if lid is not None:
                    holders.setdefault(lid, set()).add((mod, fi.qualname))
    return {lid for lid, fns in holders.items() if len(fns) >= 2}


@register_rule
class HotPathStallRule(ProjectRule):
    name = "hot-path-stall"
    description = ("no sleeps, contended-lock acquisitions, file/socket "
                   "IO, or non-warmup jit compiles reachable from the "
                   "dispatch fast path")

    def check_project(self, project):
        specs = project.config.get("fast_path_roots", [])
        roots = []
        for spec in specs:
            path, _, fname = spec.partition("::")
            for mod in sorted(project.modules):
                s = project.modules[mod]
                if not path_matches(s.path, [path]):
                    continue
                for fi in project.fn_by_simple.get((mod, fname), []):
                    roots.append((mod, fi, f"{mod}.{fname}"))
        if not roots:
            return
        exempt = set(project.config.get("hot_path_lock_exempt", []))
        contended = _contended_locks(project)
        seen: set = set()
        for mod, rfi, label in roots:
            _held, parent = project.reachable_with_locks(mod, rfi)
            chain_memo: Dict = {}
            for node in sorted(parent):
                m, _qn = node
                fi = project.fn_by_qual[node]
                if not fi.blocking:
                    continue
                chain = None
                for ev in fi.blocking:
                    kind, detail, _bounded, _ds, _lrs, recv, line = ev
                    if kind not in _KINDS:
                        continue
                    if kind == "lock-acquire":
                        lid = project.lock_id(m, list(recv)) \
                            if recv is not None else None
                        if lid is None or lid in exempt or \
                                lid not in contended:
                            continue
                        what = f"acquisition of contended lock '{lid}'"
                    else:
                        what = f"{kind} '{detail}'"
                    if chain is None:
                        chain = chain_memo.get(node)
                        if chain is None:
                            chain = _chain(parent, node)
                            chain_memo[node] = chain
                    if kind == "jit-compile" and any(
                            "warmup" in cq.lower() for _cm, cq in chain):
                        continue
                    key = (m, fi.qualname, line, kind)
                    if key in seen:
                        continue
                    seen.add(key)
                    s = project.modules[m]
                    if s.suppressed(self.name, line):
                        continue
                    related = tuple(
                        {"path": project.modules[cm].path,
                         "line": project.fn_by_qual[(cm, cq)].line,
                         "message": f"witness: '{cq}'"}
                        for cm, cq in chain) + (
                        {"path": s.path, "line": line,
                         "message": f"stalls: {what}"},)
                    yield Finding(
                        s.path, line, self.name,
                        f"{what} in '{fi.qualname}' is reachable from "
                        f"the dispatch fast path (root '{label}') "
                        f"[{_chain_text(chain)}]: every op dispatch can "
                        f"pay this stall — move it off the fast path, "
                        f"guard it behind a slow-path branch, or "
                        f"baseline with the reason the stall is the "
                        f"semantics",
                        related=related)
