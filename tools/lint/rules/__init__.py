"""Built-in graft-lint rules; importing this package registers them."""

from . import (  # noqa: F401
    hot_path_import,
    host_sync,
    silent_swallow,
    trace_impurity,
    unguarded_global,
)
