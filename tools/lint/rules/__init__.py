"""Built-in graft-lint rules; importing this package registers them.

Per-file rules see one parsed module; the ``cross_*``/``lock_order``/
``import_layering`` rules are :class:`~tools.lint.engine.ProjectRule`
subclasses and run once per invocation over the whole-program graphs.
"""

from . import (  # noqa: F401
    blocking_under_lock,
    cross_host_sync,
    cross_trace_impurity,
    device_access,
    exception_contract,
    hot_path_import,
    hot_path_stall,
    host_sync,
    import_layering,
    lock_order,
    naked_retry,
    resource_discipline,
    shared_state_race,
    silent_swallow,
    span_discipline,
    trace_impurity,
    unbounded_wait,
    unguarded_global,
)
