"""exception-contract: typed failure surfaces, verified statically.

PR 15 built the serving tier's typed-failure contract by hand: every
exception escaping ``Router.submit``/``Engine.submit`` is mapped by
``http.py::status_for`` (through ``_STATUS_MAP``) to an honest 429/503/504,
and anything unmapped falls to a generic 500. That contract only held
because every raise site had been read. This rule re-derives it on every
lint run: per-function raise-sets (graft-lint 4.0 summaries) are propagated
interprocedurally through the call graph — enclosing try/except handlers
subtract the types they swallow, in CPython handler order, with bare
``except``/``Exception`` widening to everything and re-raising handlers
transparent — and every type that can escape a *declared entry root* must
appear in that root's contract table (``exception_contracts`` in the lint
config, seeded from ``_STATUS_MAP`` and the documented typed surfaces).

A raise added three layers down (say ``kv_cache.py``) that would surface as
an unexplained HTTP 500 becomes a lint finding with a witness call chain,
not a chaos-test postmortem.

Scope/soundness: only explicit ``raise`` statements count (implicit
builtin exceptions — KeyError from a subscript, ZeroDivisionError — are
out of scope); unresolved callees (stdlib, jax) contribute nothing.
Subclass matching uses the summaries' class-base tables plus a small
builtin hierarchy, so a contract naming ``EngineStopped`` also admits
``DrainTimeout`` and catching ``OSError`` subtracts ``ConnectionError``.
"""
from __future__ import annotations

from typing import Dict, Iterable, Iterator, List, Set, Tuple

from ..astutil import path_matches
from ..engine import Finding, ProjectRule, register_rule
from ..wholeprogram.project import Project

_CHAIN_CAP = 8

#: never part of a typed failure surface: assertion-style invariant
#: violations are programming errors that SHOULD crash loudly, not
#: conditions a contract maps to a status code
_ALWAYS_ALLOWED = frozenset({"AssertionError"})

#: the slice of the builtin exception hierarchy this codebase raises/catches
_BUILTIN_BASES: Dict[str, Tuple[str, ...]] = {
    "BrokenPipeError": ("ConnectionError",),
    "ConnectionAbortedError": ("ConnectionError",),
    "ConnectionRefusedError": ("ConnectionError",),
    "ConnectionResetError": ("ConnectionError",),
    "ConnectionError": ("OSError",),
    "TimeoutError": ("OSError",),
    "FileNotFoundError": ("OSError",),
    "FileExistsError": ("OSError",),
    "PermissionError": ("OSError",),
    "IsADirectoryError": ("OSError",),
    "NotADirectoryError": ("OSError",),
    "InterruptedError": ("OSError",),
    "KeyError": ("LookupError",),
    "IndexError": ("LookupError",),
    "ZeroDivisionError": ("ArithmeticError",),
    "OverflowError": ("ArithmeticError",),
    "FloatingPointError": ("ArithmeticError",),
    "RecursionError": ("RuntimeError",),
    "NotImplementedError": ("RuntimeError",),
    "UnicodeDecodeError": ("UnicodeError",),
    "UnicodeEncodeError": ("UnicodeError",),
    "UnicodeError": ("ValueError",),
    "ModuleNotFoundError": ("ImportError",),
}


def _ancestry(project: Project, type_name: str) -> Set[str]:
    """Simple names of ``type_name`` and every base reachable through the
    project class tables and the builtin table."""
    out: Set[str] = set()
    stack = [type_name.split(".")[-1]]
    while stack:
        n = stack.pop()
        if n in out:
            continue
        out.add(n)
        for b in project.class_bases.get(n, ()):
            stack.append(b.split(".")[-1])
        stack.extend(_BUILTIN_BASES.get(n, ()))
    return out


def _caught(project: Project, context: Iterable, type_name: str) -> bool:
    """Does the catch context swallow ``type_name``?

    ``context`` is a list of try-groups innermost-first; each group is the
    ordered handler list ``[[names], swallows]``. Within a group the FIRST
    matching handler decides: swallowing -> caught; transparent (re-raise)
    -> the exception skips the rest of the group and continues outward.
    """
    anc = _ancestry(project, type_name)
    for group in context:
        for names, swallows in group:
            if names == ["*"] or \
                    any(n.split(".")[-1] in anc for n in names):
                if swallows:
                    return True
                break  # transparent: re-raised past this group
    return False


@register_rule
class ExceptionContractRule(ProjectRule):
    name = "exception-contract"
    description = ("an exception type escaping a declared entry root "
                   "(serving/training/RPC surface) is not in that root's "
                   "declared contract")

    def check_project(self, project: Project) -> Iterator[Finding]:
        contracts = project.config.get("exception_contracts", {})
        if not contracts:
            return

        # escaping-set propagation, memoized over the call graph. Values:
        # simple type name -> (full name, witness chain of
        # (module, qualname, line) from the queried function to the raise).
        memo: Dict[Tuple[str, str], Dict[str, tuple]] = {}
        on_stack: Set[Tuple[str, str]] = set()

        def esc(mod: str, fi) -> Dict[str, tuple]:
            key = (mod, fi.qualname)
            if key in memo:
                return memo[key]
            if key in on_stack:   # recursion: cut the cycle conservatively
                return {}
            on_stack.add(key)
            out: Dict[str, tuple] = {}
            for rname, ctx, line in fi.raises:
                t = rname.split(".")[-1]
                if t in out or _caught(project, ctx, t):
                    continue
                out[t] = (rname, ((mod, fi.qualname, line),))
            for dn, ctx, line in fi.call_catches:
                for cm, cfi in project.resolve_call(mod, fi.cls, dn):
                    for t, (full, chain) in esc(cm, cfi).items():
                        if t in out or _caught(project, ctx, t):
                            continue
                        out[t] = (full, ((mod, fi.qualname, line),) + chain)
            on_stack.discard(key)
            memo[key] = out
            return out

        roots: List[tuple] = []
        for s in sorted(project.by_path.values(), key=lambda s: s.path):
            for pat, table in contracts.items():
                if not path_matches(s.path, [pat]):
                    continue
                for spec, allowed in sorted(table.items()):
                    fi = project.fn_by_qual.get((s.module, spec))
                    if fi is not None:
                        roots.append((s, spec, allowed, fi))

        for s, spec, allowed, fi in roots:
            escaping = esc(s.module, fi)
            for t in sorted(escaping):
                full, chain = escaping[t]
                anc = _ancestry(project, t)
                if anc & _ALWAYS_ALLOWED:
                    continue
                if any(a.split(".")[-1] in anc for a in allowed):
                    continue
                raise_mod, raise_qual, raise_line = chain[-1]
                if project.modules[s.module].suppressed(self.name, fi.line):
                    continue
                raise_summary = project.modules.get(raise_mod)
                if raise_summary is not None and \
                        raise_summary.suppressed(self.name, raise_line):
                    continue
                shown = chain if len(chain) <= _CHAIN_CAP else (
                    chain[:_CHAIN_CAP - 1] + (chain[-1],))
                related = tuple(
                    {"path": project.modules[cm].path, "line": cl,
                     "message": f"witness: '{cq}'"}
                    for cm, cq, cl in shown if cm in project.modules)
                yield Finding(
                    path=s.path, line=fi.line, rule=self.name,
                    message=(
                        f"'{full}' raised in '{raise_qual}' can escape the "
                        f"declared entry root '{spec}' but is not in that "
                        f"root's exception contract — catch/map it along "
                        f"the chain, or add it to 'exception_contracts' "
                        f"(and any paired status table) in the same "
                        f"change"),
                    related=related)
