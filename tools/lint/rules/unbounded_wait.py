"""unbounded-wait: every blocking primitive reachable from a serving /
supervisor entry root must be bounded — a timeout argument the local
constant reasoning can see, or a lexical ``resilience.deadline_scope``.

A single untimed ``Future.result()`` / ``queue.get()`` / ``Event.wait()``
/ ``thread.join()`` turns a replica crash into a permanently wedged
supervisor: the caller blocks forever on an event that will never
arrive. The PR 8/10 watchdogs catch that wedge at runtime; this rule
makes it unrepresentable at review time inside the strict tier.

Roots are the declared failure surface (the PR 18 ``exception_contracts``
table — HTTP handlers, ``Router.submit``, ``Engine.submit``/``stop``,
the ps RPC handlers, ``TrainingSupervisor.run``) plus the long-lived
poll threads (``bounded_wait_roots``). Only events inside modules
matching ``bounded_wait_paths`` fire (strict tier mirroring
``poll_loop_paths``): a CLI launcher may wait on its child forever, a
serving thread may not.

An event passes when its boundedness bit is set (literal / env_float-
derived / computed timeout, ``block=False``) or it runs lexically under
``deadline_scope`` (``ds``). ``sleep`` (inherently bounded),
``lock-acquire`` (blocking-under-lock's domain when it matters),
``device-sync``/``jit-compile``/``file-io`` (bounded by the device/OS,
hot-path-stall's concern) are not checked here.

Suppression: pragma on the waiting line, or a baseline entry whose
reason says why the wait must be unbounded (MIGRATING, "Latency
invariants").
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from ..astutil import path_matches
from ..engine import Finding, ProjectRule, register_rule
from .shared_state_race import _chain, _chain_text

_KINDS = ("condition-wait", "queue", "future-wait", "thread-join", "rpc",
          "subprocess")


def _in_paths(path: str, patterns) -> bool:
    """Directory-prefix-aware membership, same idiom as naked-retry's
    poll_loop_paths tier."""
    return any(path == p or path.startswith(p + "/")
               or path_matches(path, [p]) for p in patterns)


def _config_roots(project):
    """(module, FunctionInfo, label) for exception_contracts +
    bounded_wait_roots — the same spec resolution as thread_roots."""
    out = []
    seen = set()

    def add(mod, fi, label):
        node = (mod, fi.qualname)
        if node not in seen:
            seen.add(node)
            out.append((mod, fi, label))

    def add_specs(cfg_path, specs, what):
        for mod in sorted(project.modules):
            s = project.modules[mod]
            if not path_matches(s.path, [cfg_path]):
                continue
            for spec in specs:
                if "." in spec:
                    c2, meth = spec.split(".", 1)
                    fi = project.methods.get((mod, c2, meth))
                    if fi is not None:
                        add(mod, fi, f"{what} '{mod}.{spec}'")
                else:
                    for fi in project.fn_by_simple.get((mod, spec), []):
                        add(mod, fi, f"{what} '{mod}.{spec}'")

    contracts = project.config.get("exception_contracts", {})
    for cfg_path in sorted(contracts):
        add_specs(cfg_path, sorted(contracts[cfg_path]), "entry")
    extra = project.config.get("bounded_wait_roots", {})
    for cfg_path in sorted(extra):
        add_specs(cfg_path, extra[cfg_path], "poll thread")
    return out


@register_rule
class UnboundedWaitRule(ProjectRule):
    name = "unbounded-wait"
    description = ("blocking primitives reachable from serving/supervisor "
                   "roots must carry a timeout or run under "
                   "resilience.deadline_scope (bounded_wait_paths tier)")

    def check_project(self, project):
        strict = project.config.get("bounded_wait_paths", [])
        if not strict:
            return
        seen: set = set()
        for mod, rfi, label in _config_roots(project):
            _held, parent = project.reachable_with_locks(mod, rfi)
            chain_memo: Dict[Tuple[str, str], List] = {}
            for node in sorted(parent):
                m, _qn = node
                fi = project.fn_by_qual[node]
                if not fi.blocking:
                    continue
                s = project.modules[m]
                if not _in_paths(s.path, strict):
                    continue
                for ev in fi.blocking:
                    kind, detail, bounded, ds, _lrs, _recv, line = ev
                    if kind not in _KINDS or bounded or ds:
                        continue
                    key = (m, fi.qualname, line)
                    if key in seen:
                        continue
                    seen.add(key)
                    if s.suppressed(self.name, line):
                        continue
                    chain = chain_memo.get(node)
                    if chain is None:
                        chain = _chain(parent, node)
                        chain_memo[node] = chain
                    related = tuple(
                        {"path": project.modules[cm].path,
                         "line": project.fn_by_qual[(cm, cq)].line,
                         "message": f"witness: '{cq}'"}
                        for cm, cq in chain) + (
                        {"path": s.path, "line": line,
                         "message": f"waits: {kind} '{detail}'"},)
                    yield Finding(
                        s.path, line, self.name,
                        f"unbounded {kind} '{detail}' in '{fi.qualname}' "
                        f"is reachable from {label} "
                        f"[{_chain_text(chain)}]: a peer that never "
                        f"answers wedges this entry point forever — pass "
                        f"a timeout, wrap the call in "
                        f"resilience.deadline_scope, or baseline with "
                        f"the reason the wait must be unbounded",
                        related=related)
