"""device-access: direct ``jax.devices``/``jax.device_put`` calls belong
to the device module and the backend-fallback module only.

PR 6 added backend-fallback dispatch (``paddle_tpu/core/fallback.py``):
per-op placement decisions — which device an op actually executes on —
now have exactly two sanctioned owners: ``paddle_tpu/device.py`` (the
Place taxonomy, ``set_device``, the memoized device-list probes that
``force_platform`` knows how to invalidate) and the fallback module (the
CPU degrade path). An ad-hoc ``jax.devices()``/``jax.device_put`` call
anywhere else bypasses both: it pins placement the fallback registry
can't see, and it can latch a stale device list across a
``force_platform`` switch. Route through ``device.Place``/
``default_jax_device`` or the fallback helpers instead; load-bearing
survivors (the distributed mesh-sharding layer predates this rule) are
grandfathered in the baseline with reasons, per the PR-3 convention.

The rule flags ``jax.devices(...)`` / ``jax.device_put(...)`` attribute
calls (including via ``import jax as <alias>``) and ``from jax import
devices/device_put`` bindings, outside ``device_access_allowed_paths``
(config; default ``paddle_tpu/device.py`` + ``paddle_tpu/core/fallback.py``).
"""

from __future__ import annotations

import ast

from ..astutil import path_matches
from ..engine import FileContext, Rule, register_rule

_CALLEES = ("devices", "device_put")


def _jax_aliases(tree: ast.Module):
    """Names bound to the ``jax`` module by any import in the file."""
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax":
                    aliases.add(a.asname or "jax")
                elif a.name.startswith("jax.") and a.asname is None:
                    # `import jax.numpy` binds the top-level name `jax`
                    aliases.add("jax")
    return aliases


@register_rule
class DeviceAccessRule(Rule):
    name = "device-access"
    description = ("direct jax.devices()/jax.device_put outside "
                   "paddle_tpu/device.py and core/fallback.py (route "
                   "through device.Place or the fallback helpers)")

    def check(self, ctx: FileContext):
        allowed = ctx.config.get("device_access_allowed_paths",
                                 ["paddle_tpu/device.py",
                                  "paddle_tpu/core/fallback.py"])
        if path_matches(ctx.path, allowed):
            return
        aliases = _jax_aliases(ctx.tree)
        rule = self.name
        findings = []
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Attribute)
                    and node.attr in _CALLEES
                    and isinstance(node.value, ast.Name)
                    and node.value.id in aliases):
                # message stays line- and function-free so every use of
                # one callee in a file collapses to a single counted
                # baseline entry (the text report still carries path:line)
                findings.append(ctx.finding(
                    node, rule,
                    f"direct `jax.{node.attr}` — device placement belongs "
                    f"to paddle_tpu/device.py (Place/jax_device) or the "
                    f"backend-fallback module (core/fallback.py); route "
                    f"through those, or baseline with the reason this "
                    f"site must own placement itself"))
            elif isinstance(node, ast.ImportFrom) and node.module == "jax":
                for a in node.names:
                    if a.name in _CALLEES:
                        findings.append(ctx.finding(
                            node, rule,
                            f"`from jax import {a.name}` — device "
                            f"placement belongs to paddle_tpu/device.py "
                            f"or core/fallback.py; route through those, "
                            f"or baseline with the reason this site must "
                            f"own placement itself"))
        return findings
