"""cross-trace-impurity: trace purity across module boundaries.

The per-file ``trace-impurity`` rule stops at the module edge: a traced
function in ``core/tensor.py`` that calls an impure helper imported from
``paddle_tpu/utils/`` looks clean in both files. This rule runs the same
root detection (``jax.jit``, ``apply(name, fn, …)``, configured roots,
inline traced lambdas) but walks the PROJECT call graph, so the helper's
``time.time()`` / unkeyed randomness / ``os.environ`` / mutable-global
read is attributed back to the trace root that bakes it in.

Division of labor (no double reporting):

* functions covered by the per-file rule's own reachability — the
  intra-module simple-name closure from a root in the SAME module — stay
  its findings (it needs no project graph and keeps working on
  scoped/single-file runs, the fallback when resolution fails), even
  when a root in another module ALSO reaches them;
* this rule reports (a) impure reads in functions only reachable from a
  root in ANOTHER module, and (b) ``alias.NAME`` reads of a mutable
  global that LIVES in another module — invisible to any per-file scan
  regardless of where the root is.
"""

from __future__ import annotations

from typing import Set

from ..engine import Finding, ProjectRule, register_rule


def _intra_covered(project, mod: str) -> Set[str]:
    """Simple names the per-file rule's reachability covers in ``mod``:
    the closure of plain-name same-module calls from the module's own
    trace roots (mirrors trace_impurity's worklist)."""
    s = project.modules[mod]
    seen: Set[str] = set()
    work = [n for n in s.trace_roots
            if (mod, n) in project.fn_by_simple]
    while work:
        name = work.pop()
        if name in seen:
            continue
        seen.add(name)
        for fi in project.fn_by_simple[(mod, name)]:
            for dn, _line in fi.calls:
                if "." not in dn and (mod, dn) in project.fn_by_simple:
                    work.append(dn)
    return seen

_ADVICE = ("the value is baked in at trace time and silently served stale "
           "(pass it in as an argument, use jax.random for randomness, or "
           "route the knob through the epoch-keyed flags accessor)")


@register_rule
class CrossTraceImpurityRule(ProjectRule):
    name = "cross-trace-impurity"
    description = ("no clock/randomness/env/mutable-global reads anywhere "
                   "a jax trace can reach, across module boundaries")

    def check_project(self, project):
        roots = []
        for mod in sorted(project.modules):
            s = project.modules[mod]
            for rname in s.trace_roots:
                for fi in project.fn_by_simple.get((mod, rname), []):
                    roots.append((mod, fi, (mod, rname)))
        if not roots:
            return
        reached = project.reachable_from(roots)
        intra_cov = {mod: _intra_covered(project, mod)
                     for mod in {m for m, _q in reached}}
        for (mod, qualname) in sorted(reached):
            root_mod, root_name = reached[(mod, qualname)]
            fi = project.fn_by_qual[(mod, qualname)]
            s = project.modules[mod]
            # the per-file rule owns anything its own intra-module closure
            # reaches, regardless of which root the BFS labeled it with
            cross_root = mod != root_mod and fi.name not in intra_cov[mod]
            root_label = f"{root_mod}.{root_name}"
            for kind, detail, line in fi.impure:
                if kind == "attr":
                    # alias.NAME — flag only when it resolves to a mutable
                    # module global living in ANOTHER project module
                    alias, attr = detail.split(".", 1)
                    target = s.bindings.get(alias)
                    if not target or target == mod or \
                            target not in project.modules:
                        continue
                    if attr not in project.modules[target].mutable_globals:
                        continue
                    yield Finding(
                        s.path, line, self.name,
                        f"mutable global '{target}.{attr}' (another "
                        f"module's) read in '{fi.qualname}', which is "
                        f"trace-reachable from '{root_label}': {_ADVICE}")
                elif cross_root:
                    if kind == "call":
                        yield Finding(
                            s.path, line, self.name,
                            f"'{detail}(...)' in '{fi.qualname}' is "
                            f"trace-reachable from '{root_label}' in "
                            f"another module: {_ADVICE}")
                    elif kind == "environ":
                        yield Finding(
                            s.path, line, self.name,
                            f"'os.environ' read in '{fi.qualname}', which "
                            f"is trace-reachable from '{root_label}' in "
                            f"another module: {_ADVICE}")
                    elif kind == "global":
                        yield Finding(
                            s.path, line, self.name,
                            f"module-level mutable global '{detail}' read "
                            f"in '{fi.qualname}', which is trace-reachable "
                            f"from '{root_label}' in another module: "
                            f"{_ADVICE}")
