"""silent-swallow: an ``except`` whose body is a bare ``pass`` must carry
a signal.

Generalizes the PR 1 rule (then scoped to ``paddle_tpu/distributed/``) to
every scanned file: failure paths that map errors to healthy states with
no comment, log line, or counter are exactly how dropped gradients and
"fresh node" elastic restarts shipped. A swallow is fine when it says why
— an inline comment on the ``except``/``pass`` lines (or a comment-only
line directly below), or an actual logged/counted statement in the body
(which makes it not-a-bare-pass).
"""

from __future__ import annotations

import ast

from ..engine import FileContext, Rule, register_rule

MESSAGE = ("silent `except ...: pass` maps a failure to a healthy state "
           "with no signal (add a justifying comment, a log line, or an "
           "observability counter)")


@register_rule
class SilentSwallowRule(Rule):
    name = "silent-swallow"
    description = ("bare `except: pass` handlers must carry a justifying "
                   "comment or an observable signal")

    def check(self, ctx: FileContext):
        lines = ctx.lines
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not (len(node.body) == 1 and isinstance(node.body[0], ast.Pass)):
                continue
            # window: except line .. pass line, plus trailing comment-only
            # lines (a justification written just below the pass counts)
            lo, hi = node.lineno - 1, node.body[0].lineno
            window = list(lines[lo:hi])
            j = hi
            while j < len(lines) and lines[j].lstrip().startswith("#"):
                window.append(lines[j])
                j += 1
            if not any("#" in ln for ln in window):
                yield ctx.finding(node, self.name, MESSAGE)
