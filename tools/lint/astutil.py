"""Small AST helpers shared by graft-lint rules (stdlib only)."""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

#: module-level assignments of these constructors (or dict/list/set
#: literals) are treated as mutable module state
MUTABLE_CTORS = {"dict", "list", "set", "OrderedDict", "defaultdict",
                 "deque", "Counter"}

#: stdlib modules whose call results are process state, not math — calling
#: them at trace time bakes one sample into the compiled executable
IMPURE_MODULES = {"time", "random", "datetime", "uuid"}
IMPURE_PREFIXES = ("np.random.", "numpy.random.")

#: constructors that create a lock-like object (Condition wraps a Lock)
LOCK_CTORS = {"Lock": "Lock", "RLock": "RLock", "Condition": "Condition"}

#: method names that mutate their receiver in place (shared between the
#: per-file unguarded-global rule and the whole-program race detector)
MUTATORS = {"append", "extend", "insert", "pop", "popitem", "clear",
            "update", "setdefault", "remove", "discard", "add",
            "move_to_end", "appendleft", "extendleft"}

#: constructors whose instances carry their own internal synchronization —
#: calling .set()/.get()/.put()/.clear() on them is thread-safe by design,
#: so ``self.<attr>`` fields holding one are NOT shared mutable state for
#: the race detector (rebinding the field itself still is; only fields
#: assigned nothing but these ctors are exempt)
THREADSAFE_CTORS = {"Event", "Queue", "SimpleQueue", "LifoQueue",
                    "PriorityQueue", "Semaphore", "BoundedSemaphore",
                    "Barrier", "local", "Future"}


def dotted_name(node: ast.AST) -> str:
    """``a.b.c`` for Name/Attribute chains, else ``""``."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def root_name(node: ast.AST) -> Optional[str]:
    """Base ``Name.id`` under any Attribute/Subscript chain, else None."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def module_mutable_globals(tree: ast.Module) -> Set[str]:
    """Names assigned a mutable container at module scope (``__all__``
    excluded: written once at import, read-only after)."""
    out: Set[str] = set()
    for node in tree.body:
        targets: List[ast.Name] = []
        value: Optional[ast.AST] = None
        if isinstance(node, ast.Assign):
            targets = [t for t in node.targets if isinstance(t, ast.Name)]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name) and node.value is not None:
            targets = [node.target]
            value = node.value
        if not targets or value is None:
            continue
        is_mut = isinstance(value, (ast.Dict, ast.List, ast.Set))
        if isinstance(value, ast.Call):
            fname = value.func.attr if isinstance(value.func, ast.Attribute) \
                else getattr(value.func, "id", "")
            is_mut = fname in MUTABLE_CTORS
        if is_mut:
            out.update(t.id for t in targets)
    out.discard("__all__")
    return out


def module_lock_defs(tree: ast.Module) -> Dict[str, str]:
    """Name -> ctor kind for ``threading.Lock()``/``RLock()``/``Condition()``
    assigned at module scope."""
    out: Dict[str, str] = {}
    for node in tree.body:
        targets: List[ast.Name] = []
        value: Optional[ast.AST] = None
        if isinstance(node, ast.Assign):
            targets = [t for t in node.targets if isinstance(t, ast.Name)]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name) and node.value is not None:
            targets = [node.target]
            value = node.value
        if not targets or not isinstance(value, ast.Call):
            continue
        fname = value.func.attr if isinstance(value.func, ast.Attribute) \
            else getattr(value.func, "id", "")
        if fname in LOCK_CTORS:
            for t in targets:
                out[t.id] = LOCK_CTORS[fname]
    return out


def module_lock_names(tree: ast.Module) -> Set[str]:
    """Names assigned ``threading.Lock()``/``RLock()`` at module scope."""
    return {n for n, kind in module_lock_defs(tree).items()
            if kind in ("Lock", "RLock")}


def safe_ctor_in(expr: ast.AST) -> bool:
    """True when ``expr`` constructs one of the internally-synchronized
    stdlib objects (Event/Queue/…) anywhere in its subtree."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            fname = n.func.attr if isinstance(n.func, ast.Attribute) \
                else getattr(n.func, "id", "")
            if fname in THREADSAFE_CTORS:
                return True
    return False


def lock_ctor_in(expr: ast.AST) -> Optional[str]:
    """Lock kind when ``expr`` constructs one anywhere in its subtree
    (covers ``lock if lock is not None else threading.Lock()`` defaults)."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            fname = n.func.attr if isinstance(n.func, ast.Attribute) \
                else getattr(n.func, "id", "")
            if fname in LOCK_CTORS:
                return LOCK_CTORS[fname]
    return None


_META_ATTRS = ("shape", "dtype", "ndim", "size")


def mentions_device_value(expr: ast.AST) -> bool:
    """``._data`` reads (minus pure-metadata ``._data.shape``-style chains)
    or ``jnp.`` / ``jax.numpy.`` calls anywhere in the subtree."""
    meta_only = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in _META_ATTRS \
                and isinstance(node.value, ast.Attribute) \
                and node.value.attr == "_data":
            meta_only.add(id(node.value))
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr == "_data" \
                and id(node) not in meta_only:
            return True
        if isinstance(node, ast.Call):
            dn = dotted_name(node.func)
            if dn.startswith(("jnp.", "jax.numpy.")):
                return True
    return False


def function_table(tree: ast.Module) -> Dict[str, List[ast.AST]]:
    """Simple-name -> FunctionDefs (top-level, methods, and nested)."""
    fns: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fns.setdefault(node.name, []).append(node)
    return fns


def snippet(node: ast.AST, limit: int = 64) -> str:
    s = ast.unparse(node)
    return s if len(s) <= limit else s[:limit - 1] + "…"


def path_matches(path: str, patterns) -> bool:
    """True when repo-relative ``path`` equals a pattern or ends with
    ``/<pattern>`` (so fixture trees rooted elsewhere still match)."""
    return any(path == p or path.endswith("/" + p) for p in patterns)
