"""graft-lint engine: contexts, registry, pragmas, baseline, reporting.

Design notes (mirrors how large-framework CIs structure this):

* One ``FileContext`` per file, parsed once, shared by every rule — rules
  are pure functions of the context and must not mutate it.
* Findings are keyed for baseline purposes by ``(path, rule, message)``
  WITHOUT the line number, so an unrelated edit that shifts lines does not
  invalidate a grandfathered entry; identical findings in one file
  collapse into a single baseline entry with a ``count``.
* Suppression is explicit and greppable: ``# graft-lint: disable=<rule>``
  on the finding's line (or on a comment-only line directly above it), or
  ``# graft-lint: disable-file=<rule>`` anywhere in the file. ``all``
  matches every rule.
"""

from __future__ import annotations

import ast
import json
import os
import re
import subprocess
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# ---------------------------------------------------------------------------
# findings + file context
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Finding:
    path: str          # repo-relative, posix separators
    line: int
    rule: str
    message: str
    # optional structured witness locations ((path, line, message) dicts):
    # rendered as SARIF relatedLocations, indented lines in text, and a
    # "related" list in JSON; excluded from key() so baselines match on
    # the finding alone and an edit that shifts a witness line does not
    # orphan the entry
    related: Tuple = ()

    def key(self) -> Tuple[str, str, str]:
        """Line-free fingerprint used for baseline matching."""
        return (self.path, self.rule, self.message)

    def text(self) -> str:
        out = f"{self.path}:{self.line}: {self.rule} {self.message}"
        for r in self.related:
            out += f"\n    {r['path']}:{r['line']}: {r.get('message', '')}"
        return out

    def as_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"path": self.path, "line": self.line,
                             "rule": self.rule, "message": self.message}
        if self.related:
            d["related"] = [dict(r) for r in self.related]
        return d


class FileContext:
    """One parsed source file handed to every rule."""

    def __init__(self, path: str, source: str, config: Dict[str, Any]):
        self.path = path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.config = config
        self.tree = ast.parse(source)

    def finding(self, node_or_line, rule: str, message: str) -> Finding:
        line = getattr(node_or_line, "lineno", node_or_line)
        return Finding(self.path, int(line), rule, message)


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------


class Rule:
    """Base class: subclass, set ``name``/``description``, implement
    ``check(ctx) -> iterable of Finding``."""

    name: str = ""
    description: str = ""

    def check(self, ctx: FileContext) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError


class ProjectRule(Rule):
    """Whole-program rule: sees every scanned module at once.

    Registered in the same ``RULES`` registry, but instead of ``check``
    (which is a no-op), the engine calls ``check_project`` exactly once
    per run with the assembled :class:`tools.lint.wholeprogram.Project`.
    Findings still name a (path, line) — suppression pragmas and the
    baseline apply unchanged. Under ``--changed-only`` project rules keep
    analyzing the FULL tree (an edit in one file can create a finding in
    another); the summary cache makes that cheap.
    """

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def check_project(self, project) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError


RULES: Dict[str, Rule] = {}


def register_rule(cls):
    """Class decorator: instantiate + register a rule by its ``name``."""
    inst = cls()
    if not inst.name:
        raise ValueError(f"rule {cls.__name__} has no name")
    RULES[inst.name] = inst
    return cls


# ---------------------------------------------------------------------------
# default configuration
# ---------------------------------------------------------------------------

DEFAULT_CONFIG: Dict[str, Any] = {
    # directories (repo-relative) scanned when the CLI gets no paths
    "default_paths": ["paddle_tpu"],
    # hot-path-import: modules whose function bodies must not import
    "hot_path_modules": [
        "paddle_tpu/core/tensor.py",
        "paddle_tpu/core/dispatch_cache.py",
        "paddle_tpu/core/autograd.py",
    ],
    # trace-impurity: extra per-file trace roots beyond the auto-detected
    # ``jax.jit(fn)`` / ``@jax.jit`` / ``apply(name, fn, ...)`` seams
    "trace_roots": {
        "paddle_tpu/core/tensor.py": ["_build_pure_fn"],
    },
    # unguarded-global: functions whose NAME ends with one of these
    # suffixes are assumed to run with the module lock already held by
    # their caller (the ``_locked`` convention used across core/)
    "lock_held_suffixes": ["_locked"],
    # shared-state-race (ISSUE 14): thread roots the spawn-site discovery
    # cannot see — public entry points that run on CALLER threads, and
    # callback seams (stream callbacks fire on the engine step thread,
    # Future resolution on whatever thread completes it). Discovery
    # handles threading.Thread(target=…)/Timer and ThreadingHTTPServer
    # handlers by itself; list here only what runs on OTHER threads.
    "thread_roots": {
        # any caller thread: submit/cancel/stop race the step loop thread
        "paddle_tpu/serving/engine.py": [
            "Engine.submit", "Engine.cancel", "Engine.stop"],
        # ISSUE 15: the router's public surface runs on caller threads
        # while its health-poll thread (Router._poll_loop — also listed so
        # the root survives a spawn-site refactor) hedges and the replica
        # engines' step threads resolve Futures into _on_replica_done;
        # the stream-counting callback fires on the engine step thread
        "paddle_tpu/serving/router.py": [
            "Router.submit", "Router.cancel", "Router.stop",
            "Router.drain_replica", "Router.restore_replica",
            "Router._on_replica_done", "Router._poll_loop"],
        # ISSUE 17: the prefix index + refcount table are shared mutable
        # state with THREE writer/reader populations — the engine step
        # thread (admission acquires/publishes, completion frees), router
        # caller threads (Replica.prefix_depth walks prefix_summary during
        # placement), and offline bench/test drivers; every access must be
        # dominated by the pool lock, so the public sharing surface is
        # rooted explicitly and survives spawn-site refactors
        "paddle_tpu/serving/kv_cache.py": [
            "PagedKVCache.alloc", "PagedKVCache.free",
            "PagedKVCache.acquire_prefix", "PagedKVCache.peek_prefix_pages",
            "PagedKVCache.publish", "PagedKVCache.prefix_summary",
            "PagedKVCache.prefix_stats"],
        # ISSUE 20: the fleet tier — supervisor public surface runs on
        # caller threads while the monitor thread respawns/latches and
        # per-request reader threads resolve Futures; the worker-side
        # _srv_* handlers run on connection handler threads against the
        # engine step thread (listed explicitly: ThreadingTCPServer
        # handler discovery is best-effort, the roots must survive it)
        "paddle_tpu/serving/fleet.py": [
            "FleetSupervisor.start", "FleetSupervisor.stop",
            "FleetSupervisor.submit", "FleetSupervisor.drain_worker",
            "FleetSupervisor._monitor_loop",
            "RemoteEngine.submit", "RemoteEngine.cancel",
            "RemoteEngine.stop", "RemoteEngine.beat",
            "RemoteEngine._read_stream"],
        "paddle_tpu/serving/fleet_worker.py": [
            "_Handler.handle", "_srv_submit", "_srv_cancel",
            "_srv_withdraw", "_srv_drain", "_srv_beat", "main"],
        # the step/train thread arms and disarms around the compiled call
        # while the poll daemon classifies the window
        "paddle_tpu/resilience/watchdog.py": [
            "StepWatchdog.arm", "StepWatchdog.disarm", "StepWatchdog.stop"],
        # engine construction / supervisor run call the opt-in seam while
        # scrape threads serve /metrics; ServerHost.close (the scaffolding
        # shared with the serving front door, ISSUE 15) runs on whatever
        # thread shuts an endpoint down
        "paddle_tpu/observability/http.py": ["maybe_serve_from_env",
                                             "ServerHost.close"],
        # the training thread saves and waits while async commit threads
        # rotate the latest pointer
        "paddle_tpu/distributed/checkpoint/__init__.py": [
            "save_state_dict", "wait_async_saves"],
        # worker threads push/pull against the same client whose async
        # drain daemon replays; close() races the drain
        "paddle_tpu/distributed/ps_service.py": [
            "PsClient.push", "PsClient.push_sparse", "PsClient.close"],
        # the trainer consumes batches while the prefetch thread produces
        "paddle_tpu/io/__init__.py": [
            "DataLoader._thread_prefetch", "DataLoader._native_prefetch"],
    },
    # naked-retry: the module(s) allowed to own raw sleep-in-retry-loop
    # mechanics — everything else routes through their policies
    "retry_allowed_paths": ["paddle_tpu/resilience"],
    # naked-retry strict tier: modules where ANY in-loop time.sleep is a
    # finding (not just try/except loops) — poll threads (the step
    # watchdog, drain waits, the training supervisor's loops) must use
    # resilience.jitter_sleep. Strict outranks retry_allowed_paths, so
    # the extracted watchdog stays strict inside paddle_tpu/resilience.
    "poll_loop_paths": [
        "paddle_tpu/serving",
        # ISSUE 15: the HTTP tier is covered by the package prefix above;
        # named explicitly so the strict-tier membership survives a
        # package split (pinned in test_lint_wholeprogram.py)
        "paddle_tpu/serving/http.py",
        "paddle_tpu/serving/router.py",
        # ISSUE 20: the fleet supervisor/worker loops, same convention
        "paddle_tpu/serving/fleet.py",
        "paddle_tpu/serving/fleet_worker.py",
        "paddle_tpu/resilience/watchdog.py",
        "paddle_tpu/resilience/trainer.py",
    ],
    # device-access: the only modules allowed to call jax.devices /
    # jax.device_put directly — the Place taxonomy and the backend-
    # fallback dispatcher (PR 6); everything else routes through them
    "device_access_allowed_paths": [
        "paddle_tpu/device.py",
        "paddle_tpu/core/fallback.py",
    ],
    # cross-host-sync: whole-program reachability roots of the eager
    # dispatch fast path ("<path>::<function simple name>"): anything a
    # dispatch can reach pays its host syncs once per op
    "fast_path_roots": [
        "paddle_tpu/core/tensor.py::apply",
        "paddle_tpu/core/tensor.py::_apply_impl",
        # ISSUE 11: the captured-step entry — a host sync reachable from
        # here stalls every TRAIN STEP of the compiled fast path (the
        # eager-tier loss read lives behind the bypass seam and is
        # baselined as the debug semantics)
        "paddle_tpu/core/step_capture.py::__call__",
        # ISSUE 13: the paged-attention decode entry — the kernel launch
        # is pure-functional; a host sync reachable from here would stall
        # every serving decode STEP (per token, per layer)
        "paddle_tpu/ops/paged_attention.py::paged_decode_attention",
        # ISSUE 16: the cost-registry hook call-sites — fired from the
        # dispatch fast path and the captured-step/serving build paths;
        # a host sync reachable from either would turn one-time compile
        # accounting into a per-dispatch stall
        "paddle_tpu/observability/cost.py::_on_static_build",
        "paddle_tpu/observability/cost.py::_on_dispatch_event",
    ],
    # span-discipline (ISSUE 12): the tracing implementation module (the
    # one place manual event emission is legal), and the fast-path modules
    # where span construction must hide behind an enabled() guard — the
    # same set that hosts fast_path_roots
    "span_impl_paths": ["paddle_tpu/observability/trace.py"],
    "span_hot_modules": [
        "paddle_tpu/core/tensor.py",
        "paddle_tpu/core/dispatch_cache.py",
        "paddle_tpu/core/autograd.py",
        "paddle_tpu/core/step_capture.py",
        # ISSUE 16: the cost hooks run inside the dispatch/build paths —
        # any trace emission here must hide behind an enabled() guard so
        # PADDLE_TPU_COST=off (and disabled obs) stays zero-overhead
        "paddle_tpu/observability/cost.py",
    ],
    # import-layering: the declared layer DAG, base layers first; a module
    # may (module-scope) import same-or-lower layers only. Matching is by
    # most-specific prefix, so the bare package in the top layer covers
    # the root __init__ without swallowing the rest.
    "import_layers": [
        {"name": "foundation", "prefixes": [
            "paddle_tpu.version", "paddle_tpu.flags", "paddle_tpu.device",
            "paddle_tpu.sysconfig", "paddle_tpu._native",
            "paddle_tpu.observability", "paddle_tpu.resilience"]},
        {"name": "core", "prefixes": [
            "paddle_tpu.core", "paddle_tpu.autograd", "paddle_tpu.framework",
            "paddle_tpu.profiler", "paddle_tpu.utils", "paddle_tpu.amp",
            "paddle_tpu.ops", "paddle_tpu.tensor", "paddle_tpu.jit"]},
        {"name": "api", "prefixes": [
            "paddle_tpu.nn", "paddle_tpu.optimizer", "paddle_tpu.regularizer",
            "paddle_tpu.io", "paddle_tpu.metric", "paddle_tpu.distribution",
            "paddle_tpu.linalg", "paddle_tpu.fft", "paddle_tpu.signal",
            "paddle_tpu.sparse", "paddle_tpu.geometric",
            "paddle_tpu.quantization", "paddle_tpu.text", "paddle_tpu.audio",
            "paddle_tpu.flops_counter", "paddle_tpu.vision",
            "paddle_tpu.serving",
            # ISSUE 20: the rpc transport is a leaf over foundation only
            # (resilience + stdlib at module scope); the serving fleet
            # tier shares its framing with the distributed tier above, so
            # the SUBMODULE sits in the api layer (most-specific prefix
            # wins) while the rest of paddle_tpu.distributed stays higher
            "paddle_tpu.distributed.rpc"]},
        {"name": "distributed", "prefixes": ["paddle_tpu.distributed"]},
        {"name": "apps", "prefixes": [
            "paddle_tpu.hapi", "paddle_tpu.models", "paddle_tpu.incubate",
            "paddle_tpu.static", "paddle_tpu.inference", "paddle_tpu.onnx",
            "paddle_tpu.hub", "paddle_tpu"]},
    ],
    # exception-contract (ISSUE 18): the declared failure surface of every
    # entry root — path pattern -> {qualname: [allowed exception types]}.
    # A type is allowed if ANY listed name is among its ancestors, so
    # "EngineStopped" admits DrainTimeout and "ConnectionError" admits
    # BreakerOpen/NoHealthyReplica. The serving tables are the lint-side
    # mirror of http.py::_STATUS_MAP: adding a typed exception to one
    # without the other is a finding (MIGRATING, "Failure-surface
    # invariants").
    "exception_contracts": {
        "paddle_tpu/serving/http.py": {
            # the HTTP handlers map EVERYTHING through _STATUS_MAP; a raise
            # escaping them tears down the connection thread instead of
            # answering, so their contract is empty
            "_Handler.do_GET": [],
            "_Handler.do_POST": [],
        },
        "paddle_tpu/serving/router.py": {
            "Router.submit": [
                "QueueFull", "DeadlineExceeded", "EngineStopped",
                "NoHealthyReplica", "ConnectionError", "ValueError",
                # ISSUE 20: a fleet worker dying before admission — named
                # explicitly (its ConnectionError base already admits it)
                # because it is a distinct row in http.py::_STATUS_MAP
                "RpcTransportError",
            ],
        },
        # ISSUE 20: the fleet tier's failure surfaces. The worker-side
        # _srv_* handlers mirror the PS service convention (a raise is
        # serialized back as a typed envelope); the supervisor's start is
        # the spawn-failure surface.
        "paddle_tpu/serving/fleet.py": {
            "FleetSupervisor.start": [
                "FleetWorkerLost", "ValueError", "OSError",
            ],
        },
        "paddle_tpu/serving/fleet_worker.py": {
            "_srv_submit": [
                "QueueFull", "DeadlineExceeded", "EngineStopped",
                "ValueError", "OSError",
                # rpc.send_msg raises RuntimeError on a missing/empty
                # secret — a misconfigured worker, mapped 500-equivalent
                "RuntimeError",
            ],
            "_srv_cancel": [],
            "_srv_withdraw": [],
            "_srv_drain": ["DrainTimeout", "ValueError", "RuntimeError"],
            "_srv_prefix_summary": [],
            "_srv_beat": [],
        },
        "paddle_tpu/serving/engine.py": {
            "Engine.submit": [
                "QueueFull", "DeadlineExceeded", "EngineStopped",
                "ValueError",
            ],
            # stop() raises on caller mistakes (bad on_timeout, calling
            # from the step thread) besides the documented DrainTimeout
            "Engine.stop": ["DrainTimeout", "ValueError", "RuntimeError"],
        },
        "paddle_tpu/distributed/ps_service.py": {
            # RPC service handlers: a raise here is serialized back to the
            # client as a typed error envelope; KeyError covers unknown
            # table names (mapped, not a transport fault)
            "_srv_create": ["KeyError", "ValueError"],
            "_srv_push": ["KeyError", "ValueError"],
            "_srv_pull": ["KeyError", "ValueError"],
            "_srv_stats": [],
            "_srv_table_snapshot": ["KeyError", "ValueError"],
            "_srv_create_sparse": ["KeyError", "ValueError"],
            "_srv_push_sparse": ["KeyError", "ValueError"],
            "_srv_pull_sparse": ["KeyError", "ValueError"],
            "_srv_shrink": ["KeyError", "ValueError"],
            "_srv_sparse_rows": ["KeyError", "ValueError"],
            "_srv_save": ["KeyError", "ValueError", "OSError"],
            "_srv_load": ["KeyError", "ValueError", "OSError"],
        },
        "paddle_tpu/resilience/trainer.py": {
            "TrainingSupervisor.run": [
                "TrainAborted", "NonFiniteLossError", "ValueError",
            ],
        },
    },
    # resource-discipline (ISSUE 18): acquire/release pairs whose pairing
    # is verified per CFG path. "transfer" names callees that take over
    # the obligation; "handleless" pairs match acquire/release on the
    # receiver expression instead of a handle variable.
    "resource_pairs": [
        {"name": "kv-pages",
         "acquire": ["PagedKVCache.alloc", "PagedKVCache.acquire_prefix"],
         "release": ["PagedKVCache.free"],
         # publish() moves pages into the shared prefix index (refcounted
         # there); admission hands them to the slot table
         "transfer": ["publish"]},
        {"name": "sched-pending",
         "acquire": ["Scheduler.next_admissions", "Scheduler.drain_queue"],
         "release": ["Scheduler.requeue"],
         # popped requests are discharged by resolving their futures
         # (error or result); _admit_one takes ownership only on
         # successful return, so its exception edge still holds the batch
         "transfer": ["set_exception", "set_result"],
         "fork_transfers": ["_admit_one"]},
        {"name": "breaker-probe",
         "acquire": ["CircuitBreaker.before_call"],
         "release": ["CircuitBreaker.record_success",
                     "CircuitBreaker.record_failure"],
         "handleless": True,
         # before_call raises BreakerOpen INSTEAD of taking the probe, so
         # a handler catching only BreakerOpen can never hold one
         "acquire_raises": ["BreakerOpen"]},
    ],
    # functions whose name ends with one of these own no obligations of
    # their own — the caller holds the handle (mirrors lock_held_suffixes)
    "resource_caller_owns_suffixes": ["_locked"],
    # unbounded-wait (ISSUE 19): the strict tier mirroring poll_loop_paths
    # — modules where every blocking primitive reachable from the serving/
    # supervisor entry roots must be bounded by a timeout argument or run
    # lexically under resilience.deadline_scope. A wedge inside these is a
    # permanently hung request/supervisor, exactly what the PR 8/10
    # watchdogs exist to paper over at runtime.
    "bounded_wait_paths": [
        "paddle_tpu/serving",
        # named explicitly so the strict-tier membership survives a
        # package split (same convention as poll_loop_paths)
        "paddle_tpu/serving/http.py",
        "paddle_tpu/serving/router.py",
        # ISSUE 20: the fleet supervisor/worker, same convention
        "paddle_tpu/serving/fleet.py",
        "paddle_tpu/serving/fleet_worker.py",
        "paddle_tpu/resilience/watchdog.py",
        "paddle_tpu/resilience/trainer.py",
        "paddle_tpu/distributed/ps_service.py",
    ],
    # unbounded-wait roots beyond the exception_contracts table: the
    # long-lived poll threads whose wedge a bounded wait is supposed to
    # make impossible (path -> ["Class.method", "fn"])
    "bounded_wait_roots": {
        "paddle_tpu/serving/router.py": ["Router._poll_loop"],
        # ISSUE 20: the fleet monitor thread and the worker's main
        # wait-for-SIGTERM loop
        "paddle_tpu/serving/fleet.py": ["FleetSupervisor._monitor_loop"],
        "paddle_tpu/serving/fleet_worker.py": ["main"],
        "paddle_tpu/resilience/watchdog.py": ["StepWatchdog._loop"],
    },
    # hot-path-stall: contended locks the dispatch fast path legitimately
    # takes — short critical sections by design, reviewed; everything else
    # acquired on the fast path AND somewhere off it is a stall finding
    "hot_path_lock_exempt": [
        # program-cache lookup/insert: dict ops only, never held across
        # build/compile (PR 5 moved builds outside the lock)
        "paddle_tpu.core.dispatch_cache._LOCK",
        # fallback decision memo: dict get/set only
        "paddle_tpu.core.fallback._LOCK",
        # capture-cache lookup: dict ops only, compile happens outside
        "paddle_tpu.core.step_capture._LOCK",
        # cost-registry hooks: RLock around dict bookkeeping only (PR 16
        # pinned zero-overhead-when-disabled)
        "paddle_tpu.observability.cost._LOCK",
    ],
}


# ---------------------------------------------------------------------------
# suppression pragmas
# ---------------------------------------------------------------------------

_PRAGMA_RE = re.compile(
    r"#\s*graft-lint:\s*(disable|disable-file)\s*=\s*([A-Za-z0-9_,\- ]+)")


def _pragma_tables(lines: Sequence[str]) -> Tuple[Dict[int, set], set]:
    """(line -> suppressed rule names, file-level suppressed names)."""
    per_line: Dict[int, set] = {}
    file_level: set = set()
    pending: set = set()  # from comment-only lines, applies to next code line
    for i, raw in enumerate(lines, start=1):
        m = _PRAGMA_RE.search(raw)
        stripped = raw.strip()
        is_comment_only = stripped.startswith("#")
        if m:
            names = {n.strip() for n in m.group(2).split(",") if n.strip()}
            if m.group(1) == "disable-file":
                file_level |= names
            elif is_comment_only:
                pending |= names
            else:
                per_line.setdefault(i, set()).update(names)
        elif stripped and not is_comment_only:
            if pending:
                per_line.setdefault(i, set()).update(pending)
                pending = set()
    return per_line, file_level


def _suppressed(f: Finding, per_line: Dict[int, set], file_level: set) -> bool:
    names = per_line.get(f.line, set()) | file_level
    return f.rule in names or "all" in names


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

BASELINE_VERSION = 1


def default_baseline_path() -> str:
    return os.path.join(REPO_ROOT, "tools", "lint", "baseline.json")


def load_baseline(path: Optional[str]) -> List[Dict[str, Any]]:
    if path is None or not os.path.exists(path):
        return []
    with open(path) as f:
        data = json.load(f)
    return list(data.get("entries", []))


def match_baseline(findings: Sequence[Finding],
                   entries: Sequence[Dict[str, Any]]
                   ) -> Tuple[List[Finding], List[Finding], List[Dict[str, Any]]]:
    """Split ``findings`` into (new, baselined) and report stale entries.

    An entry ``{path, rule, message, count}`` absorbs up to ``count``
    findings with the same (path, rule, message); an entry that absorbs
    fewer than ``count`` is stale (the code improved — prune it with
    ``--update-baseline``).
    """
    budget: Dict[Tuple[str, str, str], int] = {}
    for e in entries:
        k = (e["path"], e["rule"], e["message"])
        budget[k] = budget.get(k, 0) + int(e.get("count", 1))
    remaining = dict(budget)
    new: List[Finding] = []
    baselined: List[Finding] = []
    for f in findings:
        k = f.key()
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
            baselined.append(f)
        else:
            new.append(f)
    stale = []
    for e in entries:
        k = (e["path"], e["rule"], e["message"])
        if remaining.get(k, 0) > 0:
            stale.append(dict(e, unused=remaining[k]))
            remaining[k] = 0  # report duplicates of the same key once
    return new, baselined, stale


def update_baseline(findings: Sequence[Finding],
                    old_entries: Sequence[Dict[str, Any]]
                    ) -> List[Dict[str, Any]]:
    """Regenerate baseline entries from the CURRENT findings, preserving
    the human-written ``reason`` of any surviving entry. New entries get a
    TODO reason on purpose: grandfathering must be a reviewed diff, not a
    silent flag-flip."""
    reasons = {(e["path"], e["rule"], e["message"]): e.get("reason", "")
               for e in old_entries}
    grouped: Dict[Tuple[str, str, str], int] = {}
    for f in findings:
        grouped[f.key()] = grouped.get(f.key(), 0) + 1
    entries = []
    for (path, rule, message), count in sorted(grouped.items()):
        entries.append({
            "path": path, "rule": rule, "message": message, "count": count,
            "reason": reasons.get((path, rule, message))
            or "TODO: justify this grandfathered finding",
        })
    return entries


def save_baseline(path: str, entries: Sequence[Dict[str, Any]]) -> None:
    with open(path, "w") as f:
        json.dump({"version": BASELINE_VERSION, "entries": list(entries)},
                  f, indent=2, sort_keys=True)
        f.write("\n")


# ---------------------------------------------------------------------------
# running
# ---------------------------------------------------------------------------


@dataclass
class LintResult:
    new: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    stale: List[Dict[str, Any]] = field(default_factory=list)
    files_checked: int = 0
    scanned: List[str] = field(default_factory=list)  # per-file pass paths
    #                         (successfully checked only — a file that failed
    #                          to read/parse is NOT "seen", so baseline
    #                          regeneration cannot prune its entries)
    selection: List[str] = field(default_factory=list)  # full selection
    #                         (what the whole-program pass covers, even when
    #                          --changed-only narrowed the per-file pass)
    failed_files: List[str] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)
    # incremental-run bookkeeping (graft-lint 2.0)
    total_files: int = 0          # files in project scope (incl. unscanned)
    parsed_files: int = 0         # files actually parsed this run
    findings_cache_hits: int = 0  # per-file passes served from cache
    summary_cache_hits: int = 0   # project summaries served from cache
    cache_enabled: bool = False
    changed_only: bool = False    # git narrowing actually applied
    run_seconds: float = 0.0

    @property
    def clean(self) -> bool:
        return not self.new and not self.errors

    def as_dict(self) -> Dict[str, Any]:
        counts: Dict[str, int] = {}
        for f in self.new:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return {
            "files_checked": self.files_checked,
            "findings": [f.as_dict() for f in self.new],
            "baselined": len(self.baselined),
            "stale_baseline_entries": self.stale,
            "counts_by_rule": counts,
            "errors": self.errors,
            "clean": self.clean,
            "run_seconds": round(self.run_seconds, 4),
            "changed_only": self.changed_only,
            "cache": {
                "enabled": self.cache_enabled,
                "total_files": self.total_files,
                "parsed_files": self.parsed_files,
                "findings_hits": self.findings_cache_hits,
                "summary_hits": self.summary_cache_hits,
            },
        }


def iter_python_files(paths: Sequence[str], root: str = REPO_ROOT
                      ) -> List[str]:
    """Expand files/directories into a sorted list of absolute .py paths."""
    out = []
    for p in paths:
        absp = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isdir(absp):
            for dirpath, dirnames, filenames in os.walk(absp):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                for fn in filenames:
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
        elif absp.endswith(".py"):
            out.append(absp)
    return sorted(set(out))


def _git_changed_files(root: str, base: str = "main") -> Optional[Set[str]]:
    """Repo-relative paths changed vs ``git merge-base HEAD <base>`` plus
    untracked files; None when git (or the merge base) is unavailable, in
    which case the caller falls back to a full run."""
    def git(*args):
        return subprocess.run(["git", *args], cwd=root, capture_output=True,
                              text=True, timeout=30)
    try:
        mb = git("merge-base", "HEAD", base)
        if mb.returncode != 0:
            return None
        diff = git("diff", "--name-only", mb.stdout.strip())
        if diff.returncode != 0:
            return None
        changed = {ln for ln in diff.stdout.splitlines() if ln}
        untracked = git("ls-files", "--others", "--exclude-standard")
        if untracked.returncode == 0:
            changed |= {ln for ln in untracked.stdout.splitlines() if ln}
        return changed
    except (OSError, subprocess.SubprocessError):
        return None


def _parallel_scan_worker(payload):
    """ProcessPoolExecutor worker for ``--jobs``: parse one file, run the
    per-file rules, build the project summary. Returns plain dicts only
    (picklable); the PARENT merges results in deterministic serial order,
    so parallel findings are byte-identical to a serial run."""
    rel, src, cfg, rule_names = payload
    import tools.lint.rules  # noqa: F401  (register under spawn start)
    from .wholeprogram.summary import build_summary
    out: Dict[str, Any] = {"rel": rel, "error": None, "findings": {},
                           "summary": None}
    try:
        ctx = FileContext(rel, src, cfg)
    except SyntaxError as e:
        out["error"] = f"{rel}: {e.__class__.__name__}: {e}"
        return out
    per_line, file_level = _pragma_tables(ctx.lines)
    for name in rule_names:
        rule = RULES[name]
        fs = [f for f in (rule.check(ctx) or ())
              if not _suppressed(f, per_line, file_level)]
        out["findings"][name] = [f.as_dict() for f in fs]
    out["summary"] = build_summary(rel, ctx.tree, ctx.lines, cfg).to_dict()
    return out


def run_lint(paths: Optional[Sequence[str]] = None,
             rules: Optional[Sequence[str]] = None,
             config: Optional[Dict[str, Any]] = None,
             baseline_entries: Optional[Sequence[Dict[str, Any]]] = None,
             root: str = REPO_ROOT,
             changed_only: bool = False,
             diff_base: str = "main",
             cache_path: Optional[str] = None,
             jobs: int = 1) -> LintResult:
    """Run the engine. ``paths`` may be absolute or ``root``-relative;
    findings always report ``root``-relative paths.

    ``changed_only`` narrows the per-file pass to files changed vs the
    merge base with ``diff_base`` (full run when not in a git repo);
    whole-program rules always analyze the full selection, served from
    the summary cache. ``cache_path`` enables the content-hash cache —
    per-file findings and project summaries keyed by file sha, so warm
    runs skip parsing.

    ``jobs > 1`` fans the COLD work (parse + per-file rules + summary
    build for cache-miss files) over a process pool; cache-hit files and
    the whole-program pass stay on the serial path, results are merged
    in the serial order, and any pool failure falls back to serial — so
    findings are byte-identical to ``jobs=1`` and the warm-cache path is
    untouched.
    """
    t_start = time.perf_counter()
    cfg = dict(DEFAULT_CONFIG)
    if config:
        cfg.update(config)
    if paths is None:
        paths = cfg["default_paths"]
    active = [RULES[n] for n in (rules or sorted(RULES))]
    file_rules = [r for r in active if not isinstance(r, ProjectRule)]
    project_rules = [r for r in active if isinstance(r, ProjectRule)]

    result = LintResult()
    all_files = iter_python_files(paths, root=root)
    rels = {p: os.path.relpath(p, root).replace(os.sep, "/")
            for p in all_files}
    result.total_files = len(all_files)
    result.selection = [rels[p] for p in all_files]

    changed: Optional[Set[str]] = None
    if changed_only:
        changed = _git_changed_files(root, diff_base)
        result.changed_only = changed is not None
    scan_files = all_files if changed is None \
        else [p for p in all_files if rels[p] in changed]

    from .wholeprogram.cache import SummaryCache, content_sha
    cache = None
    if cache_path:
        cache = SummaryCache.load(
            cache_path, cfg, [r.name for r in RULES.values()], root)
        result.cache_enabled = True

    sources: Dict[str, Tuple[str, str]] = {}   # rel -> (sha, src)
    contexts: Dict[str, FileContext] = {}      # rel -> parsed ctx

    def read(abspath: str, rel: str) -> Tuple[str, str]:
        if rel not in sources:
            with open(abspath, encoding="utf-8") as f:
                src = f.read()
            sources[rel] = (content_sha(src), src)
        return sources[rel]

    def parse(rel: str, src: str) -> FileContext:
        if rel not in contexts:
            contexts[rel] = FileContext(rel, src, cfg)
            result.parsed_files += 1
        return contexts[rel]

    findings: List[Finding] = []
    failed: Set[str] = set()

    # ---- optional parallel cold pass (--jobs) ----
    # fan the cache-miss files (per-file pass AND summary build) over a
    # process pool; the serial loops below consume `precomputed` in their
    # usual deterministic order, so findings match jobs=1 byte for byte
    precomputed: Dict[str, dict] = {}
    if jobs and jobs > 1:
        scan_set = set(scan_files)
        check = list(scan_files)
        if project_rules:
            check = list(dict.fromkeys(list(scan_files) + list(all_files)))
        need: List[str] = []
        for abspath in check:
            rel = rels[abspath]
            try:
                sha, _src = read(abspath, rel)
            except (UnicodeDecodeError, OSError):
                continue   # the serial loop reports the read error
            ent = cache.get(rel, sha) if cache else None
            findings_hit = ent is not None and all(
                r.name in ent["findings"] for r in file_rules)
            summary_hit = ent is not None and \
                ent.get("summary") is not None
            if (abspath in scan_set and not findings_hit) or \
                    (project_rules and not summary_hit):
                need.append(rel)
        if need:
            import concurrent.futures as _cf
            import multiprocessing as _mp
            rule_names = [r.name for r in file_rules]
            payloads = [(rel, sources[rel][1], cfg, rule_names)
                        for rel in need]
            try:
                # spawn, not fork: the caller may have threads (pytest,
                # jax) and a forked child inherits their locks mid-flight
                with _cf.ProcessPoolExecutor(
                        max_workers=jobs,
                        mp_context=_mp.get_context("spawn")) as pool:
                    for res in pool.map(
                            _parallel_scan_worker, payloads,
                            chunksize=max(1, len(payloads) // (jobs * 4))):
                        precomputed[res["rel"]] = res
                        if res["error"] is None:
                            result.parsed_files += 1
            except Exception:
                precomputed = {}   # pool failure: plain serial run

    # ---- per-file pass over the (possibly narrowed) scan set ----
    for abspath in scan_files:
        rel = rels[abspath]
        try:
            sha, src = read(abspath, rel)
        except (UnicodeDecodeError, OSError) as e:
            result.errors.append(f"{rel}: {e.__class__.__name__}: {e}")
            failed.add(rel)
            continue
        ent = cache.get(rel, sha) if cache else None
        if ent is not None and \
                all(r.name in ent["findings"] for r in file_rules):
            result.scanned.append(rel)
            result.files_checked += 1
            result.findings_cache_hits += 1
            for r in file_rules:
                findings.extend(Finding(**d) for d in ent["findings"][r.name])
            continue
        pre = precomputed.get(rel)
        if pre is not None:
            if pre["error"] is not None:
                result.errors.append(pre["error"])
                failed.add(rel)
                continue
            result.scanned.append(rel)
            result.files_checked += 1
            per_rule = pre["findings"]
            for r in file_rules:
                findings.extend(Finding(**d) for d in per_rule[r.name])
            if cache is not None:
                cache.put_findings(rel, sha, per_rule)
            continue
        try:
            ctx = parse(rel, src)
        except SyntaxError as e:
            result.errors.append(f"{rel}: {e.__class__.__name__}: {e}")
            failed.add(rel)
            continue
        result.scanned.append(rel)
        result.files_checked += 1
        per_line, file_level = _pragma_tables(ctx.lines)
        per_rule = {}
        for rule in file_rules:
            fs = [f for f in (rule.check(ctx) or ())
                  if not _suppressed(f, per_line, file_level)]
            findings.extend(fs)
            per_rule[rule.name] = [f.as_dict() for f in fs]
        if cache is not None:
            cache.put_findings(rel, sha, per_rule)

    # ---- whole-program pass over the FULL selection ----
    if project_rules:
        from .wholeprogram.project import Project
        from .wholeprogram.summary import ModuleSummary, build_summary
        summaries: Dict[str, ModuleSummary] = {}
        for abspath in all_files:
            rel = rels[abspath]
            if rel in failed:
                continue
            try:
                sha, src = read(abspath, rel)
            except (UnicodeDecodeError, OSError) as e:
                result.errors.append(f"{rel}: {e.__class__.__name__}: {e}")
                failed.add(rel)
                continue
            ent = cache.get(rel, sha) if cache else None
            if ent is not None and ent.get("summary") is not None:
                summaries[rel] = ModuleSummary.from_dict(ent["summary"])
                result.summary_cache_hits += 1
                continue
            pre = precomputed.get(rel)
            if pre is not None and pre["error"] is None and \
                    pre["summary"] is not None:
                s = ModuleSummary.from_dict(pre["summary"])
                summaries[rel] = s
                if cache is not None:
                    cache.put_summary(rel, sha, s.to_dict())
                continue
            try:
                ctx = parse(rel, src)
            except SyntaxError as e:
                result.errors.append(f"{rel}: {e.__class__.__name__}: {e}")
                failed.add(rel)
                continue
            s = build_summary(rel, ctx.tree, ctx.lines, cfg)
            summaries[rel] = s
            if cache is not None:
                cache.put_summary(rel, sha, s.to_dict())
        project = Project(summaries, cfg, root=root)
        for rule in project_rules:
            for f in rule.check_project(project) or ():
                s = summaries.get(f.path)
                if s is not None and s.suppressed(f.rule, f.line):
                    continue
                findings.append(f)

    if cache is not None:
        cache.save()

    result.failed_files = sorted(failed)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))

    # under an APPLIED git narrowing, baseline entries of per-file rules
    # for unscanned files can neither match nor meaningfully go stale —
    # scope them out so a warm incremental run doesn't scream "stale"
    entries = list(baseline_entries or [])
    if result.changed_only:
        project_names = {r.name for r in project_rules}
        scanned_set = set(result.scanned)
        entries = [e for e in entries
                   if e["rule"] in project_names or e["path"] in scanned_set]

    new, baselined, stale = match_baseline(findings, entries)
    result.new, result.baselined, result.stale = new, baselined, stale
    result.run_seconds = time.perf_counter() - t_start
    return result
