"""graft-lint engine: contexts, registry, pragmas, baseline, reporting.

Design notes (mirrors how large-framework CIs structure this):

* One ``FileContext`` per file, parsed once, shared by every rule — rules
  are pure functions of the context and must not mutate it.
* Findings are keyed for baseline purposes by ``(path, rule, message)``
  WITHOUT the line number, so an unrelated edit that shifts lines does not
  invalidate a grandfathered entry; identical findings in one file
  collapse into a single baseline entry with a ``count``.
* Suppression is explicit and greppable: ``# graft-lint: disable=<rule>``
  on the finding's line (or on a comment-only line directly above it), or
  ``# graft-lint: disable-file=<rule>`` anywhere in the file. ``all``
  matches every rule.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# ---------------------------------------------------------------------------
# findings + file context
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Finding:
    path: str          # repo-relative, posix separators
    line: int
    rule: str
    message: str

    def key(self) -> Tuple[str, str, str]:
        """Line-free fingerprint used for baseline matching."""
        return (self.path, self.rule, self.message)

    def text(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def as_dict(self) -> Dict[str, Any]:
        return {"path": self.path, "line": self.line, "rule": self.rule,
                "message": self.message}


class FileContext:
    """One parsed source file handed to every rule."""

    def __init__(self, path: str, source: str, config: Dict[str, Any]):
        self.path = path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.config = config
        self.tree = ast.parse(source)

    def finding(self, node_or_line, rule: str, message: str) -> Finding:
        line = getattr(node_or_line, "lineno", node_or_line)
        return Finding(self.path, int(line), rule, message)


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------


class Rule:
    """Base class: subclass, set ``name``/``description``, implement
    ``check(ctx) -> iterable of Finding``."""

    name: str = ""
    description: str = ""

    def check(self, ctx: FileContext) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError


RULES: Dict[str, Rule] = {}


def register_rule(cls):
    """Class decorator: instantiate + register a rule by its ``name``."""
    inst = cls()
    if not inst.name:
        raise ValueError(f"rule {cls.__name__} has no name")
    RULES[inst.name] = inst
    return cls


# ---------------------------------------------------------------------------
# default configuration
# ---------------------------------------------------------------------------

DEFAULT_CONFIG: Dict[str, Any] = {
    # directories (repo-relative) scanned when the CLI gets no paths
    "default_paths": ["paddle_tpu"],
    # hot-path-import: modules whose function bodies must not import
    "hot_path_modules": [
        "paddle_tpu/core/tensor.py",
        "paddle_tpu/core/dispatch_cache.py",
        "paddle_tpu/core/autograd.py",
    ],
    # trace-impurity: extra per-file trace roots beyond the auto-detected
    # ``jax.jit(fn)`` / ``@jax.jit`` / ``apply(name, fn, ...)`` seams
    "trace_roots": {
        "paddle_tpu/core/tensor.py": ["_build_pure_fn"],
    },
    # unguarded-global: functions whose NAME ends with one of these
    # suffixes are assumed to run with the module lock already held by
    # their caller (the ``_locked`` convention used across core/)
    "lock_held_suffixes": ["_locked"],
}


# ---------------------------------------------------------------------------
# suppression pragmas
# ---------------------------------------------------------------------------

_PRAGMA_RE = re.compile(
    r"#\s*graft-lint:\s*(disable|disable-file)\s*=\s*([A-Za-z0-9_,\- ]+)")


def _pragma_tables(lines: Sequence[str]) -> Tuple[Dict[int, set], set]:
    """(line -> suppressed rule names, file-level suppressed names)."""
    per_line: Dict[int, set] = {}
    file_level: set = set()
    pending: set = set()  # from comment-only lines, applies to next code line
    for i, raw in enumerate(lines, start=1):
        m = _PRAGMA_RE.search(raw)
        stripped = raw.strip()
        is_comment_only = stripped.startswith("#")
        if m:
            names = {n.strip() for n in m.group(2).split(",") if n.strip()}
            if m.group(1) == "disable-file":
                file_level |= names
            elif is_comment_only:
                pending |= names
            else:
                per_line.setdefault(i, set()).update(names)
        elif stripped and not is_comment_only:
            if pending:
                per_line.setdefault(i, set()).update(pending)
                pending = set()
    return per_line, file_level


def _suppressed(f: Finding, per_line: Dict[int, set], file_level: set) -> bool:
    names = per_line.get(f.line, set()) | file_level
    return f.rule in names or "all" in names


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

BASELINE_VERSION = 1


def default_baseline_path() -> str:
    return os.path.join(REPO_ROOT, "tools", "lint", "baseline.json")


def load_baseline(path: Optional[str]) -> List[Dict[str, Any]]:
    if path is None or not os.path.exists(path):
        return []
    with open(path) as f:
        data = json.load(f)
    return list(data.get("entries", []))


def match_baseline(findings: Sequence[Finding],
                   entries: Sequence[Dict[str, Any]]
                   ) -> Tuple[List[Finding], List[Finding], List[Dict[str, Any]]]:
    """Split ``findings`` into (new, baselined) and report stale entries.

    An entry ``{path, rule, message, count}`` absorbs up to ``count``
    findings with the same (path, rule, message); an entry that absorbs
    fewer than ``count`` is stale (the code improved — prune it with
    ``--update-baseline``).
    """
    budget: Dict[Tuple[str, str, str], int] = {}
    for e in entries:
        k = (e["path"], e["rule"], e["message"])
        budget[k] = budget.get(k, 0) + int(e.get("count", 1))
    remaining = dict(budget)
    new: List[Finding] = []
    baselined: List[Finding] = []
    for f in findings:
        k = f.key()
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
            baselined.append(f)
        else:
            new.append(f)
    stale = []
    for e in entries:
        k = (e["path"], e["rule"], e["message"])
        if remaining.get(k, 0) > 0:
            stale.append(dict(e, unused=remaining[k]))
            remaining[k] = 0  # report duplicates of the same key once
    return new, baselined, stale


def update_baseline(findings: Sequence[Finding],
                    old_entries: Sequence[Dict[str, Any]]
                    ) -> List[Dict[str, Any]]:
    """Regenerate baseline entries from the CURRENT findings, preserving
    the human-written ``reason`` of any surviving entry. New entries get a
    TODO reason on purpose: grandfathering must be a reviewed diff, not a
    silent flag-flip."""
    reasons = {(e["path"], e["rule"], e["message"]): e.get("reason", "")
               for e in old_entries}
    grouped: Dict[Tuple[str, str, str], int] = {}
    for f in findings:
        grouped[f.key()] = grouped.get(f.key(), 0) + 1
    entries = []
    for (path, rule, message), count in sorted(grouped.items()):
        entries.append({
            "path": path, "rule": rule, "message": message, "count": count,
            "reason": reasons.get((path, rule, message))
            or "TODO: justify this grandfathered finding",
        })
    return entries


def save_baseline(path: str, entries: Sequence[Dict[str, Any]]) -> None:
    with open(path, "w") as f:
        json.dump({"version": BASELINE_VERSION, "entries": list(entries)},
                  f, indent=2, sort_keys=True)
        f.write("\n")


# ---------------------------------------------------------------------------
# running
# ---------------------------------------------------------------------------


@dataclass
class LintResult:
    new: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    stale: List[Dict[str, Any]] = field(default_factory=list)
    files_checked: int = 0
    scanned: List[str] = field(default_factory=list)  # repo-relative paths
    errors: List[str] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.new and not self.errors

    def as_dict(self) -> Dict[str, Any]:
        counts: Dict[str, int] = {}
        for f in self.new:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return {
            "files_checked": self.files_checked,
            "findings": [f.as_dict() for f in self.new],
            "baselined": len(self.baselined),
            "stale_baseline_entries": self.stale,
            "counts_by_rule": counts,
            "errors": self.errors,
            "clean": self.clean,
        }


def iter_python_files(paths: Sequence[str], root: str = REPO_ROOT
                      ) -> List[str]:
    """Expand files/directories into a sorted list of absolute .py paths."""
    out = []
    for p in paths:
        absp = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isdir(absp):
            for dirpath, dirnames, filenames in os.walk(absp):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                for fn in filenames:
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
        elif absp.endswith(".py"):
            out.append(absp)
    return sorted(set(out))


def run_lint(paths: Optional[Sequence[str]] = None,
             rules: Optional[Sequence[str]] = None,
             config: Optional[Dict[str, Any]] = None,
             baseline_entries: Optional[Sequence[Dict[str, Any]]] = None,
             root: str = REPO_ROOT) -> LintResult:
    """Run the engine. ``paths`` may be absolute or ``root``-relative;
    findings always report ``root``-relative paths."""
    cfg = dict(DEFAULT_CONFIG)
    if config:
        cfg.update(config)
    if paths is None:
        paths = cfg["default_paths"]
    active = [RULES[n] for n in (rules or sorted(RULES))]
    result = LintResult()
    findings: List[Finding] = []
    for abspath in iter_python_files(paths, root=root):
        rel = os.path.relpath(abspath, root).replace(os.sep, "/")
        result.scanned.append(rel)
        try:
            with open(abspath, encoding="utf-8") as f:
                src = f.read()
            ctx = FileContext(rel, src, cfg)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            result.errors.append(f"{rel}: {e.__class__.__name__}: {e}")
            continue
        result.files_checked += 1
        per_line, file_level = _pragma_tables(ctx.lines)
        for rule in active:
            for f in rule.check(ctx) or ():
                if not _suppressed(f, per_line, file_level):
                    findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    new, baselined, stale = match_baseline(findings, baseline_entries or [])
    result.new, result.baselined, result.stale = new, baselined, stale
    return result
