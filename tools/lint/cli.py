"""graft-lint command line.

Usage::

    python -m tools.lint                       # lint paddle_tpu/ (default)
    python -m tools.lint paddle_tpu/core       # lint a subtree / files
    python -m tools.lint --changed-only        # only files changed vs the
                                               # merge-base with main (whole-
                                               # program rules still see the
                                               # full tree via the cache)
    python -m tools.lint --format=json         # machine-readable report
    python -m tools.lint --format=sarif        # GitHub-code-scanning SARIF
                                               # (witness paths become
                                               # relatedLocations)
    python -m tools.lint --rules=silent-swallow,host-sync
    python -m tools.lint --list-rules
    python -m tools.lint --no-baseline         # show baselined findings too
    python -m tools.lint --no-cache            # ignore + don't write the
                                               # content-hash summary cache
    python -m tools.lint --jobs 4              # parallel COLD pass (cache-
                                               # miss files); byte-identical
                                               # findings, warm path untouched
    python -m tools.lint --update-baseline     # regenerate the grandfather
                                               # list (reviewed diff!)

Exit codes: 0 — clean (every finding baselined); 1 — non-baselined
findings, or the baseline still carries ``TODO`` reasons (write the
justification, or pass ``--allow-todo`` while drafting); 2 — usage error
(unknown rule, path matching no python files). Stale baseline entries are
reported but do not fail a CLI run; the tier-1 gate
(``tests/test_lint.py``) rejects them so the baseline cannot rot.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, Sequence

from .engine import (ProjectRule, RULES, default_baseline_path,
                     iter_python_files, load_baseline, run_lint,
                     save_baseline, update_baseline)
from .wholeprogram.cache import default_cache_path
from . import rules as _rules  # noqa: F401  (registers built-ins)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="graft-lint: framework-aware static analysis")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: paddle_tpu/)")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule names (default: all)")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--changed-only", action="store_true",
                   help="narrow the per-file pass to files changed vs "
                        "`git merge-base HEAD main` (+ untracked); falls "
                        "back to a full run outside git. Whole-program "
                        "rules always analyze the full tree (cached).")
    p.add_argument("--diff-base", default="main",
                   help="branch/ref for --changed-only (default: main)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: {default_baseline_path()})")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: report every finding")
    p.add_argument("--allow-todo", action="store_true",
                   help="do not fail on baseline entries whose reason is "
                        "still the TODO stamp (drafting escape hatch; the "
                        "tier-1 gate never allows them)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from current findings "
                        "(preserves existing reasons; new entries get a "
                        "TODO reason to force review)")
    p.add_argument("--prune-baseline", action="store_true",
                   help="delete baseline entries that no longer fire (and "
                        "lower over-counted ones), printing each removal. "
                        "Full default runs only: a run narrowed by paths/"
                        "--changed-only/--rules cannot tell a fixed "
                        "finding from one it never looked at")
    p.add_argument("--cache-file", default=None,
                   help=f"summary/findings cache "
                        f"(default: {default_cache_path()})")
    p.add_argument("--no-cache", action="store_true",
                   help="disable the content-hash cache for this run")
    p.add_argument("--jobs", type=int, default=1, metavar="N",
                   help="fan the cold pass (parse + per-file rules + "
                        "summary build for cache-miss files) over N "
                        "processes; findings are byte-identical to a "
                        "serial run and the warm-cache path is untouched")
    return p


#: pinned in tests/test_bench_selfdefense.py next to the --format=json pin
SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def sarif_report(result) -> dict:
    """GitHub-code-scanning-loadable SARIF: every registered rule ships
    its metadata, every NEW (non-baselined) finding becomes a result, and
    a finding's structured witness chain (``Finding.related`` — the
    shared-state-race root→access paths) becomes relatedLocations."""

    def _loc(path, line, message=None):
        loc = {"physicalLocation": {
            "artifactLocation": {"uri": path, "uriBaseId": "%SRCROOT%"},
            "region": {"startLine": int(line)}}}
        if message:
            loc["message"] = {"text": message}
        return loc

    rule_ids = sorted(RULES)
    results = []
    for f in result.new:
        res = {"ruleId": f.rule,
               "ruleIndex": rule_ids.index(f.rule),
               "level": "warning",
               "message": {"text": f.message},
               "locations": [_loc(f.path, f.line)]}
        if f.related:
            res["relatedLocations"] = [
                _loc(r["path"], r["line"], r.get("message"))
                for r in f.related]
        results.append(res)
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "graft-lint",
                "informationUri":
                    "https://github.com/paddle-tpu/paddle-tpu",
                "rules": [{
                    "id": name,
                    "shortDescription": {"text": RULES[name].description},
                    "defaultConfiguration": {"level": "warning"},
                } for name in rule_ids],
            }},
            "columnKind": "utf16CodeUnits",
            "results": results,
        }],
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for name in sorted(RULES):
            print(f"{name:20s} {RULES[name].description}")
        return 0

    rule_names = None
    if args.rules:
        rule_names = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rule_names if r not in RULES]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)} "
                  f"(see --list-rules)", file=sys.stderr)
            return 2

    if args.prune_baseline and (args.paths or args.changed_only or
                                args.rules or args.no_baseline or
                                args.update_baseline):
        print("--prune-baseline requires a full default run: with paths, "
              "--changed-only, --rules, --no-baseline or --update-baseline "
              "in play, a non-firing entry may just be one this run never "
              "looked at", file=sys.stderr)
        return 2

    for p in args.paths:
        if not iter_python_files([p]):
            # a renamed/typo'd path must not silently go green — that is
            # the silent-failure class this tool exists to prevent
            print(f"no python files found under {p!r}", file=sys.stderr)
            return 2

    baseline_path = args.baseline or default_baseline_path()
    entries = [] if (args.no_baseline or args.update_baseline) \
        else load_baseline(baseline_path)

    # TODO-stamped reasons are a drafting state, not a shipped state: a
    # baseline that still carries them fails the run (after reporting, so
    # JSON consumers always get the report) unless --allow-todo
    todo_entries = [] if args.allow_todo else \
        [e for e in entries
         if str(e.get("reason", "")).strip().startswith("TODO")]

    cache_path = None if args.no_cache \
        else (args.cache_file or default_cache_path())
    result = run_lint(paths=args.paths or None, rules=rule_names,
                      baseline_entries=entries,
                      changed_only=args.changed_only,
                      diff_base=args.diff_base,
                      cache_path=cache_path,
                      jobs=args.jobs)

    if args.prune_baseline:
        # result.stale is exactly the non-firing budget of this (full)
        # run; entries for files that failed to read/parse produced no
        # findings for the wrong reason and are never pruned
        failed = set(result.failed_files)
        stale_by_key = {(e["path"], e["rule"], e["message"]): e["unused"]
                        for e in result.stale if e["path"] not in failed}
        kept, removed, lowered = [], 0, 0
        for e in load_baseline(baseline_path):
            k = (e["path"], e["rule"], e["message"])
            unused = stale_by_key.pop(k, 0)
            count = int(e.get("count", 1))
            if unused >= count:
                print(f"pruned: {e['path']}: {e['rule']} x{count}: "
                      f"{e['message'][:70]}")
                removed += 1
                continue
            if unused:
                print(f"lowered: {e['path']}: {e['rule']} "
                      f"x{count} -> x{count - unused}")
                e = dict(e, count=count - unused)
                lowered += 1
            kept.append(e)
        if removed or lowered:
            save_baseline(baseline_path, kept)
        print(f"pruned {removed}, lowered {lowered}, kept {len(kept)} "
              f"baseline entr{'y' if len(kept) == 1 else 'ies'}")
        return 0 if result.clean else 1

    if args.update_baseline:
        # regenerate only what this run could SEE: entries for unscanned
        # files / inactive rules pass through untouched, so a scoped
        # `tools.lint paddle_tpu/core --update-baseline` can never delete
        # the rest of the tree's reviewed justifications. Whole-program
        # rules need the FULL default selection to be regenerable at all —
        # a path-narrowed run builds a partial graph whose missing roots /
        # call edges make their findings vanish spuriously — so their
        # entries are only in scope on a default-paths run (which is also
        # what --changed-only uses: its narrowing hits the per-file pass
        # only, so project findings in unchanged files keep matching their
        # justified entries instead of growing TODO-stamped twins). Files
        # that failed to read/parse produced no findings either way —
        # their entries always pass through untouched.
        old = load_baseline(baseline_path)
        scanned = set(result.scanned)
        selection = set(result.selection)
        failed = set(result.failed_files)
        full_selection = not args.paths
        active = set(rule_names or RULES)
        project_names = {n for n, r in RULES.items()
                         if isinstance(r, ProjectRule)}

        def saw(e):
            if e["rule"] not in active or e["path"] in failed:
                return False
            if e["rule"] in project_names:
                return full_selection and e["path"] in selection
            return e["path"] in scanned

        in_scope = [e for e in old if saw(e)]
        out_scope = [e for e in old if not saw(e)]
        # symmetric filter on the findings side: project-rule findings
        # from a partial graph must not mint entries next to the
        # preserved (out-of-scope) justified ones
        regen = [f for f in result.new
                 if full_selection or f.rule not in project_names]
        new_entries = sorted(
            update_baseline(regen, in_scope) + out_scope,
            key=lambda e: (e["path"], e["rule"], e["message"]))
        save_baseline(baseline_path, new_entries)
        print(f"wrote {len(new_entries)} entr"
              f"{'y' if len(new_entries) == 1 else 'ies'} to "
              f"{baseline_path}")
        todo = sum(1 for e in new_entries
                   if str(e.get("reason", "")).startswith("TODO"))
        if todo:
            print(f"{todo} new entr{'y' if todo == 1 else 'ies'} carry a "
                  f"TODO reason — edit the justification before committing")
        return 0

    cache_line = (f"cache: {result.parsed_files} parsed, "
                  f"{result.findings_cache_hits} file-pass hits, "
                  f"{result.summary_cache_hits} summary hits "
                  f"(of {result.total_files} files) "
                  f"in {result.run_seconds:.2f}s")
    if args.format == "sarif":
        print(json.dumps(sarif_report(result), indent=2, sort_keys=True))
    elif args.format == "json":
        report = result.as_dict()
        report["todo_baseline_entries"] = [
            {"path": e["path"], "rule": e["rule"], "message": e["message"]}
            for e in todo_entries]
        if todo_entries:
            report["clean"] = False
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        for f in result.new:
            print(f.text())
        for e in result.stale:
            print(f"stale baseline entry (code improved — run "
                  f"--update-baseline): {e['path']}: {e['rule']} "
                  f"x{e['unused']}")
        for err in result.errors:
            print(f"error: {err}", file=sys.stderr)
        summary = (f"{result.files_checked} files, "
                   f"{len(result.new)} finding(s), "
                   f"{len(result.baselined)} baselined, "
                   f"{len(result.stale)} stale baseline entr"
                   f"{'y' if len(result.stale) == 1 else 'ies'}"
                   + ("; changed-only" if result.changed_only else ""))
        print(cache_line)
        ok = result.clean and not todo_entries
        print(("FAILED: " if not ok else "ok: ") + summary)
    if todo_entries:
        for e in todo_entries:
            print(f"baseline entry without a reviewed reason: "
                  f"{e['path']}: {e['rule']}: {e['message'][:60]}…",
                  file=sys.stderr)
        print(f"{len(todo_entries)} baseline entr"
              f"{'y' if len(todo_entries) == 1 else 'ies'} still "
              f"carr{'ies' if len(todo_entries) == 1 else 'y'} a TODO "
              f"reason — write the justification (or pass --allow-todo "
              f"while drafting)", file=sys.stderr)
    return 0 if result.clean and not todo_entries else 1
