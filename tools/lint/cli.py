"""graft-lint command line.

Usage::

    python -m tools.lint                       # lint paddle_tpu/ (default)
    python -m tools.lint paddle_tpu/core       # lint a subtree / files
    python -m tools.lint --format=json         # machine-readable report
    python -m tools.lint --rules=silent-swallow,host-sync
    python -m tools.lint --list-rules
    python -m tools.lint --no-baseline         # show baselined findings too
    python -m tools.lint --update-baseline     # regenerate the grandfather
                                               # list (reviewed diff!)

Exit codes: 0 — clean (every finding baselined); 1 — non-baselined
findings; 2 — usage error (unknown rule, path matching no python files).
Stale baseline entries are reported but do not fail a CLI run; the tier-1
gate (``tests/test_lint.py``) rejects them so the baseline cannot rot.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, Sequence

from .engine import (RULES, default_baseline_path, iter_python_files,
                     load_baseline, run_lint, save_baseline, update_baseline)
from . import rules as _rules  # noqa: F401  (registers built-ins)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="graft-lint: framework-aware static analysis")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: paddle_tpu/)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule names (default: all)")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: {default_baseline_path()})")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: report every finding")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from current findings "
                        "(preserves existing reasons; new entries get a "
                        "TODO reason to force review)")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for name in sorted(RULES):
            print(f"{name:18s} {RULES[name].description}")
        return 0

    rule_names = None
    if args.rules:
        rule_names = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rule_names if r not in RULES]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)} "
                  f"(see --list-rules)", file=sys.stderr)
            return 2

    for p in args.paths:
        if not iter_python_files([p]):
            # a renamed/typo'd path must not silently go green — that is
            # the silent-failure class this tool exists to prevent
            print(f"no python files found under {p!r}", file=sys.stderr)
            return 2

    baseline_path = args.baseline or default_baseline_path()
    entries = [] if (args.no_baseline or args.update_baseline) \
        else load_baseline(baseline_path)
    result = run_lint(paths=args.paths or None, rules=rule_names,
                      baseline_entries=entries)

    if args.update_baseline:
        # regenerate only what this run could SEE: entries for unscanned
        # files / inactive rules pass through untouched, so a scoped
        # `tools.lint paddle_tpu/core --update-baseline` can never delete
        # the rest of the tree's reviewed justifications
        old = load_baseline(baseline_path)
        scanned = set(result.scanned)
        active = set(rule_names or RULES)
        in_scope = [e for e in old
                    if e["path"] in scanned and e["rule"] in active]
        out_scope = [e for e in old
                     if not (e["path"] in scanned and e["rule"] in active)]
        new_entries = sorted(
            update_baseline(result.new, in_scope) + out_scope,
            key=lambda e: (e["path"], e["rule"], e["message"]))
        save_baseline(baseline_path, new_entries)
        print(f"wrote {len(new_entries)} entr"
              f"{'y' if len(new_entries) == 1 else 'ies'} to "
              f"{baseline_path}")
        todo = sum(1 for e in new_entries
                   if str(e.get("reason", "")).startswith("TODO"))
        if todo:
            print(f"{todo} new entr{'y' if todo == 1 else 'ies'} carry a "
                  f"TODO reason — edit the justification before committing")
        return 0

    if args.format == "json":
        print(json.dumps(result.as_dict(), indent=2, sort_keys=True))
    else:
        for f in result.new:
            print(f.text())
        for e in result.stale:
            print(f"stale baseline entry (code improved — run "
                  f"--update-baseline): {e['path']}: {e['rule']} "
                  f"x{e['unused']}")
        for err in result.errors:
            print(f"error: {err}", file=sys.stderr)
        summary = (f"{result.files_checked} files, "
                   f"{len(result.new)} finding(s), "
                   f"{len(result.baselined)} baselined, "
                   f"{len(result.stale)} stale baseline entr"
                   f"{'y' if len(result.stale) == 1 else 'ies'}")
        print(("FAILED: " if not result.clean else "ok: ") + summary)
    return 0 if result.clean else 1
