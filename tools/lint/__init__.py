"""graft-lint — framework-aware static analysis for the paddle_tpu tree.

A self-contained AST lint engine (stdlib only, ``python -m tools.lint``)
that mechanically enforces the invariants this codebase keeps re-learning
by hand: trace-time purity for everything ``jax.jit``/the dispatch-cache
compile path can reach, no silently swallowed exceptions, no per-call
imports on the dispatch hot path, lock discipline around module-level
mutable state, and no hidden host syncs inside loops.

Layout:

* ``engine``   — file walking, rule registry, ``# graft-lint:`` pragmas,
  baseline bookkeeping, ``--changed-only`` git narrowing, text/JSON
  reporting (with ``run_seconds`` + cache-hit accounting).
* ``rules``    — one module per rule; importing ``tools.lint.rules``
  registers them all. Per-file rules implement ``check(ctx)``;
  whole-program rules subclass ``ProjectRule`` and implement
  ``check_project(project)``.
* ``wholeprogram`` — graft-lint 2.0 substrate: per-module summaries,
  the content-hash disk cache, and the ``Project`` import/call graphs
  with alias-resolving reachability queries.
* ``cli``      — argument parsing + exit-code policy (0 clean, 1
  non-baselined findings or TODO-stamped baseline reasons, 2 usage
  error).
* ``baseline.json`` — checked-in grandfather list; every entry carries a
  human-written ``reason``. Regenerate with ``--update-baseline`` (new
  entries get a TODO reason so grandfathering stays a reviewed diff —
  and fails any normal run until replaced, ``--allow-todo`` excepted).
"""

from .engine import (  # noqa: F401
    Finding, FileContext, Rule, ProjectRule, RULES, register_rule,
    DEFAULT_CONFIG, default_baseline_path, load_baseline, match_baseline,
    update_baseline, run_lint, LintResult, REPO_ROOT,
)
from . import rules  # noqa: F401  (imports register the built-in rules)
