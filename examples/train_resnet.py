"""BASELINE config #1: ResNet-50 classification (PaddleClas surface)."""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu as paddle

paddle.device.force_platform_from_env()
import paddle_tpu.nn as nn
import paddle_tpu.vision as vision


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--arch", default="resnet50")
    args = ap.parse_args()

    paddle.seed(0)
    model = getattr(vision.models, args.arch)(num_classes=100)
    opt = paddle.optimizer.Momentum(
        learning_rate=paddle.optimizer.lr.CosineAnnealingDecay(0.1,
                                                               args.steps),
        momentum=0.9, parameters=model.parameters())
    loss_fn = nn.CrossEntropyLoss()
    ds = vision.datasets.Cifar100(
        mode="train", transform=vision.transforms.Compose([
            vision.transforms.Resize(args.image_size),
            vision.transforms.Normalize(mean=[0.5] * 3, std=[0.5] * 3)]))
    loader = paddle.io.DataLoader(ds, batch_size=args.batch, shuffle=True)

    @paddle.jit.to_static
    def step(img, label):
        with paddle.amp.auto_cast(level="O2", dtype="bfloat16"):
            loss = loss_fn(model(img), label)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    it = iter(loader)
    for i in range(args.steps):
        loss = step(*next(it))
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i}: loss {float(loss):.4f} lr {opt.get_lr():.4f}")
        opt._learning_rate.step()
    paddle.save(model.state_dict(), "/tmp/resnet_example.pdparams")
    print("saved /tmp/resnet_example.pdparams")


if __name__ == "__main__":
    main()
