"""BASELINE config #2: BERT/ERNIE sequence-classification fine-tune."""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle

paddle.device.force_platform_from_env()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=15)
    ap.add_argument("--model", choices=["bert", "ernie"], default="ernie")
    args = ap.parse_args()

    paddle.seed(0)
    if args.model == "bert":
        from paddle_tpu.models.bert import (BertConfig,
                                            BertForSequenceClassification)
        cfg = BertConfig(vocab_size=1000, hidden_size=128,
                         num_hidden_layers=2, num_attention_heads=4,
                         intermediate_size=256)
        model = BertForSequenceClassification(cfg, num_classes=2)
    else:
        from paddle_tpu.models.ernie import (ErnieConfig,
                                             ErnieForSequenceClassification)
        cfg = ErnieConfig(vocab_size=1000, hidden_size=128,
                          num_hidden_layers=2, num_attention_heads=4,
                          intermediate_size=256)
        model = ErnieForSequenceClassification(cfg, num_classes=2)

    sched = paddle.optimizer.lr.LinearWarmup(
        paddle.optimizer.lr.PolynomialDecay(5e-4, args.steps), 5, 0.0, 5e-4)
    opt = paddle.optimizer.AdamW(learning_rate=sched, weight_decay=0.01,
                                 parameters=model.parameters())
    rng = np.random.default_rng(0)

    @paddle.jit.to_static
    def step(ids, label):
        loss, _ = model(ids, labels=label)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    for i in range(args.steps):
        ids_np = rng.integers(0, 1000, (8, 64), dtype=np.int32)
        # synthetic rule: class = parity of the first token
        ids = paddle.to_tensor(ids_np)
        label = paddle.to_tensor((ids_np[:, 0] % 2).astype(np.int64))
        loss = step(ids, label)
        sched.step()
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i}: loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
