"""BASELINE config #5: DeepFM on the sharded-embedding (PS -> ICI) path."""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle

paddle.device.force_platform_from_env()
from paddle_tpu.models.deepfm import DeepFM, DeepFMConfig


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=256)
    args = ap.parse_args()

    paddle.seed(0)
    cfg = DeepFMConfig(sparse_feature_number=10000, sparse_feature_dim=8,
                        num_sparse_fields=26, dense_feature_dim=13,
                        fc_sizes=(128, 64))
    model = DeepFM(cfg)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    bce = paddle.nn.BCEWithLogitsLoss()
    rng = np.random.default_rng(0)

    @paddle.jit.to_static
    def step(sparse, dense, label):
        logit = model(sparse, dense)
        loss = bce(logit.reshape([-1]), label)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    for i in range(args.steps):
        sparse_np = rng.integers(0, 10000, (args.batch, 26), dtype=np.int64)
        dense_np = rng.normal(0, 1, (args.batch, 13)).astype(np.float32)
        # synthetic click rule so AUC is learnable
        label_np = ((sparse_np[:, 0] % 7 < 3) ^
                    (dense_np[:, 0] > 0)).astype(np.float32)
        loss = step(paddle.to_tensor(sparse_np), paddle.to_tensor(dense_np),
                    paddle.to_tensor(label_np))
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i}: loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
