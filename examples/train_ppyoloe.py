"""BASELINE config #3: PP-YOLOE detection training step + decoded eval."""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle

paddle.device.force_platform_from_env()
from paddle_tpu.models.ppyoloe import PPYOLOE, PPYOLOEConfig


def synth_batch(rng, b=2, size=320, m=3, c=20):
    imgs = rng.normal(size=(b, size, size, 3)).astype(np.float32)  # NHWC
    centers = rng.uniform(20, size - 20, (b, m, 2))
    wh = rng.uniform(16, 80, (b, m, 2))
    boxes = np.concatenate([centers - wh / 2, centers + wh / 2],
                           -1).astype(np.float32)
    labels = rng.integers(0, c, (b, m)).astype(np.int32)
    mask = np.ones((b, m), np.float32)
    return imgs, labels, boxes, mask


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--image-size", type=int, default=320)
    args = ap.parse_args()

    paddle.seed(0)
    model = PPYOLOE(PPYOLOEConfig.tiny(num_classes=20))
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    rng = np.random.default_rng(0)
    imgs, labels, boxes, mask = synth_batch(rng, size=args.image_size)
    t = tuple(paddle.to_tensor(v) for v in (imgs, labels, boxes, mask))

    @paddle.jit.to_static
    def step(img, lab, box, msk):
        out = model.loss(img, lab, box, msk)
        out["loss"].backward()
        opt.step()
        opt.clear_grad()
        return out["loss"]

    for i in range(args.steps):
        loss = step(*t)
        print(f"step {i}: loss {float(loss):.4f}")

    model.eval()
    dets = model.predict(t[0])
    print("predict output:", [getattr(d, "shape", None) for d in dets]
          if isinstance(dets, (tuple, list)) else dets.shape)


if __name__ == "__main__":
    main()
