"""BASELINE config #4: Llama under hybrid parallel (dp x mp mesh).

Run on the virtual CPU mesh:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/train_llama_hybrid.py --dp 2 --mp 4
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--mp", type=int, default=4)
    ap.add_argument("--steps", type=int, default=5)
    args = ap.parse_args()

    need = args.dp * args.mp

    import jax

    import paddle_tpu as paddle

    paddle.device.force_platform_from_env()
    # this config demos the hybrid mesh; unless a machine really has `need`
    # accelerator chips, build the virtual CPU mesh (programmatically — env
    # vars are latched by TPU-plugin sitecustomize hooks)
    if len(jax.devices()) < need:
        paddle.device.force_platform("cpu", need)

    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from paddle_tpu.core.tensor import _state_registry
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    devs = jax.devices()
    if len(devs) < need:
        devs = jax.devices("cpu")
    mesh = Mesh(np.array(devs[:need]).reshape(args.dp, args.mp),
                ("dp", "mp"))

    paddle.seed(0)
    cfg = LlamaConfig.tiny(vocab=512, hidden=128, layers=2, heads=8,
                           kv_heads=8, inter=256, max_pos=128)
    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())

    def spec_for(name):
        if any(k in name for k in ("q_proj", "k_proj", "v_proj",
                                   "gate_proj", "up_proj")):
            return P(None, "mp")   # column parallel
        if any(k in name for k in ("o_proj", "down_proj")):
            return P("mp", None)   # row parallel
        return P()

    with mesh:
        for name, p in model.state_dict().items():
            p._set_data(jax.device_put(
                p._data, NamedSharding(mesh, spec_for(name))))
        sharded = {id(p) for p in model.state_dict().values()}
        for t in _state_registry.alive():
            if id(t) not in sharded:
                t._set_data(jax.device_put(t._data, NamedSharding(mesh, P())))

        @paddle.jit.to_static
        def step(ids):
            loss, _ = model(ids, labels=ids)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        rng = np.random.default_rng(0)
        for i in range(args.steps):
            ids = jax.device_put(
                rng.integers(0, cfg.vocab_size, (args.dp * 2, 64),
                             dtype=np.int32),
                NamedSharding(mesh, P("dp", None)))
            loss = step(paddle.Tensor(ids))
            print(f"step {i}: loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
