"""Tensor: an imperative, autograd-capable wrapper over ``jax.Array``.

Parity surface: ``paddle.Tensor`` (upstream: paddle/phi/api/include/tensor.h,
pybind eager tensor in paddle/fluid/pybind/eager.cc, method surface in
python/paddle/tensor/). TPU-native design: the payload is always a jax array
(or a jax tracer while ``to_static`` is tracing); every op goes through one
dispatch function, ``apply``, which is the analogue of the reference's
generated ``*_ad_func`` + Phi API path — it handles AMP autocast, autograd
tape recording (via ``jax.vjp``), trace-state read logging, and NaN checks.
"""

from __future__ import annotations

import itertools
import numbers
import time as _time
import weakref
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import flags as _flags
from .. import device as _device
from ..resilience.faults import fault_point as _fault_point
from . import dtype as _dtype
from . import dispatch_cache as _dcache
from . import fallback as _fallback
from . import lazy as _lazy
from . import tracing as _tracing
from .autograd import GradNode, backward as _backward

try:
    from jax.core import Tracer as _Tracer
except Exception:  # pragma: no cover
    from jax._src.core import Tracer as _Tracer

__all__ = ["Tensor", "Parameter", "to_tensor", "apply",
           "register_tensor_method", "TraceBreakError"]


class TraceBreakError(RuntimeError):
    """A concrete host-side read (``.numpy()``, ``float()``, ``bool()``) hit a
    traced value. Under ``to_static(full_graph=False)`` this is a graph break
    (eager fallback / segment boundary); under full_graph=True it surfaces."""


def _is_tracer(x) -> bool:
    return isinstance(x, _Tracer)


class RemovableHandle:
    # itertools.count is a single C-level atomic step: hook registration from
    # dataloader worker threads can't mint duplicate ids the way the old
    # unlocked ``_next_id += 1`` read-modify-write could
    _id_counter = itertools.count()

    def __init__(self, hooks: dict):
        self._hooks = hooks
        self.hook_id = next(RemovableHandle._id_counter)

    def remove(self) -> None:
        self._hooks.pop(self.hook_id, None)


class Tensor:
    # __dict__ is included deliberately: paddle code (and users) attach
    # ad-hoc attributes to tensors (is_distributed, placements, ...)
    __slots__ = (
        "_data", "stop_gradient", "_grad", "_grad_node", "_grad_index",
        "name", "persistable", "trainable", "_hooks", "__weakref__", "__dict__",
    )

    # let binary dunders win over numpy array ops
    __array_priority__ = 100

    def __init__(self, data, stop_gradient: bool = True, name: Optional[str] = None):
        if isinstance(data, Tensor):
            data = data._data
        self._data = data
        if type(data).__name__ == "LazyValue":  # cheap check, hot path
            data.owners.add(self)
        self.stop_gradient = stop_gradient
        self._grad: Optional["Tensor"] = None
        self._grad_node: Optional[GradNode] = None
        self._grad_index: int = 0
        self.name = name
        self.persistable = False
        self.trainable = True
        self._hooks: dict = {}
        self._version = 0  # bumped on _set_data; lets derived state (AMP
        #                    masters) detect external writes (state_dict load)

    # --- payload mutation (the single write seam; trace-visible) ------------
    def _set_data(self, value) -> None:
        ts = _tracing.trace_state()
        if ts is not None:
            ts.record_mutation("data", self)
        if type(value).__name__ == "LazyValue":
            value.owners.add(self)
        self._data = value
        self._version += 1

    @property
    def grad(self) -> Optional["Tensor"]:
        return self._grad

    @grad.setter
    def grad(self, value: Optional["Tensor"]) -> None:
        ts = _tracing.trace_state()
        if ts is not None:
            ts.record_mutation("grad", self)
        self._grad = value

    # --- metadata -----------------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    def shape_tuple(self) -> Tuple[int, ...]:
        """``shape`` without the per-access list build: the payload's shape
        tuple as-is. Hot-path consumers (dispatch-cache key extraction)
        use this so metadata reads don't allocate."""
        return self._data.shape

    @property
    def ndim(self) -> int:
        return self._data.ndim

    ndimension = ndim

    @property
    def dtype(self):
        return jnp.dtype(self._data.dtype)

    @property
    def size(self) -> int:
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def place(self):
        d = getattr(self._data, "devices", None)
        if d is None or _is_tracer(self._data):
            return _device.current_place()
        dev = next(iter(self._data.devices()))
        kind = "cpu" if dev.platform == "cpu" else "tpu"
        return _device.Place(kind, dev.id)

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    def numel(self) -> int:
        return self.size

    def element_size(self) -> int:
        return self.dtype.itemsize

    @property
    def itemsize(self) -> int:
        return self.dtype.itemsize

    @property
    def nbytes(self) -> int:
        return self.size * self.dtype.itemsize

    def is_sparse(self) -> bool:
        # method, not property: the reference API (and this repo's sparse
        # classes) spell it t.is_sparse()
        return False

    def data_ptr(self) -> int:
        """Opaque buffer identity (reference: device pointer). PJRT exposes
        the device address only on some backends; fall back to the buffer
        object's identity — stable for aliasing checks, not arithmetic."""
        try:
            return int(self._data.unsafe_buffer_pointer())
        except Exception:
            return id(self._data)

    def dim(self) -> int:
        return self.ndim

    # --- host interop -------------------------------------------------------
    def numpy(self) -> np.ndarray:
        if _is_tracer(self._data):
            raise TraceBreakError(
                "Tensor.numpy() is not available while tracing "
                "inside paddle.jit.to_static")
        if type(self._data).__name__ == "LazyValue":
            # concrete read of a pending value: segment boundary — flush the
            # recorded graph (the SOT graph-break point)
            if self._data.array is None:
                _lazy.flush()
            if type(self._data).__name__ == "LazyValue":
                if self._data.array is None:
                    # the flush failed (or this value's segment flushed while
                    # it had no live owner): surface a clear error instead of
                    # silently degrading to a 0-d object array of None
                    raise RuntimeError(
                        "lazy tensor was never materialized: its recorded "
                        "segment failed to flush or flushed without a live "
                        "owner; re-run the producing op eagerly")
                self._data = self._data.array
        return np.asarray(self._data)

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def item(self, *args):
        return self.numpy().item(*args)

    def tolist(self):
        return self.numpy().tolist()

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __bool__(self):
        return bool(self.item())

    def __index__(self):
        return int(self.item())

    def __len__(self):
        if not self._data.shape:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __hash__(self):
        return id(self)

    def __repr__(self):
        sg = self.stop_gradient
        if _is_tracer(self._data):
            body = f"<traced {self._data.aval}>"
        else:
            body = np.array2string(np.asarray(self._data), separator=", ")
        return (f"Tensor(shape={self.shape}, dtype={_dtype.dtype_name(self.dtype)}, "
                f"place={self.place}, stop_gradient={sg},\n       {body})")

    # --- autograd -----------------------------------------------------------
    def backward(self, grad_tensor: Optional["Tensor"] = None, retain_graph: bool = False):
        _backward([self], [grad_tensor] if grad_tensor is not None else None,
                  retain_graph=retain_graph)

    def clear_grad(self) -> None:
        self.grad = None

    clear_gradient = clear_grad

    def zero_grad(self) -> None:
        self.grad = None

    def register_hook(self, hook: Callable) -> RemovableHandle:
        h = RemovableHandle(self._hooks)
        self._hooks[h.hook_id] = hook
        return h

    def detach(self) -> "Tensor":
        t = Tensor(self._data, stop_gradient=True, name=self.name)
        return t

    def detach_(self) -> "Tensor":
        self._grad_node = None
        self._grad_index = 0
        self.stop_gradient = True
        return self

    @property
    def requires_grad(self) -> bool:
        return not self.stop_gradient

    @requires_grad.setter
    def requires_grad(self, v: bool) -> None:
        self.stop_gradient = not v

    # --- device movement ----------------------------------------------------
    def to(self, *args, **kwargs) -> "Tensor":
        device = kwargs.pop("device", None)
        dtype = kwargs.pop("dtype", None)
        blocking = kwargs.pop("blocking", None)  # noqa: F841  (async is native)
        for a in args:
            if isinstance(a, (str, _device.Place)):
                device = a
            else:
                dtype = a
        out = self
        if dtype is not None:
            out = out.astype(dtype)
        if device is not None:
            place = device if isinstance(device, _device.Place) else _parse_place(device)
            if out is self:
                out = Tensor(self._data, stop_gradient=self.stop_gradient, name=self.name)
                out._grad_node, out._grad_index = self._grad_node, self._grad_index
            if not _is_tracer(out._data):
                out._data = _device.device_put(out._data, place)
        return out

    def cpu(self) -> "Tensor":
        return self.to(device="cpu")

    def cuda(self, device_id=None) -> "Tensor":
        return self.to(device="tpu")

    def tpu(self) -> "Tensor":
        return self.to(device="tpu")

    def pin_memory(self) -> "Tensor":
        return self

    def clone(self) -> "Tensor":
        return apply("clone", jnp.copy, self)

    # --- misc parity --------------------------------------------------------
    def copy_(self, other: "Tensor") -> "Tensor":
        src = other._data if isinstance(other, Tensor) else jnp.asarray(other)
        self._set_data(jnp.broadcast_to(src, self._data.shape).astype(self._data.dtype))
        return self

    def set_value(self, value) -> None:
        self.copy_(value if isinstance(value, Tensor) else to_tensor(value))

    def get_tensor(self):  # LoDTensor parity shim
        return self

    def value(self):
        return self

    def _rebind(self, out: "Tensor") -> "Tensor":
        """Adopt another tensor's payload + grad linkage (in-place op seam)."""
        self._set_data(out._data)
        self._grad_node = out._grad_node
        self._grad_index = out._grad_index
        self.stop_gradient = out.stop_gradient
        return self


class Parameter(Tensor):
    """Trainable tensor (parity: paddle Parameter / EagerParamBase)."""

    __slots__ = ("optimize_attr", "regularizer", "is_distributed", "need_clip")

    _param_counter = 0

    def __init__(self, data, name: Optional[str] = None, trainable: bool = True):
        if name is None:
            name = f"param_{Parameter._param_counter}"
            Parameter._param_counter += 1
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_distributed = False
        self.need_clip = True
        _state_registry.register(self)

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


class _StateRegistry:
    """All live parameters / optimizer accumulators / RNG states.

    ``to_static`` consults this to decide which concrete tensors may legally
    become jit inputs (anything else that is read gets baked as a constant).
    """

    def __init__(self):
        self._items = weakref.WeakValueDictionary()
        self._next = 0

    def register(self, t: Tensor) -> None:
        self._items[self._next] = t
        self._next += 1

    def alive(self):
        return [t for _, t in sorted(self._items.items())]

    def alive_items(self):
        """[(registration id, tensor)] — ids are never reused, so they make a
        stable cache key distinguishing same-length registries over time."""
        return sorted(self._items.items())


_state_registry = _StateRegistry()


def register_state_tensor(t: Tensor) -> None:
    _state_registry.register(t)


def _parse_place(device) -> _device.Place:
    if isinstance(device, _device.Place):
        return device
    dev = str(device).lower()
    if dev in ("gpu", "cuda", "xpu", "tpu"):
        return _device.TPUPlace() if _device.is_compiled_with_tpu() else _device.CPUPlace()
    if ":" in dev:
        kind, _, idx = dev.partition(":")
        return _device.Place("tpu" if kind in ("gpu", "cuda", "tpu", "xpu") else kind, int(idx))
    return _device.Place(dev, 0)


# ---------------------------------------------------------------------------
# op dispatch
# ---------------------------------------------------------------------------

def _autocast_targets(op_name: str, arrays):
    """Per-input cast target dtypes for the active autocast state (or None).

    Returns None when no casting applies. The actual cast happens INSIDE the
    vjp'd function so the cast itself is differentiated — cotangents then
    arrive in each producer's original dtype.
    """
    st = _tracing.amp_state()
    if st is None or not st.enable:
        return None
    low = st.dtype
    fp32 = jnp.float32

    if st.level == "O2":
        target = fp32 if op_name in st.black_set else low
    elif op_name in st.white_set:
        target = low
    elif op_name in st.black_set:
        target = fp32
    else:
        return None
    out = [target if jnp.issubdtype(a.dtype, jnp.floating) and a.dtype != target
           else None for a in arrays]
    return out if any(t is not None for t in out) else None


# Set by paddle_tpu.profiler while a Profiler window is recording; called as
# hook(op_name, t0, t1) after each dispatch. None ⇒ zero overhead.
_op_profile_hook: Optional[Callable[[str, float, float], None]] = None

# Set by paddle_tpu.observability while metrics are enabled; same signature
# and same zero-overhead contract as the profiler hook (the disabled path
# pays only the is-None probes below).
_op_metrics_hook: Optional[Callable[[str, float, float], None]] = None

# Set by paddle_tpu.observability.trace while PADDLE_TPU_TRACE=on; same
# signature and zero-overhead contract — per-op events land in the trace
# buffer so a Chrome export shows where each eager step's time went.
_op_trace_hook: Optional[Callable[[str, float, float], None]] = None

# Set by paddle_tpu.static while static-graph mode is capturing; called as
# hook(op_name, pure_fn, tensor_inputs, out_tensors) after each dispatch so
# the Program can record a replayable op node. None ⇒ zero overhead.
_op_graph_hook: Optional[Callable] = None


def _lazy_apply(op_name, f, tensor_inputs, arrays, needs_grad):
    """Segment-mode dispatch (full_graph=False partial-graph capture): the
    op is RECORDED, outputs are LazyValue placeholders, and the tape node
    carries only pure_fn — backward re-dispatches through apply() so the
    gradient ops land in the (compiled) segment too."""
    out_lazies, multi = _lazy.record(op_name, f, arrays)
    out_tensors = []
    if needs_grad:
        node = GradNode(op_name, None, tensor_inputs, len(out_lazies),
                        tuple((lv.aval.shape, lv.aval.dtype)
                              for lv in out_lazies),
                        pure_fn=f, multi_out=multi)
        for i, lv in enumerate(out_lazies):
            t = Tensor(lv, stop_gradient=False)
            t._grad_node = node
            t._grad_index = i
            out_tensors.append(t)
    else:
        for lv in out_lazies:
            out_tensors.append(Tensor(lv, stop_gradient=True))
    if multi:
        return tuple(out_tensors)
    return out_tensors[0]


def apply(op_name: str, fn: Callable, *tensor_inputs: Tensor,
          differentiable: bool = True, amp: bool = True, **static_kwargs) -> Any:
    """Dispatch one op: the TPU analogue of ad_func → Phi API → kernel.

    ``fn`` is a pure jax function over arrays. Tensor inputs are unwrapped,
    autocast applied, and — when grad is enabled and some input requires grad
    — the op is linearized with ``jax.vjp`` and a ``GradNode`` recorded.
    """
    prof_hook = _op_profile_hook
    metrics_hook = _op_metrics_hook
    trace_hook = _op_trace_hook
    if prof_hook is not None or metrics_hook is not None \
            or trace_hook is not None:
        _t0 = _time.perf_counter()
        try:
            return _apply_impl(op_name, fn, *tensor_inputs,
                               differentiable=differentiable, amp=amp,
                               **static_kwargs)
        finally:
            _t1 = _time.perf_counter()
            if prof_hook is not None:
                prof_hook(op_name, _t0, _t1)
            if metrics_hook is not None:
                metrics_hook(op_name, _t0, _t1)
            if trace_hook is not None:
                trace_hook(op_name, _t0, _t1)
    return _apply_impl(op_name, fn, *tensor_inputs,
                       differentiable=differentiable, amp=amp, **static_kwargs)


def _build_pure_fn(fn: Callable, cast_targets, static_kwargs) -> Callable:
    """The traced/differentiated form of one op: autocast applied INSIDE so
    the cast itself is differentiated, static kwargs baked, list outputs
    normalized to tuples. Shared by the uncached, cached, and lazy paths."""
    def f(*xs):
        if cast_targets is not None:
            xs = [x.astype(d) if d is not None else x
                  for x, d in zip(xs, cast_targets)]
        r = fn(*xs, **static_kwargs) if static_kwargs else fn(*xs)
        return tuple(r) if isinstance(r, list) else r
    return f


def _input_sig(t: Tensor):
    """(shape, dtype, weak_type) of one input — the aval when the payload
    carries one (jax arrays), ``shape_tuple()`` otherwise (numpy payloads)."""
    a = t._data
    av = getattr(a, "aval", None)
    if av is not None:
        return (av.shape, av.dtype, av.weak_type)
    return (t.shape_tuple(), np.dtype(a.dtype), False)


def _make_out_tensors(op_name, tensor_inputs, out_arrays, multi, needs_grad,
                      vjp_fn, pure_fn):
    out_tensors = []
    if needs_grad:
        node = GradNode(op_name, vjp_fn, tensor_inputs, len(out_arrays),
                        tuple((oa.shape, oa.dtype) for oa in out_arrays),
                        pure_fn=pure_fn, multi_out=multi)
        for i, oa in enumerate(out_arrays):
            t = Tensor(oa, stop_gradient=False)
            t._grad_node = node
            t._grad_index = i
            out_tensors.append(t)
    else:
        for oa in out_arrays:
            out_tensors.append(Tensor(oa, stop_gradient=True))
    return out_tensors


_UNCACHED = object()  # _apply_cached verdict: run the uncached path


def _concrete_dispatch(ts, arrays) -> bool:
    """True when every input is a concrete array and no functionalization
    seam is live — the only state in which re-executing on another device
    is meaningful (symbolic values cannot be ``device_put``)."""
    if ts is not None:
        return False
    for a in arrays:
        if _is_tracer(a) or type(a).__name__ == "LazyValue":
            return False
    return True


def _dispatch_execute(op_name: str, f: Callable, arrays, needs_grad: bool,
                      ts):
    """Run one op's pure fn (with ``jax.vjp`` when grad is needed), with
    backend fallback: a primitive with no TPU lowering degrades to a CPU
    re-execution instead of crashing the program (core/fallback.py — the
    KernelFactory-fallback analogue). Returns ``(outs, vjp_fn)``.

    ``dispatch.lower`` / ``dispatch.execute`` are resilience fault sites:
    CPU-only CI installs a FaultSchedule raising e.g. NotImplementedError
    here to drive the full degrade-warn-count-cache sequence
    deterministically (tests/test_fallback.py).
    """
    if (_fallback.should_fallback(op_name)
            and _concrete_dispatch(ts, arrays)):
        # registry/denylist short-circuit: the doomed TPU compile is
        # skipped entirely — this is what makes the SECOND call cheap
        return _fallback.run_cpu(op_name, f, arrays, needs_grad)
    try:
        _fault_point("dispatch.lower")
        if needs_grad:
            outs, vjp_fn = jax.vjp(f, *arrays)
        else:
            outs, vjp_fn = f(*arrays), None
        _fault_point("dispatch.execute")
    except Exception as e:
        if not (_fallback.enabled() and _fallback.is_lowering_failure(e)
                and _concrete_dispatch(ts, arrays)):
            raise
        return _fallback.run_cpu(op_name, f, arrays, needs_grad, exc=e)
    return outs, vjp_fn


def _apply_cached(op_name, fn, tensor_inputs, differentiable, amp,
                  static_kwargs):
    """Fast path: dispatch through the signature-keyed compiled-op cache.

    Returns ``_UNCACHED`` whenever the op must see the plain path: any
    tracing/capture seam is live (to_static functionalization, lazy segment
    recording, static-graph capture), an input payload is symbolic, or the
    signature cannot be keyed safely. The caller falls through with NO state
    changed, so the bypass is semantically invisible.
    """
    if (_tracing.trace_state() is not None or _op_graph_hook is not None
            or _lazy.active()):
        _dcache.note_bypass("capture")
        return _UNCACHED
    arrays = []
    for t in tensor_inputs:
        a = t._data
        if _is_tracer(a) or type(a).__name__ == "LazyValue":
            _dcache.note_bypass("symbolic_input")
            return _UNCACHED
        arrays.append(a)

    needs_grad = (differentiable and _tracing.grad_enabled()
                  and any(not t.stop_gradient for t in tensor_inputs))
    st = _tracing.amp_state() if amp else None
    amp_key = st.cache_key if (st is not None and st.enable) else None
    nan_check = _flags.flag("check_nan_inf")
    # backend joins the signature key: an op that fell back to CPU keys
    # separately, so a TPU-compiled callable is never served for it — the
    # fallen-back signature compiles its own CPU executable below
    backend = _fallback.backend_token(op_name)
    fb_cpu = bool(backend)

    in_sigs = tuple(_input_sig(t) for t in tensor_inputs)
    key, reason = _dcache.make_key(op_name, fn, in_sigs, static_kwargs,
                                   amp_key, needs_grad, nan_check,
                                   _flags._EPOCH, backend=backend)
    if key is None:
        _dcache.note_bypass(reason)
        return _UNCACHED

    entry = _dcache.lookup(key)
    if entry is None:
        return _UNCACHED  # cold signature: stay on the uncached path
    fresh = entry is _dcache.NEEDS_COMPILE
    if fresh:
        # signature is warm: resolve autocast targets ONCE, build the
        # compiled pair, and serve this call from it
        cast_targets = _autocast_targets(op_name, arrays) if amp else None
        entry = _dcache.CachedOp(
            _build_pure_fn(fn, cast_targets, static_kwargs), nan_check)

    # fallen-back op: inputs move to host CPU first, so the jitted entry
    # compiles for (and executes on) the CPU backend — committed inputs
    # decide the jit placement — and the key's backend token keeps this
    # executable separate from any TPU-compiled one
    run_arrays = _fallback.to_cpu(arrays) if fb_cpu else arrays
    try:
        outs, finite = entry.fwd(*run_arrays)
        multi = isinstance(outs, tuple)
        out_arrays = outs if multi else (outs,)
        if fresh and needs_grad:
            # snapshot the linearization at dispatch time, like jax.vjp did
            entry.warm_bwd(run_arrays, out_arrays, multi)
    except (jax.errors.JAXTypeError, NotImplementedError):
        if fresh:
            # the fn is legal eagerly but not under jit (it branches on
            # concrete values / lacks an abstract eval): poison the
            # signature so it is never re-traced, and run the plain path —
            # a genuine op error re-raises identically from there
            _dcache.mark_uncacheable(key)
        return _UNCACHED
    except Exception:
        # anything else (transient runtime fault, input-dependent error)
        # must not poison outright: fall through, eager decides. Counted,
        # and poisoned after a few consecutive failures so a persistent
        # non-trace failure can't levy a doomed re-trace per call forever.
        if fresh:
            _dcache.note_compile_failure(key)
        return _UNCACHED
    if fresh:
        _dcache.store(key, entry)
        # ISSUE 16: compile-time cost capture — once per fresh signature,
        # with the run arrays still in scope for spec building; is-None
        # when observability.cost is not installed
        cost_hook = _dcache._cost_hook
        if cost_hook is not None:
            cost_hook("store", key, entry=entry, op=op_name,
                      arrays=run_arrays)
    if finite is not None and not bool(finite):
        raise FloatingPointError(f"op {op_name} produced nan/inf")

    vjp_fn = entry.make_vjp(tuple(run_arrays)) if needs_grad else None
    if fb_cpu:
        _fallback.note_fallback(op_name)  # warn-once for denylist-seeded ops
        _fallback.count_cpu_dispatch(op_name)
        if vjp_fn is not None:
            vjp_fn = _fallback.wrap_vjp(vjp_fn)
        out_arrays = _fallback.from_cpu(out_arrays)
    out_tensors = _make_out_tensors(op_name, tensor_inputs, out_arrays, multi,
                                    needs_grad, vjp_fn, entry.fn)
    if multi:
        return tuple(out_tensors)
    return out_tensors[0]


def _apply_impl(op_name: str, fn: Callable, *tensor_inputs: Tensor,
                differentiable: bool = True, amp: bool = True,
                **static_kwargs) -> Any:
    if _dcache._ENABLED:
        out = _apply_cached(op_name, fn, tensor_inputs, differentiable, amp,
                            static_kwargs)
        if out is not _UNCACHED:
            return out

    ts = _tracing.trace_state()
    arrays = []
    for t in tensor_inputs:
        a = t._data
        if ts is not None and not _is_tracer(a):
            ts.record_read(t)
        arrays.append(a)

    cast_targets = _autocast_targets(op_name, arrays) if amp else None

    needs_grad = (differentiable and _tracing.grad_enabled()
                  and any(not t.stop_gradient for t in tensor_inputs))

    f = _build_pure_fn(fn, cast_targets, static_kwargs)

    if _lazy.active():
        return _lazy_apply(op_name, f, tensor_inputs, arrays, needs_grad)

    outs, vjp_fn = _dispatch_execute(op_name, f, arrays, needs_grad, ts)

    multi = isinstance(outs, tuple)
    out_arrays = outs if multi else (outs,)

    if _flags.flag("check_nan_inf"):
        for oa in out_arrays:
            if not _is_tracer(oa) and jnp.issubdtype(oa.dtype, jnp.inexact):
                if not bool(jnp.all(jnp.isfinite(oa))):
                    raise FloatingPointError(f"op {op_name} produced nan/inf")

    out_tensors = _make_out_tensors(op_name, tensor_inputs, out_arrays, multi,
                                    needs_grad, vjp_fn, f)

    if _op_graph_hook is not None:
        _op_graph_hook(op_name, f, tensor_inputs, tuple(out_tensors))

    if multi:
        return tuple(out_tensors)
    return out_tensors[0]


def register_tensor_method(name: str, fn: Callable) -> None:
    """Install a method on Tensor (ops modules use this to build the ~400
    method surface without circular imports)."""
    setattr(Tensor, name, fn)


# ---------------------------------------------------------------------------
# creation
# ---------------------------------------------------------------------------

def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    """``paddle.to_tensor`` parity."""
    dtype = _dtype.convert_dtype(dtype)
    if isinstance(data, Tensor):
        arr = data._data
        if dtype is not None and arr.dtype != dtype:
            arr = arr.astype(dtype)
        t = Tensor(arr, stop_gradient=stop_gradient)
        return t
    if isinstance(data, (jnp.ndarray, jax.Array)) and not isinstance(data, np.ndarray):
        arr = data
    else:
        np_arr = np.asarray(data)
        if np_arr.dtype == np.float64 and dtype is None:
            np_arr = np_arr.astype(np.float32)
        elif np_arr.dtype == np.int32 and dtype is None and isinstance(data, (int, numbers.Integral)):
            np_arr = np_arr.astype(np.int64)
        arr = np_arr
    if dtype is not None and arr.dtype != dtype:
        if _is_tracer(arr):
            arr = jnp.asarray(arr, dtype=dtype)
        elif isinstance(arr, np.ndarray):
            arr = arr.astype(dtype)
        else:
            # committed jax.Array (device_put upstream or passed in by the
            # caller): cast on device, preserving its placement
            arr = jnp.asarray(arr, dtype=dtype)
    if not _is_tracer(arr):
        if place is not None:
            # explicit placement commits the array to that device
            arr = _device.device_put(arr, _parse_place(place))
        else:
            cur = _device.current_place()
            default_platform = "cpu" if not _device.is_compiled_with_tpu() else "tpu"
            if cur.device_type != default_platform or cur.device_id != 0:
                arr = _device.device_put(arr, cur)
            else:
                # UNCOMMITTED on the default device: lets eager ops mix with
                # mesh-committed (sharded) arrays without transfer errors
                arr = jnp.asarray(arr)
    return Tensor(arr, stop_gradient=stop_gradient)


# Tensor.is_floating_point()/is_integer()/is_complex() methods (upstream
# exposes these both as paddle.* functions and as Tensor methods)
register_tensor_method("is_floating_point",
                       lambda self: _dtype.is_floating_point(self.dtype))
register_tensor_method("is_integer",
                       lambda self: _dtype.is_integer(self.dtype))
register_tensor_method("is_complex",
                       lambda self: _dtype.is_complex(self.dtype))
