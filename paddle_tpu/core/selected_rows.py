"""SelectedRows-analogue sparse gradients.

Parity surface: ``paddle/phi/core/selected_rows.h`` + the sparse-grad path of
``lookup_table``/embedding (upstream: embedding with ``sparse=True`` emits a
SelectedRows gradient — (rows, values) — which GradientAccumulator keeps
sparse and the optimizers apply row-wise; the PS Communicator ships it as
push_sparse traffic).

TPU-native design: a gradient for an (vocab, dim) embedding touched by N
ids is carried as ``rows: (N,) int32`` + ``values: (N, dim)`` — never the
dense (vocab, dim) scatter. Accumulation across microbatches/uses is LAZY
concatenation (O(sum N), no vocab-sized buffer, and fully static-shaped so
it works inside ``to_static`` traces). Consumers:

* sparse-aware optimizers (SGD row update; Adam ``lazy_mode``) merge
  duplicate rows with a size-padded ``jnp.unique`` + segment-sum (static
  shapes, jit-safe) and scatter-add only the touched rows;
* the PS ``Communicator.push_sparse`` ships (rows, values) directly;
* everything else reads ``grad._data``, which densifies once on demand —
  dense consumers keep working unchanged.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["SelectedRows", "SelectedRowsTensor"]


class SelectedRows:
    """(rows, values) sparse rows of a dense ``dense_shape`` tensor."""

    __slots__ = ("rows", "values", "dense_shape")

    def __init__(self, rows, values, dense_shape: Tuple[int, ...]):
        self.rows = rows
        self.values = values
        self.dense_shape = tuple(dense_shape)

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def shape(self):
        return self.dense_shape

    def astype(self, dtype) -> "SelectedRows":
        return SelectedRows(self.rows, self.values.astype(dtype),
                            self.dense_shape)

    def concat(self, other: "SelectedRows") -> "SelectedRows":
        """Lazy accumulation: duplicate rows are allowed (scatter-add and
        the merged consumers sum them)."""
        if other.dense_shape != self.dense_shape:
            raise ValueError(
                f"SelectedRows shape mismatch: {self.dense_shape} vs "
                f"{other.dense_shape}")
        return SelectedRows(jnp.concatenate([self.rows, other.rows]),
                            jnp.concatenate([self.values, other.values]),
                            self.dense_shape)

    def scale(self, s) -> "SelectedRows":
        return SelectedRows(self.rows, self.values * s, self.dense_shape)

    def to_dense(self):
        out = jnp.zeros(self.dense_shape, self.values.dtype)
        return out.at[self.rows].add(self.values)

    def merged(self) -> "SelectedRows":
        """Deduplicate rows (values summed). Static-shaped (padded unique):
        output keeps N slots; tail slots point at a guaranteed-unused
        sentinel row index with zero values, so row-wise consumers can
        scatter them harmlessly out of range (jit-safe: jnp clips/drops
        out-of-bounds scatter indices)."""
        n = self.rows.shape[0]
        sentinel = self.dense_shape[0]  # one past the last valid row
        uniq, inv = jnp.unique(self.rows, size=n, fill_value=sentinel,
                               return_inverse=True)
        summed = jax.ops.segment_sum(self.values, inv.reshape(-1),
                                     num_segments=n)
        return SelectedRows(uniq, summed, self.dense_shape)

    def __repr__(self):
        return (f"SelectedRows(rows={self.rows.shape}, "
                f"values={self.values.shape}, dense={self.dense_shape})")


class SelectedRowsTensor:
    """``param.grad`` holder for sparse gradients.

    Duck-types the slice of the Tensor surface gradient consumers touch;
    ``._data`` densifies ON DEMAND (cached), so dense-only consumers work
    transparently while sparse-aware ones (optimizer lazy paths, the PS
    communicator) read ``.selected_rows`` and never pay the dense cost.
    """

    def __init__(self, sr: SelectedRows, name: Optional[str] = None):
        self._sr: Optional[SelectedRows] = sr
        self._dense: Optional[jax.Array] = None
        self.name = name
        self.stop_gradient = True
        self.persistable = False

    # -- sparse surface ------------------------------------------------------
    def is_selected_rows(self) -> bool:
        return self._sr is not None

    @property
    def selected_rows(self) -> Optional[SelectedRows]:
        return self._sr

    def accumulate_sparse(self, sr: SelectedRows) -> None:
        if self._dense is not None:
            # the dense copy is authoritative from here on: keeping _sr
            # would leave a stale sparse view missing these rows while
            # is_selected_rows() still answered True
            self._dense = self._dense + sr.to_dense()
            self._sr = None
        else:
            self._sr = self._sr.concat(sr)

    def accumulate_dense(self, g) -> None:
        self._dense = self._data + g
        self._sr = None

    # -- dense (Tensor-compatible) surface -----------------------------------
    @property
    def _data(self):
        if self._dense is None:
            self._dense = self._sr.to_dense()
        return self._dense

    @_data.setter
    def _data(self, value):
        self._dense = value
        self._sr = None

    def _set_data(self, value) -> None:
        self._data = value

    @property
    def dtype(self):
        return self._sr.dtype if self._sr is not None else self._dense.dtype

    @property
    def shape(self):
        return (self._sr.dense_shape if self._sr is not None
                else tuple(self._dense.shape))

    def numpy(self):
        import numpy as np
        return np.asarray(self._data)

    def __repr__(self):
        return f"SelectedRowsTensor({self._sr if self._sr is not None else self._dense.shape})"
