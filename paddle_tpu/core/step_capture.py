"""Whole-step static capture with buffer donation (ISSUE 11).

The survey's CINN→XLA thesis is that Paddle-on-TPU wins by compiling whole
PROGRAMS, not ops: the eager fast path (PR 2) amortizes per-op dispatch
behind a signature-keyed compiled-op cache, but a train step still pays one
host dispatch per op plus inter-op materialization. This module captures
the ENTIRE train step — forward, backward, optimizer update (q8/Adam
including the fused Pallas path), with the LR schedule riding as carried
state — into ONE ``jax.jit`` program with ``donate_argnums`` on every
registered state tensor (parameters, optimizer moments/masters, the RNG
key), built on the ``to_static`` functionalization (PR 10's ``TrainState``
already enumerates every piece of carried state, which is what makes the
donation safe: each state tensor is rebound to a live output buffer after
every call).

:class:`CapturedStep` (surfaced as ``paddle_tpu.jit.capture_step``) is the
per-run handle; ``hapi.Model.fit`` and ``resilience.TrainingSupervisor``
route over it behind ``PADDLE_TPU_STEP_CAPTURE``:

* ``auto`` (default) — capture when safe, bypass cleanly (and visibly)
  when a functionalization seam is already live (``to_static`` trace, lazy
  segment recording, static-graph capture — the PR 2 "capture" bypass
  accounting counts the per-op side of this), when an input payload is
  symbolic, when a fault schedule targets the in-trace ``dispatch.*``
  seams (injected per-op faults must keep firing per op, not once at
  trace), or when the step cannot trace (memoized per signature).
* ``off`` — the eager tier, unchanged dispatch; the debug escape hatch.

Re-traces are keyed on the PR 2 structural signature (code objects +
hashable closure state of the step/update closures) + the runtime
flags-epoch + input avals, so a shape change, a mutated closure scalar, or
a ``set_flags`` write can never serve a stale executable. Counters:
``train.capture_hits_total`` / ``train.capture_retraces_total`` /
``train.capture_bypasses_total{reason}`` and the
``train.capture_donated_bytes`` gauge.

NaN-gating (the supervisor contract "a non-finite loss withholds the
update"): when ``update_fn`` is folded in with ``nan_gate=True``, the
update's state writes are selected per-tensor with
``where(isfinite(loss), new, old)`` INSIDE the program — a skipped batch
leaves parameters, moments, step count and RNG key bitwise untouched,
exactly like the eager skip path, without a host round-trip.

Numerics contract (measured, honest): a captured step is bitwise
DETERMINISTIC — same program, same inputs, same bits — so restart/resume
within the captured tier is bit-identical (the PR 10 guarantee). A
captured step is NOT bitwise-equal to the eager tier: XLA contracts
``a*x + b*y`` chains to FMA inside a fused whole-step kernel, which per-op
dispatch cannot (micro-repro: ``jit(lambda: b1*m + (1-b1)*g)`` differs
from the op-by-op value by 1 ulp; ``--xla_allow_excess_precision=false``
does not restore equality). Eager↔captured parity is therefore pinned at
ulp-scale tolerance in tests, and a checkpoint must be resumed under the
same tier it was written from for bitwise continuation.

Host-written state (the stale-constant trap): a per-step ``update_fn``
that computes a state value in PYTHON and writes it (the classic case:
``scheduler.step()`` inside the update — ``_sync_lr_tensor`` writes
``opt_lr`` from a host float) would bake the trace-time value into the
executable and silently serve it forever. Capture detects any registered
state tensor whose post-step payload is concrete (not a tracer) DURING
tracing and raises :class:`HostStateWriteError` before anything executes
— loud and uniform, never stale. The fix is to keep ``scheduler.step()``
outside the captured step: the LR VALUE rides the program as carried
state (``opt._lr_t``), so the host-side schedule advance between steps is
picked up by the next call with no retrace.
"""

from __future__ import annotations

import os
import threading
import types
import warnings
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import flags as _flags
from .. import observability as _obs
from ..observability import trace as _trace
from ..resilience import faults as _faults
from . import dispatch_cache as _dcache
from . import lazy as _lazy
from . import tensor as _tensor_mod
from . import tracing as _tracing
from .tensor import Tensor, _is_tracer, _state_registry

__all__ = ["CapturedStep", "HostStateWriteError", "capture_step", "mode",
           "capture_info", "stats_clear"]


class HostStateWriteError(RuntimeError):
    """The captured step writes a registered state tensor from a
    host-computed (concrete) value. Replaying the compiled program would
    serve the trace-time constant forever — e.g. ``scheduler.step()``
    inside the captured update freezes the LR. Move the host-side write
    outside the captured step (the LR schedule's VALUE already rides as
    carried state), or run with ``PADDLE_TPU_STEP_CAPTURE=off``."""


_VALID_MODES = ("auto", "off")


def mode() -> str:
    """Resolve ``PADDLE_TPU_STEP_CAPTURE`` (default ``auto``)."""
    m = os.environ.get("PADDLE_TPU_STEP_CAPTURE", "auto").strip().lower()
    if m in _VALID_MODES:
        return m
    if m in ("0", "false", "no", "disable", "disabled"):
        return "off"
    return "auto"


# process-global counters (always maintained — the observability mirror
# no-ops while metrics are disabled, like the PR 2 dispatch-cache stats)
_LOCK = threading.Lock()
_STATS: Dict[str, Any] = {"hits": 0, "retraces": 0, "bypasses": {},
                          "donated_bytes": 0}


def _count(kind: str, reason: Optional[str] = None) -> None:
    with _LOCK:
        if kind == "bypass":
            b = _STATS["bypasses"]
            b[reason] = b.get(reason, 0) + 1
        else:
            _STATS[kind] += 1
    if kind == "bypass":
        _obs.inc("train.capture_bypasses_total", reason=reason or "other")
    elif kind == "hits":
        _obs.inc("train.capture_hits_total")
    else:
        _obs.inc("train.capture_retraces_total")


def capture_info() -> Dict[str, Any]:
    with _LOCK:
        return {"hits": _STATS["hits"], "retraces": _STATS["retraces"],
                "bypasses": dict(_STATS["bypasses"]),
                "donated_bytes": _STATS["donated_bytes"]}


def stats_clear() -> None:
    with _LOCK:
        _STATS.update(hits=0, retraces=0, bypasses={}, donated_bytes=0)


# ---------------------------------------------------------------------------
# structural signature (the PR 2 fingerprint, made total)
# ---------------------------------------------------------------------------

class _IdKey:
    """Identity wrapper for closure values the PR 2 fingerprint refuses
    (arrays, tensors, layers, optimizers): hashable, equal only to itself,
    and holding a strong ref so the id can never be reused while the key
    lives. Identity keying is stable for per-run closures — a NEW closure
    over a NEW model simply keys a new program."""

    __slots__ = ("obj",)

    def __init__(self, obj):
        self.obj = obj

    def __hash__(self):
        return id(self.obj)

    def __eq__(self, other):
        return isinstance(other, _IdKey) and other.obj is self.obj


def _lenient_fp(v, depth: int = 0):
    """Value fingerprint: content-keyed where the PR 2 rules allow (python
    scalars, tuples, dicts — a mutated closure scalar retraces rather than
    serving a stale program), identity-keyed where they bypass."""
    try:
        return _dcache._fp_value(v, depth)
    except (_dcache._Bypass, TypeError):
        return _IdKey(v)


def _structural_sig(fn) -> Any:
    """The step/update closure's structural signature: code object +
    per-cell closure fingerprints + defaults (the PR 2 ``_fp_fn`` walk,
    with the lenient per-value fallback above)."""
    if fn is None:
        return None
    if not isinstance(fn, types.FunctionType):
        return _IdKey(fn)
    parts = [fn.__code__]
    for cell in fn.__closure__ or ():
        try:
            parts.append(_lenient_fp(cell.cell_contents))
        except ValueError:  # empty cell
            parts.append(("E",))
    if fn.__defaults__:
        parts.append(tuple(_lenient_fp(d) for d in fn.__defaults__))
    if fn.__kwdefaults__:
        parts.append(tuple(sorted(
            (k, _lenient_fp(d)) for k, d in fn.__kwdefaults__.items())))
    return tuple(parts)


def _loss_array(out):
    """The loss payload out of whatever the step closure returned (the
    supervisor's ``_loss_value`` coercion, minus the host read)."""
    if isinstance(out, (tuple, list)):
        if not out:
            raise ValueError("captured step returned an empty loss sequence")
        out = out[0]
    if out is None:
        raise ValueError("captured step must return the step's loss")
    return out._data if isinstance(out, Tensor) else out


def _is_sym(a) -> bool:
    return _is_tracer(a) or type(a).__name__ == "LazyValue"


# ---------------------------------------------------------------------------
# the captured step
# ---------------------------------------------------------------------------

class CapturedStep:
    """One train step as one compiled, donated-buffer XLA program.

    ``step_fn(*args) -> loss`` (or ``(loss, extras...)``) runs forward +
    backward. ``update_fn`` (optional) is folded INTO the program —
    callers that fold it must keep it pure tensor math over carried state
    (the optimizer update qualifies; per-step host Python like
    ``scheduler.step()`` does not and raises
    :class:`HostStateWriteError`). ``nan_gate=True`` makes the folded
    update conditional on ``isfinite(loss)`` in-program (the supervisor's
    skip-batch contract). ``iters_per_call`` scans the step over K-stacked
    args inside one program (the bench's scan-over-steps pattern;
    incompatible with ``nan_gate``).

    Bypasses run the step eagerly with identical semantics (update applied
    iff the gate passes), so callers never branch on the tier.
    """

    _MAX_PROGRAMS = 8  # distinct (signature, flags-epoch, avals) programs

    def __init__(self, step_fn: Callable, *,
                 update_fn: Optional[Callable[[], None]] = None,
                 clear_fn: Optional[Callable[[], None]] = None,
                 nan_gate: bool = False, iters_per_call: int = 1,
                 donate: bool = True, mode: Optional[str] = None,
                 label: str = "train"):
        if nan_gate and update_fn is None:
            raise ValueError("nan_gate requires update_fn (the gate decides "
                             "whether the folded update applies)")
        if nan_gate and iters_per_call > 1:
            raise ValueError("nan_gate is a per-step host contract; it "
                             "cannot ride a scanned multi-step program")
        self._step_fn = step_fn
        self._update_fn = update_fn
        self._clear_fn = clear_fn
        self._nan_gate = bool(nan_gate)
        self._iters = int(iters_per_call)
        self._donate = bool(donate)
        self._mode = globals()["mode"]() if mode is None else mode
        self._label = label
        self._programs: "OrderedDict[Any, Any]" = OrderedDict()
        self._dead: set = set()  # keys whose trace failed: eager forever
        self._warned = False
        self.stats = {"hits": 0, "retraces": 0, "bypasses": {}}
        self.donated_bytes = 0

    @property
    def applies_update(self) -> bool:
        """True when the optimizer update is folded into this step (the
        caller must NOT apply it again)."""
        return self._update_fn is not None

    # -- accounting ----------------------------------------------------------
    def _note(self, kind: str, reason: Optional[str] = None) -> None:
        if kind == "bypass":
            b = self.stats["bypasses"]
            b[reason] = b.get(reason, 0) + 1
        else:
            self.stats[kind] += 1
        _count(kind if kind != "bypass" else "bypass", reason)

    # -- the call ------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        reason = self._bypass_reason(args, kwargs)
        if reason is not None:
            self._note("bypass", reason)
            return self._eager_step(args, kwargs)
        key = self._key(args, kwargs)
        if key is None:
            self._note("bypass", "symbolic_input")
            return self._eager_step(args, kwargs)
        if key in self._dead:
            self._note("bypass", "untraceable")
            return self._eager_step(args, kwargs)
        sf = self._programs.get(key)
        fresh = sf is None
        if fresh:
            from ..jit.to_static import StaticFunction
            sf = StaticFunction(self._program_fn, donate_states=self._donate,
                                iters_per_call=self._iters)
            # ISSUE 16: file this program's cost record under the training
            # step, not a generic "jit" entry
            sf.cost_site = "train.step"
            sf.cost_label = self._label
            self._programs[key] = sf
            if len(self._programs) > self._MAX_PROGRAMS:
                # the popped StaticFunction's weakref finalizer retires its
                # cost records with it
                self._programs.popitem(last=False)
            self._set_donated_bytes()
        else:
            self._programs.move_to_end(key)
        try:
            # span-discipline: this __call__ is a fast_path_roots entry, so
            # even the disabled-mode span probe stays behind the explicit
            # enabled() guard (the _op_metrics_hook discipline)
            if _trace.enabled():
                with _trace.span("train.captured_step", label=self._label,
                                 fresh=fresh):
                    out = sf(*args, **kwargs)
            else:
                out = sf(*args, **kwargs)
        except HostStateWriteError:
            raise  # deliberate, loud: never demote to a silently-stale tier
        except Exception as e:
            from ..jit.to_static import _is_trace_failure
            if not _is_trace_failure(e):
                raise  # runtime failure (XLA error, device fault): surface —
                #        the supervisor's restore-last-good owns recovery
            # the step cannot trace (tensor-dependent python control flow,
            # host read mid-step): memoize and stay eager for this signature
            # — trace-time tensor state was restored by the functionalizer,
            # so the eager re-run below is the step's one real execution
            self._programs.pop(key, None)
            self._dead.add(key)
            self._note("bypass", "untraceable")
            if not self._warned:
                self._warned = True
                warnings.warn(
                    f"step capture ({self._label}): the step cannot be "
                    f"captured ({type(e).__name__}: {e}); this signature "
                    f"runs on the eager tier")
            return self._eager_step(args, kwargs)
        self._note("retraces" if fresh else "hits")
        return out

    # -- bypass policy -------------------------------------------------------
    def _bypass_reason(self, args, kwargs) -> Optional[str]:
        if self._mode == "off":
            return "off"
        if (_tracing.trace_state() is not None or _lazy.active()
                or _tensor_mod._op_graph_hook is not None):
            # a functionalization seam is already live: capture-inside-
            # capture would fight over the same mutation log (the per-op
            # half of this is the PR 2 "capture" bypass accounting)
            return "capture_seam"
        sched = _faults._SCHEDULE
        if sched is not None and any(
                s.startswith("dispatch.") for s in sched.sites()):
            # injected per-op faults must keep firing per op; inside a
            # compiled program the dispatch seams run only at trace time
            return "fault_injection"
        return None

    def _key(self, args, kwargs):
        # the structural signature is rebuilt per call (the PR 2 contract:
        # closure CONTENT keys the program — a mutated python scalar in the
        # step's closure must retire the executable, never serve the baked
        # constant); identity-keyed leaves make the walk cheap, and it runs
        # once per STEP, not per op
        fn_sig = (_structural_sig(self._step_fn),
                  _structural_sig(self._update_fn),
                  self._nan_gate, self._iters)
        leaves, treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        sigs = []
        for leaf in leaves:
            if isinstance(leaf, Tensor):
                a = leaf._data
                if _is_sym(a):
                    return None
                sigs.append(("t", a.shape, str(a.dtype)))
            elif isinstance(leaf, (jax.Array, np.ndarray)):
                if _is_sym(leaf):
                    return None
                sigs.append(("a", leaf.shape, str(leaf.dtype)))
            else:
                try:
                    hash(leaf)
                    sigs.append(("s", leaf))
                except TypeError:
                    sigs.append(("s", repr(leaf)))
        return (fn_sig, _flags.epoch(), treedef, tuple(sigs))

    # -- program body (runs under the to_static functionalization) -----------
    def _program_fn(self, *args, **kwargs):
        states = _state_registry.alive()
        entry = {id(t): t._data for t in states}
        out = self._step_fn(*args, **kwargs)
        if self._update_fn is not None:
            if self._nan_gate:
                # gate on the SAME value the supervisor (and the eager
                # bypass below) reads — the first element of the loss —
                # not an all() over a vector loss: the tiers must agree
                # on whether the update applied, and the supervisor's
                # skip accounting keys on that exact scalar
                finite = jnp.isfinite(jnp.ravel(jnp.asarray(
                    _loss_array(out), jnp.float32))[0])
                pre = [(t, t._data) for t in states]
                self._update_fn()
                for t, old in pre:
                    new = t._data
                    if new is not old and not _is_tracer(new):
                        continue  # reported by the walk below
                    if new is not old:
                        # withheld update == bitwise-untouched state: the
                        # eager skip path's exact contract, in-program
                        t._set_data(jnp.where(finite, new, old))
            else:
                self._update_fn()
        bad = [t.name or "unnamed" for t in states
               if t._data is not entry.get(id(t), t._data)
               and not _is_tracer(t._data)]
        if bad:
            raise HostStateWriteError(
                f"captured step writes state from host-computed values "
                f"({', '.join(sorted(bad))}); replaying the program would "
                f"serve the trace-time constant forever — keep per-step "
                f"host writes (e.g. scheduler.step()) outside the captured "
                f"step, or set PADDLE_TPU_STEP_CAPTURE=off")
        return out

    # -- eager tier ----------------------------------------------------------
    def _eager_step(self, args, kwargs):
        if self._iters > 1:
            return self._eager_iters(args, kwargs)
        out = self._step_fn(*args, **kwargs)
        if self._update_fn is not None:
            if self._nan_gate:
                lossf = float(np.asarray(_loss_array(out)).ravel()[0])
                if np.isfinite(lossf):
                    self._update_fn()
                elif self._clear_fn is not None:
                    self._clear_fn()
            else:
                self._update_fn()
        return out

    def _eager_iters(self, args, kwargs):
        """Slice the K-stacked args and run the step per iteration (the
        ``StaticFunction._run_iters_eager`` semantics, so a bypassed scan
        keeps the compiled run's meaning)."""
        def is_leaf(x):
            return isinstance(x, Tensor)

        def slice_at(i):
            def f(x):
                if isinstance(x, Tensor):
                    return x[i]
                if isinstance(x, (jax.Array, np.ndarray)) \
                        and getattr(x, "ndim", 0) > 0:
                    return x[i]
                return x
            return f

        outs = []
        for i in range(self._iters):
            a_i, k_i = jax.tree_util.tree_map(
                slice_at(i), (args, kwargs), is_leaf=is_leaf)
            outs.append(self._eager_step_once(a_i, k_i))

        def stack(*xs):
            if isinstance(xs[0], Tensor):
                return Tensor(jnp.stack([x._data for x in xs]),
                              stop_gradient=True)
            if isinstance(xs[0], (jax.Array, np.ndarray)):
                return jnp.stack([jnp.asarray(x) for x in xs])
            return xs[0]

        return jax.tree_util.tree_map(stack, *outs, is_leaf=is_leaf)

    def _eager_step_once(self, args, kwargs):
        out = self._step_fn(*args, **kwargs)
        if self._update_fn is not None:
            self._update_fn()
        return out

    # -- observability -------------------------------------------------------
    def _set_donated_bytes(self) -> None:
        if not self._donate:
            return
        total = 0
        for t in _state_registry.alive():
            a = t._data
            shape = getattr(a, "shape", None)
            if shape is None or _is_sym(a):
                continue
            n = 1
            for s in shape:
                n *= int(s)
            total += n * jnp.dtype(a.dtype).itemsize
        self.donated_bytes = total
        with _LOCK:
            _STATS["donated_bytes"] = total
        _obs.set_gauge("train.capture_donated_bytes", float(total))


def capture_step(step_fn: Callable, *,
                 update_fn: Optional[Callable[[], None]] = None,
                 clear_fn: Optional[Callable[[], None]] = None,
                 nan_gate: bool = False, iters_per_call: int = 1,
                 donate: bool = True) -> CapturedStep:
    """Capture a train step as ONE donated-buffer XLA program.

    ``paddle_tpu.jit.capture_step`` — see :class:`CapturedStep`. Honors
    ``PADDLE_TPU_STEP_CAPTURE`` (``off`` keeps every call on the eager
    debug tier with identical semantics)."""
    return CapturedStep(step_fn, update_fn=update_fn, clear_fn=clear_fn,
                        nan_gate=nan_gate, iters_per_call=iters_per_call,
                        donate=donate)
