"""Define-by-run autograd tape.

Capability parity with the reference's eager autograd engine (upstream:
paddle/fluid/eager/ — ``GradNodeBase``, ``Edge``, ``egr::Backward`` topological
queue, ``GradientAccumulator``). TPU-native design: instead of per-op C++ grad
kernels, each forward op captures its vjp through ``jax.vjp`` at dispatch time
(linearization is itself jax-traced, so under ``to_static`` the whole tape
inlines into one XLA program). ``backward`` walks nodes in reverse creation
order — a valid topological order for a tape — accumulating cotangents.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = ["GradNode", "backward", "grad"]

_node_counter = itertools.count()
_detect_anomaly = False  # toggled by paddle.autograd.set_detect_anomaly


class GradNode:
    """One recorded op on the tape (analogue of ``GradNodeBase``).

    Input grad linkage (``Edge``s) is SNAPSHOTTED at record time — in-place
    ops rebind a tensor onto the node they just produced, so reading the
    *current* ``_grad_node`` of an input during backward would find a cycle.

    ``vjp_fn`` contract: callable taking the output cotangent structure
    (tuple iff ``multi_out``) and returning one cotangent per input, where a
    non-differentiable input may come back as ``jax.dtypes.float0`` or
    ``None`` — both skipped by ``backward``. Eager dispatch records a fresh
    ``jax.vjp`` closure; the compiled-op cache (core/dispatch_cache.py)
    instead hands the tape a cached jitted backward that re-linearizes the
    op at its primals inside ONE compiled program per signature, so
    repeated-signature backward pays no per-call retrace.
    """

    __slots__ = ("id", "op_name", "vjp_fn", "pure_fn", "inputs",
                 "input_links", "n_outputs", "out_avals", "released",
                 "multi_out")

    def __init__(self, op_name: str, vjp_fn, inputs: Sequence[Any],
                 n_outputs: int, out_avals, pure_fn=None, multi_out=None):
        self.id = next(_node_counter)
        self.op_name = op_name
        self.vjp_fn = vjp_fn
        # the op's pure array->array fn; kept so create_graph=True can
        # re-linearize the op as a function of its primals (the captured
        # vjp_fn closes over them as constants, which double-grad can't use)
        self.pure_fn = pure_fn
        self.inputs = tuple(inputs)  # input Tensors (strong refs keep graph alive)
        # (tensor, producing node or None, output slot) captured NOW:
        self.input_links = tuple(
            (t, t._grad_node, t._grad_index) for t in inputs)
        self.n_outputs = n_outputs
        self.out_avals = out_avals  # (shape, dtype) per output for zero-fill
        # whether pure_fn returns a tuple (vjp cotangent structure must match)
        self.multi_out = n_outputs > 1 if multi_out is None else multi_out
        self.released = False

    def release(self) -> None:
        self.vjp_fn = None
        self.pure_fn = None
        self.inputs = ()
        self.input_links = ()
        self.released = True

    def __repr__(self):
        return f"GradNode<{self.op_name}#{self.id}>"


def _topo_nodes(roots: Sequence[GradNode]) -> List[GradNode]:
    """All reachable nodes, descending creation id (reverse topological)."""
    seen: Dict[int, GradNode] = {}
    stack = list(roots)
    while stack:
        node = stack.pop()
        if node.id in seen:
            continue
        seen[node.id] = node
        for _, n, _idx in node.input_links:
            if n is not None and n.id not in seen:
                stack.append(n)
    return [seen[i] for i in sorted(seen, reverse=True)]


def backward(tensors, grad_tensors=None, retain_graph: bool = False,
             create_graph: bool = False, _leaf_set: Optional[set] = None) -> None:
    """``paddle.autograd.backward`` / ``Tensor.backward``.

    Seeds the output cotangents (ones for scalar losses), walks the tape in
    reverse creation order, and accumulates leaf gradients into ``.grad``.
    ``create_graph=True`` records the backward pass itself on the tape (each
    node's vjp re-dispatches through the op layer), enabling grad-of-grad.
    ``_leaf_set`` restricts which leaves receive ``.grad`` (paddle.grad).
    """
    from .tensor import Tensor  # local import to avoid cycle

    from . import lazy as _lazy
    if create_graph or _lazy.active():
        # lazy segment mode records nodes without a materialized vjp_fn;
        # the tensor-space path re-dispatches each node's vjp through
        # apply(), so backward ops join the recorded segment
        _backward_create_graph(tensors, grad_tensors, retain_graph, _leaf_set)
        return

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    # cotangent store: node id -> list per output slot
    cotangents: Dict[int, List[Optional[jnp.ndarray]]] = {}
    roots: List[GradNode] = []

    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient:
            raise RuntimeError(
                "backward() called on a tensor with stop_gradient=True; "
                "it is not connected to the autograd graph")
        seed = g._data if isinstance(g, Tensor) else g
        if seed is None:
            if t._data.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    "pass grad_tensors for non-scalar backward()")
            seed = jnp.ones_like(t._data)
        node, idx = t._grad_node, t._grad_index
        if node is None:
            _accumulate_leaf(t, seed, _leaf_set)
            continue
        slots = cotangents.setdefault(node.id, [None] * node.n_outputs)
        slots[idx] = seed if slots[idx] is None else slots[idx] + seed
        roots.append(node)

    for node in _topo_nodes(roots):
        slots = cotangents.pop(node.id, None)
        if slots is None:
            continue
        if node.released:
            raise RuntimeError(
                f"trying to backward through {node} a second time; "
                "set retain_graph=True to allow this")
        filled = [
            s if s is not None else jnp.zeros(av[0], av[1])
            for s, av in zip(slots, node.out_avals)
        ]
        in_grads = node.vjp_fn(tuple(filled) if node.multi_out else filled[0])
        if _detect_anomaly:
            for g in in_grads:
                if g is not None and hasattr(g, "dtype") and \
                        jnp.issubdtype(g.dtype, jnp.floating) and \
                        not bool(jnp.isfinite(g).all()):
                    raise RuntimeError(
                        f"anomaly detected: non-finite gradient produced by "
                        f"{node} (enable via set_detect_anomaly)")
        for (t, sub, slot), g in zip(node.input_links, in_grads):
            if t.stop_gradient or g is None:
                continue
            if getattr(g, "dtype", None) is not None and g.dtype == jax.dtypes.float0:
                continue  # non-differentiable (integer) input
            g = _apply_hooks(t, g)
            if sub is None:
                _accumulate_leaf(t, g, _leaf_set)
            else:
                sl = cotangents.setdefault(sub.id, [None] * sub.n_outputs)
                sl[slot] = g if sl[slot] is None else sl[slot] + g
        if not retain_graph:
            node.release()


def _accumulate_leaf(t, g, leaf_set: Optional[set] = None) -> None:
    """GradientAccumulator parity: sum into ``.grad`` in place. Sparse
    (SelectedRows) gradients stay sparse: accumulation concatenates rows
    lazily; mixing with a dense gradient densifies (upstream
    GradientAccumulator does the same merge)."""
    from .selected_rows import SelectedRows, SelectedRowsTensor
    from .tensor import Tensor

    if leaf_set is not None and id(t) not in leaf_set:
        return

    if isinstance(g, SelectedRows):
        if g.dtype != t._data.dtype and \
                jnp.issubdtype(t._data.dtype, jnp.floating):
            g = g.astype(t._data.dtype)
        if t.grad is None:
            t.grad = SelectedRowsTensor(g, name=(t.name or "tensor") + "@GRAD")
        elif isinstance(t.grad, SelectedRowsTensor):
            t.grad.accumulate_sparse(g)
        else:
            t.grad._set_data(t.grad._data + g.to_dense())
        return

    if g.dtype != t._data.dtype and jnp.issubdtype(t._data.dtype, jnp.floating):
        g = g.astype(t._data.dtype)
    if t.grad is None:
        gt = Tensor(g, stop_gradient=True)
        gt.name = (t.name or "tensor") + "@GRAD"
        t.grad = gt
    elif isinstance(t.grad, SelectedRowsTensor):
        t.grad.accumulate_dense(g)
    else:
        t.grad._set_data(t.grad._data + g)


def _backward_create_graph(tensors, grad_tensors, retain_graph: bool,
                           leaf_set: Optional[set]) -> None:
    """Tensor-space backward: cotangents are tape-connected Tensors and each
    node's vjp is re-dispatched through ``apply`` as
    ``grads = vjp(pure_fn at primals)(cotangents)`` — a differentiable op of
    (primals, cotangents), so a further backward() through the produced
    grads works (upstream: double-grad nodes in paddle/fluid/eager/)."""
    from .tensor import Tensor, apply

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    cotangents: Dict[int, List[Optional[Tensor]]] = {}
    roots: List[GradNode] = []

    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient:
            raise RuntimeError(
                "backward() called on a tensor with stop_gradient=True; "
                "it is not connected to the autograd graph")
        if g is None:
            if t._data.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    "pass grad_tensors for non-scalar backward()")
            seed = Tensor(jnp.ones_like(t._data), stop_gradient=True)
        else:
            seed = g if isinstance(g, Tensor) else Tensor(jnp.asarray(g),
                                                          stop_gradient=True)
        node, idx = t._grad_node, t._grad_index
        if node is None:
            _accumulate_leaf_tensor(t, seed, leaf_set)
            continue
        slots = cotangents.setdefault(node.id, [None] * node.n_outputs)
        slots[idx] = seed if slots[idx] is None else slots[idx] + seed
        roots.append(node)

    for node in _topo_nodes(roots):
        slots = cotangents.pop(node.id, None)
        if slots is None:
            continue
        if node.released:
            raise RuntimeError(
                f"trying to backward through {node} a second time; "
                "set retain_graph=True to allow this")
        if node.pure_fn is None:
            raise RuntimeError(
                f"{node} was recorded without its primal function; "
                "create_graph=True needs ops dispatched through apply()")
        filled = [
            s if s is not None else Tensor(jnp.zeros(av[0], av[1]),
                                           stop_gradient=True)
            for s, av in zip(slots, node.out_avals)
        ]
        n_in = len(node.inputs)
        pure_fn = node.pure_fn
        multi_out = node.multi_out

        def grad_fn(*xs_and_cts, _pure_fn=pure_fn, _n_in=n_in,
                    _multi=multi_out):
            xs, cts = xs_and_cts[:_n_in], xs_and_cts[_n_in:]
            _, vjp = jax.vjp(_pure_fn, *xs)
            gs = vjp(tuple(cts) if _multi else cts[0])
            return tuple(gs)

        in_grads = apply(f"{node.op_name}_grad", grad_fn,
                         *node.inputs, *filled)
        if not isinstance(in_grads, tuple):
            in_grads = (in_grads,)
        if _detect_anomaly:
            for g in in_grads:
                gd = getattr(g, "_data", g)
                if gd is not None and hasattr(gd, "dtype") and \
                        jnp.issubdtype(gd.dtype, jnp.floating) and \
                        not bool(jnp.isfinite(gd).all()):
                    raise RuntimeError(
                        f"anomaly detected: non-finite gradient produced by "
                        f"{node} (enable via set_detect_anomaly)")
        for (t, sub, slot), g in zip(node.input_links, in_grads):
            if t.stop_gradient or g is None:
                continue
            gd = g._data if isinstance(g, Tensor) else g
            if getattr(gd, "dtype", None) is not None and                     gd.dtype == jax.dtypes.float0:
                continue  # non-differentiable (integer) input
            for hook in t._hooks.values():
                out = hook(g)
                if out is not None:
                    # hooks may return raw arrays (normal backward accepts
                    # them); normalize back to a tape Tensor
                    g = out if isinstance(out, Tensor) else \
                        Tensor(jnp.asarray(out), stop_gradient=True)
            if sub is None:
                _accumulate_leaf_tensor(t, g, leaf_set)
            else:
                sl = cotangents.setdefault(sub.id, [None] * sub.n_outputs)
                sl[slot] = g if sl[slot] is None else sl[slot] + g
        if not retain_graph:
            node.release()


def _accumulate_leaf_tensor(t, g, leaf_set: Optional[set]) -> None:
    """create_graph accumulation: ``.grad`` stays tape-connected."""
    if leaf_set is not None and id(t) not in leaf_set:
        return
    if g.dtype != t.dtype and jnp.issubdtype(t._data.dtype, jnp.floating):
        g = g.astype(t.dtype)
    if t.grad is None:
        g.name = (t.name or "tensor") + "@GRAD"
        t.grad = g
    else:
        t.grad = t.grad + g


def _apply_hooks(t, g):
    if not t._hooks:
        return g
    from .selected_rows import SelectedRows
    if isinstance(g, SelectedRows):
        g = g.to_dense()  # hooks (DP reducers etc.) see the dense gradient
    for hook in t._hooks.values():
        out = hook(_wrap_hook_arg(t, g))
        if out is not None:
            g = out._data if hasattr(out, "_data") else out
    return g


def _wrap_hook_arg(t, g):
    from .tensor import Tensor

    return Tensor(g, stop_gradient=True)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None, create_graph=False,
         only_inputs=True, allow_unused=False):
    """``paddle.grad``: returns grads of ``outputs`` w.r.t ``inputs`` without
    touching ``.grad`` slots. Implemented by running backward on a shadow
    accumulation map.

    ``create_graph=True`` records the backward itself on the tape, so the
    returned grads are differentiable (grad-of-grad, WGAN-GP penalties).
    """
    from .tensor import Tensor

    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if retain_graph is None:
        retain_graph = bool(create_graph)

    # stash existing .grad, run backward, read, restore (leaf filtering is
    # threaded through as an argument — reentrant, unlike a module global)
    stash = [t.grad for t in inputs]
    for t in inputs:
        t.grad = None
    try:
        backward(outputs, grad_outputs, retain_graph=retain_graph,
                 create_graph=create_graph,
                 _leaf_set={id(t) for t in inputs})
        results = []
        for t in inputs:
            if t.grad is None:
                if not allow_unused:
                    raise RuntimeError(
                        f"one of the input tensors ({t.name}) was not used in the "
                        "graph; pass allow_unused=True to return None for it")
                results.append(None)
            else:
                results.append(t.grad)
    finally:
        for t, old in zip(inputs, stash):
            t.grad = old
    return results
