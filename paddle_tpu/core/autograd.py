"""Define-by-run autograd tape.

Capability parity with the reference's eager autograd engine (upstream:
paddle/fluid/eager/ — ``GradNodeBase``, ``Edge``, ``egr::Backward`` topological
queue, ``GradientAccumulator``). TPU-native design: instead of per-op C++ grad
kernels, each forward op captures its vjp through ``jax.vjp`` at dispatch time
(linearization is itself jax-traced, so under ``to_static`` the whole tape
inlines into one XLA program). ``backward`` walks nodes in reverse creation
order — a valid topological order for a tape — accumulating cotangents.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = ["GradNode", "backward", "grad"]

_node_counter = itertools.count()
_detect_anomaly = False  # toggled by paddle.autograd.set_detect_anomaly

# When non-None, _accumulate_leaf only writes .grad for these tensor ids
# (used by paddle.grad to avoid polluting unrelated leaves).
_leaf_filter: Optional[set] = None


class GradNode:
    """One recorded op on the tape (analogue of ``GradNodeBase``).

    Input grad linkage (``Edge``s) is SNAPSHOTTED at record time — in-place
    ops rebind a tensor onto the node they just produced, so reading the
    *current* ``_grad_node`` of an input during backward would find a cycle.
    """

    __slots__ = ("id", "op_name", "vjp_fn", "inputs", "input_links",
                 "n_outputs", "out_avals", "released")

    def __init__(self, op_name: str, vjp_fn, inputs: Sequence[Any], n_outputs: int, out_avals):
        self.id = next(_node_counter)
        self.op_name = op_name
        self.vjp_fn = vjp_fn
        self.inputs = tuple(inputs)  # input Tensors (strong refs keep graph alive)
        # (tensor, producing node or None, output slot) captured NOW:
        self.input_links = tuple(
            (t, t._grad_node, t._grad_index) for t in inputs)
        self.n_outputs = n_outputs
        self.out_avals = out_avals  # (shape, dtype) per output for zero-fill
        self.released = False

    def release(self) -> None:
        self.vjp_fn = None
        self.inputs = ()
        self.input_links = ()
        self.released = True

    def __repr__(self):
        return f"GradNode<{self.op_name}#{self.id}>"


def _topo_nodes(roots: Sequence[GradNode]) -> List[GradNode]:
    """All reachable nodes, descending creation id (reverse topological)."""
    seen: Dict[int, GradNode] = {}
    stack = list(roots)
    while stack:
        node = stack.pop()
        if node.id in seen:
            continue
        seen[node.id] = node
        for _, n, _idx in node.input_links:
            if n is not None and n.id not in seen:
                stack.append(n)
    return [seen[i] for i in sorted(seen, reverse=True)]


def backward(tensors, grad_tensors=None, retain_graph: bool = False) -> None:
    """``paddle.autograd.backward`` / ``Tensor.backward``.

    Seeds the output cotangents (ones for scalar losses), walks the tape in
    reverse creation order, and accumulates leaf gradients into ``.grad``.
    """
    from .tensor import Tensor  # local import to avoid cycle

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    # cotangent store: node id -> list per output slot
    cotangents: Dict[int, List[Optional[jnp.ndarray]]] = {}
    roots: List[GradNode] = []

    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient:
            raise RuntimeError(
                "backward() called on a tensor with stop_gradient=True; "
                "it is not connected to the autograd graph")
        seed = g._data if isinstance(g, Tensor) else g
        if seed is None:
            if t._data.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    "pass grad_tensors for non-scalar backward()")
            seed = jnp.ones_like(t._data)
        node, idx = t._grad_node, t._grad_index
        if node is None:
            _accumulate_leaf(t, seed)
            continue
        slots = cotangents.setdefault(node.id, [None] * node.n_outputs)
        slots[idx] = seed if slots[idx] is None else slots[idx] + seed
        roots.append(node)

    for node in _topo_nodes(roots):
        slots = cotangents.pop(node.id, None)
        if slots is None:
            continue
        if node.released:
            raise RuntimeError(
                f"trying to backward through {node} a second time; "
                "set retain_graph=True to allow this")
        filled = [
            s if s is not None else jnp.zeros(av[0], av[1])
            for s, av in zip(slots, node.out_avals)
        ]
        in_grads = node.vjp_fn(tuple(filled) if node.n_outputs > 1 else filled[0])
        if _detect_anomaly:
            for g in in_grads:
                if g is not None and hasattr(g, "dtype") and \
                        jnp.issubdtype(g.dtype, jnp.floating) and \
                        not bool(jnp.isfinite(g).all()):
                    raise RuntimeError(
                        f"anomaly detected: non-finite gradient produced by "
                        f"{node} (enable via set_detect_anomaly)")
        for (t, sub, slot), g in zip(node.input_links, in_grads):
            if t.stop_gradient or g is None:
                continue
            if getattr(g, "dtype", None) is not None and g.dtype == jax.dtypes.float0:
                continue  # non-differentiable (integer) input
            g = _apply_hooks(t, g)
            if sub is None:
                _accumulate_leaf(t, g)
            else:
                sl = cotangents.setdefault(sub.id, [None] * sub.n_outputs)
                sl[slot] = g if sl[slot] is None else sl[slot] + g
        if not retain_graph:
            node.release()


def _accumulate_leaf(t, g) -> None:
    """GradientAccumulator parity: sum into ``.grad`` in place."""
    from .tensor import Tensor

    if _leaf_filter is not None and id(t) not in _leaf_filter:
        return

    if g.dtype != t._data.dtype and jnp.issubdtype(t._data.dtype, jnp.floating):
        g = g.astype(t._data.dtype)
    if t.grad is None:
        gt = Tensor(g, stop_gradient=True)
        gt.name = (t.name or "tensor") + "@GRAD"
        t.grad = gt
    else:
        t.grad._set_data(t.grad._data + g)


def _apply_hooks(t, g):
    for hook in t._hooks.values():
        out = hook(_wrap_hook_arg(t, g))
        if out is not None:
            g = out._data if hasattr(out, "_data") else out
    return g


def _wrap_hook_arg(t, g):
    from .tensor import Tensor

    return Tensor(g, stop_gradient=True)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None, create_graph=False,
         only_inputs=True, allow_unused=False):
    """``paddle.grad``: returns grads of ``outputs`` w.r.t ``inputs`` without
    touching ``.grad`` slots. Implemented by running backward on a shadow
    accumulation map.

    Note: ``create_graph=True`` (higher-order grads through the tape) is
    supported by re-dispatching the vjp through the op layer is not yet
    implemented — use ``to_static``/jax.grad composition for higher order.
    """
    from .tensor import Tensor

    if create_graph:
        raise NotImplementedError(
            "create_graph=True is not supported by the eager tape yet; "
            "wrap the computation in paddle.jit.to_static and use jax.grad")
    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if retain_graph is None:
        retain_graph = False

    # stash existing .grad, run backward, read, restore
    global _leaf_filter
    stash = [t.grad for t in inputs]
    for t in inputs:
        t.grad = None
    _leaf_filter = {id(t) for t in inputs}
    try:
        backward(outputs, grad_outputs, retain_graph=retain_graph)
        results = []
        for t in inputs:
            if t.grad is None:
                if not allow_unused:
                    raise RuntimeError(
                        f"one of the input tensors ({t.name}) was not used in the "
                        "graph; pass allow_unused=True to return None for it")
                results.append(None)
            else:
                results.append(t.grad)
    finally:
        _leaf_filter = None
        for t, old in zip(inputs, stash):
            t.grad = old
    return results
