"""Core: tensor, dtype, autograd tape, tracing contexts, RNG."""
