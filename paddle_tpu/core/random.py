"""Global RNG state.

Parity surface: ``paddle.seed`` / generator-per-device (upstream:
paddle/phi/core/generator.h). TPU-native design: the state is a jax PRNG key
held in a registered state Tensor, so randomness is (a) reproducible, (b)
functionalized under ``to_static`` — the key becomes a carried jit state and
every compiled step advances it — and (c) splittable for per-device streams
(the RNG-tracker pattern tensor-parallel layers need).
"""

from __future__ import annotations

from typing import Optional

import jax

from . import tracing as _tracing
from .tensor import Tensor, register_state_tensor, _is_tracer

__all__ = ["Generator", "default_generator", "seed", "get_rng_state", "set_rng_state"]


class Generator:
    def __init__(self, seed_val: int = 0, name: Optional[str] = None):
        self._key = Tensor(jax.random.PRNGKey(seed_val), stop_gradient=True,
                           name=name or "rng_state")
        self._key.persistable = True
        register_state_tensor(self._key)

    def manual_seed(self, seed_val: int) -> "Generator":
        self._key._set_data(jax.random.PRNGKey(seed_val))
        return self

    def split_key(self):
        """Return a fresh subkey; advances (and trace-logs) the state."""
        ts = _tracing.trace_state()
        key = self._key._data
        if ts is not None and not _is_tracer(key):
            ts.record_read(self._key)
        next_key, sub = jax.random.split(key)
        self._key._set_data(next_key)
        return sub

    @property
    def state(self) -> Tensor:
        return self._key

    def get_state(self):
        # snapshot, not the live state tensor — saved states must not advance
        # with the generator (paddle.get_rng_state contract)
        return Tensor(self._key._data, stop_gradient=True)

    def set_state(self, state) -> None:
        self._key._set_data(state._data if isinstance(state, Tensor) else state)


default_generator = Generator(0)


def seed(seed: int) -> Generator:
    """``paddle.seed`` parity (upstream names the arg ``seed``)."""
    default_generator.manual_seed(int(seed))
    return default_generator


def get_rng_state():
    return [default_generator.get_state()]


def set_rng_state(states) -> None:
    default_generator.set_state(states[0] if isinstance(states, (list, tuple)) else states)
