"""Signature-keyed compiled-op cache for eager dispatch.

Parity surface: the reference buries per-op dispatch cost in codegen'd C++
``*_ad_func``s plus the Phi kernel fast path; our ``apply()`` is Python and
re-traces every op through un-jitted ``jax.vjp``/``fn`` calls. This module
amortizes that work the way LazyTensor and TorchDynamo do: the FIRST calls
for a signature run the plain eager path, and once a signature repeats it is
compiled (``jax.jit``) and every later call goes straight to the cached
executable — no retrace, no closure rebuild, no per-op ``jnp`` re-lowering.

A signature is ``(op_name, fn structural fingerprint, static kwargs, input
avals (shape/dtype/weak-type), resolved-autocast token, needs_grad,
check_nan_inf)``. The fingerprint walks the op fn's closure cells and
defaults (ops here are tiny per-call lambdas closing over python scalars —
``lambda a: jfn(a, y)``), so two calls with equal closure state share one
compiled executable while ``reshape([2, 3])`` vs ``reshape([3, 2])`` do not.
Anything value-unstable (arrays/tensors/tracers in closures, unhashable
statics) makes the op fall back to the uncached path, counted per reason.

The cache is process-global, thread-safe (one lock; jitted callables are
themselves thread-safe), LRU-bounded, and toggleable:

* ``PADDLE_TPU_EAGER_CACHE=0``       — disable entirely (dispatch identical
  to the uncached path; ``core.tensor`` probes one module bool).
* ``PADDLE_TPU_EAGER_CACHE_SIZE``    — LRU capacity (default 1024).
* ``PADDLE_TPU_EAGER_CACHE_WARMUP``  — sightings of a signature before it is
  compiled (default 2: never pay a compile for a signature seen once).

``core.tensor`` owns the dispatch integration; this module owns keys,
storage, policy, and counters (mirrored into ``paddle_tpu.observability``
through ``_obs_hook`` while metrics are enabled).
"""

from __future__ import annotations

import functools
import os
import threading
import types
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

try:
    from jax.core import Tracer as _Tracer
except Exception:  # pragma: no cover
    from jax._src.core import Tracer as _Tracer

__all__ = [
    "CachedOp", "configure", "cache_clear", "cache_info", "lookup", "store",
    "note_bypass", "make_key", "NEEDS_COMPILE",
]


def _env_enabled() -> bool:
    return os.environ.get("PADDLE_TPU_EAGER_CACHE", "1").strip().lower() \
        not in ("0", "false", "off", "no")


def _env_int(name: str, default: int, lo: int = 1) -> int:
    try:
        return max(lo, int(os.environ.get(name, default)))
    except ValueError:
        return default


_ENABLED: bool = _env_enabled()
_MAXSIZE: int = _env_int("PADDLE_TPU_EAGER_CACHE_SIZE", 1024)
_WARMUP: int = _env_int("PADDLE_TPU_EAGER_CACHE_WARMUP", 2)

_LOCK = threading.Lock()
# key -> CachedOp | _UNCACHEABLE. Kept SEPARATE from the warmup counters:
# identity-keyed signatures that never repeat (fresh functools.partial-like
# callables) would otherwise churn counter insertions through the LRU and
# flush genuinely hot compiled entries.
_CACHE: "OrderedDict[Any, Any]" = OrderedDict()
# key -> sighting count (seen, not yet compiled); same bound, own churn.
_PENDING: "OrderedDict[Any, int]" = OrderedDict()
# key -> consecutive failed compile attempts (non-trace errors); a key that
# keeps failing is poisoned after _MAX_COMPILE_RETRIES so dispatch doesn't
# silently pay a doomed re-trace per call forever.
_FAILS: "OrderedDict[Any, int]" = OrderedDict()
_MAX_COMPILE_RETRIES = 3

_STATS: Dict[str, Any] = {
    "hits": 0, "misses": 0, "compiles": 0, "evictions": 0,
    "pending_drops": 0,  # warmup counters displaced before compiling —
    #                      NOT evictions: no compile work was lost
    "bypass": {},  # reason -> count
}

# Installed by paddle_tpu.observability while enabled; called as
# hook(kind, reason) with kind in {hit, miss, compile, evict, bypass}.
# None => the cache pays nothing beyond the is-None probe.
_obs_hook: Optional[Callable[[str, Optional[str]], None]] = None

# ISSUE 16: compile-time cost capture (observability.cost), same is-None
# contract. Called as hook(event, key, **kw): "store" fires from
# core.tensor._apply_cached with the entry + run arrays still in scope
# (spec building needs them), "evict"/"clear" fire here so the cost
# registry retires records for entries the LRU dropped.
_cost_hook: Optional[Callable] = None

NEEDS_COMPILE = object()  # lookup() verdict: signature is warm, build an entry
_UNCACHEABLE = object()   # poisoned signature: fn untraceable, never retry


class CachedOp:
    """One compiled signature: jitted forward (+ fused finite check) and a
    lazily-jitted backward that re-linearizes the op inside XLA.

    ``fwd(*arrays) -> (outs, finite)`` where ``finite`` is None when the
    nan-check is off (or no inexact outputs) and a scalar bool otherwise —
    ONE host sync replaces the per-output blocking ``jnp.all`` loop.
    ``bwd(arrays, cts) -> input cotangents`` recomputes the vjp of the
    composed fn at the primals inside one compiled program; numerics are
    identical to an eager ``jax.vjp`` at the same primals, but the
    linearization is traced once per signature instead of once per call.
    """

    __slots__ = ("fn", "fwd", "bwd", "nan_check")

    def __init__(self, fn: Callable, nan_check: bool):
        self.fn = fn  # the composed pure fn (casts + static kwargs baked in)
        self.nan_check = nan_check

        def _fwd(*xs):
            r = fn(*xs)
            if not nan_check:
                return r, None
            outs = r if isinstance(r, tuple) else (r,)
            finite = None
            for o in outs:
                if jax.numpy.issubdtype(o.dtype, jax.numpy.inexact):
                    ok = jax.numpy.all(jax.numpy.isfinite(o))
                    finite = ok if finite is None else \
                        jax.numpy.logical_and(finite, ok)
            return r, finite

        def _bwd(xs, cts):
            _, vjp = jax.vjp(fn, *xs)
            gs = vjp(cts)
            # float0 cotangents (integer primals) never leave the program:
            # backward skips None exactly like it skips float0
            return tuple(
                None if getattr(g, "dtype", None) == jax.dtypes.float0 else g
                for g in gs)

        self.fwd = jax.jit(_fwd)
        self.bwd = jax.jit(_bwd)

    def make_vjp(self, arrays: Tuple[Any, ...]) -> Callable:
        """A vjp callable for the tape with the ``jax.vjp`` contract (takes
        the output cotangent structure, returns per-input grads)."""
        bwd = self.bwd

        def vjp_fn(cts):
            return bwd(arrays, cts)

        return vjp_fn

    def warm_bwd(self, arrays, out_arrays, multi: bool) -> None:
        """Trace+compile the backward NOW (at dispatch/store time) with
        zero cotangents of the outputs' avals. The seed's ``jax.vjp``
        snapshots the op fn's closure state at dispatch; deferring the bwd
        trace to the first ``backward()`` would instead read closure state
        as of backward time — observable if a caller mutates e.g. a
        closure-held list in between. One throwaway execution on zeros per
        signature keeps the snapshot semantics."""
        zeros = tuple(jax.numpy.zeros(o.shape, o.dtype) for o in out_arrays)
        self.bwd(tuple(arrays), zeros if multi else zeros[0])


# ---------------------------------------------------------------------------
# signature fingerprinting
# ---------------------------------------------------------------------------

class _Bypass(Exception):
    def __init__(self, reason: str):
        self.reason = reason


_SCALARS = (bool, int, float, str, bytes, complex)
_MAX_FN_DEPTH = 3


def _is_arraylike(v) -> bool:
    return (isinstance(v, (jax.Array, np.ndarray)) or isinstance(v, _Tracer)
            or type(v).__name__ == "LazyValue" or hasattr(v, "_grad_node"))


def _fp_value(v, depth: int):
    """Hashable, value-stable fingerprint of one closure/static value.

    Mutable containers are keyed by CONTENT (a later mutation yields a new
    key, never a stale hit); arrays, tensors, tracers and unknown objects
    raise ``_Bypass`` — unhashable or identity-keyed-but-mutable values must
    not silently pin a compiled constant.
    """
    if v is None:
        return None
    t = v.__class__
    if t in _SCALARS:
        return (t, v)
    if t is tuple or t is list:
        return ("T" if t is tuple else "L",
                tuple(_fp_value(x, depth) for x in v))
    if t is dict:
        return ("D", tuple(sorted(
            (str(k), _fp_value(x, depth)) for k, x in v.items())))
    if t is slice:
        return ("SL", _fp_value(v.start, depth), _fp_value(v.stop, depth),
                _fp_value(v.step, depth))
    if isinstance(v, np.dtype) or (isinstance(v, type)
                                   and issubclass(v, np.generic)):
        return ("DT", np.dtype(v).str)
    if isinstance(v, np.generic):  # 0-d numpy scalar: immutable, hashable
        return (t, v.item())
    if _is_arraylike(v):
        raise _Bypass("closure_array")
    if isinstance(v, types.FunctionType):
        if depth >= _MAX_FN_DEPTH:
            # deep nesting: key on the function object itself — stable for
            # module-level fns, per-call churn (bounded by the LRU) for
            # fresh closures
            return ("F", v)
        return _fp_fn(v, depth + 1)
    if isinstance(v, functools.partial):
        # ops build fresh partials per call (e.g. partial(_pairwise_iou,
        # mode=mode)): identity keying would never hit — fingerprint by
        # (func, args, keywords), which IS stable across calls
        return ("P", _fp_value(v.func, depth),
                tuple(_fp_value(a, depth) for a in v.args),
                tuple(sorted((k, _fp_value(a, depth))
                             for k, a in v.keywords.items())))
    if callable(v):
        # builtins / ufuncs / jitted wrappers: module-level singletons with
        # stable identity; keyed by the object (the key tuple keeps it alive
        # so the id can never be reused)
        return ("C", v)
    if t is frozenset:
        return ("FS", v)
    raise _Bypass("static_unhashable")


def _fp_fn(fn, depth: int):
    code = fn.__code__
    parts = [code]
    closure = fn.__closure__
    if closure:
        for cell in closure:
            try:
                parts.append(_fp_value(cell.cell_contents, depth))
            except ValueError:  # empty cell
                parts.append(("E",))
    defaults = fn.__defaults__
    if defaults:
        parts.append(tuple(_fp_value(v, depth) for v in defaults))
    kwdefaults = fn.__kwdefaults__
    if kwdefaults:
        parts.append(tuple(sorted(
            (k, _fp_value(v, depth)) for k, v in kwdefaults.items())))
    return ("FN", tuple(parts))


def make_key(op_name: str, fn: Callable, in_sigs: Tuple,
             static_kwargs: Dict[str, Any], amp_key, needs_grad: bool,
             nan_check: bool, flags_epoch: int, backend: str = ""):
    """Build the cache key, or ``(None, reason)`` when the op must bypass.

    ``flags_epoch`` folds every runtime ``set_flags`` write into the key:
    op fns read flags at trace time (tpu_matmul_precision, flash_block_*),
    so a flag flip must retire all compiled entries rather than serve the
    baked-in old value.

    ``backend`` is the placement token from ``core/fallback.py`` (``""``
    for default placement, ``"cpu"`` for an op on the CPU-fallback path):
    the moment an op falls back its signatures key differently, so a
    TPU-compiled callable can never be served for it — and the CPU
    executable compiled under the new key never leaks back.
    """
    try:
        if isinstance(fn, types.FunctionType):
            fn_key = _fp_fn(fn, 0)
        else:
            fn_key = _fp_value(fn, 0)  # partial/builtin/ufunc rules
        if static_kwargs:
            statics = tuple(sorted(
                (k, _fp_value(v, 0)) for k, v in static_kwargs.items()))
        else:
            statics = ()
        key = (op_name, fn_key, statics, in_sigs, amp_key, needs_grad,
               nan_check, flags_epoch, backend)
        hash(key)  # identity-keyed callables may be hash-less: probe NOW,
        #            not inside the cache dict where it would escape
    except _Bypass as e:
        return None, e.reason
    except TypeError:
        return None, "static_unhashable"
    return key, None


# ---------------------------------------------------------------------------
# storage / policy
# ---------------------------------------------------------------------------

def lookup(key):
    """One cache probe. Returns a ``CachedOp`` (hit), ``NEEDS_COMPILE``
    (signature warm: caller builds + ``store()``s an entry), or ``None``
    (cold miss: caller runs the uncached path). The observability hook is
    invoked AFTER the lock is released — a hit must never serialize on a
    metric-family lock."""
    hook = _obs_hook
    with _LOCK:
        v = _CACHE.get(key)
        if v.__class__ is CachedOp:
            _CACHE.move_to_end(key)
            _STATS["hits"] += 1
            event, result = "hit", v
        elif v is _UNCACHEABLE:
            _CACHE.move_to_end(key)
            b = _STATS["bypass"]
            b["untraceable"] = b.get("untraceable", 0) + 1
            event, result = "bypass", None
        else:
            _STATS["misses"] += 1
            event = "miss"
            n = _PENDING.get(key)
            if n is None:
                if _WARMUP <= 1:  # compile-on-first-sighting mode
                    result = NEEDS_COMPILE
                else:
                    _PENDING[key] = 1
                    result = None
                    if len(_PENDING) > _MAXSIZE:
                        _PENDING.popitem(last=False)
                        _STATS["pending_drops"] += 1
            elif n + 1 >= _WARMUP:
                result = NEEDS_COMPILE
            else:
                _PENDING[key] = n + 1
                _PENDING.move_to_end(key)
                result = None
    if hook is not None:
        hook(event, "untraceable" if event == "bypass" else None)
    return result


def _insert_locked(key, value):
    """Put a compiled/poisoned entry; returns the key the LRU evicted to
    make room (None when nothing was displaced) — the cost registry
    retires the evicted program's record by that key."""
    _CACHE[key] = value
    _CACHE.move_to_end(key)
    _PENDING.pop(key, None)
    _FAILS.pop(key, None)
    if len(_CACHE) > _MAXSIZE:
        old_key, _old = _CACHE.popitem(last=False)
        _STATS["evictions"] += 1
        return old_key
    return None


def store(key, entry: CachedOp) -> None:
    hook = _obs_hook
    cost_hook = _cost_hook
    with _LOCK:
        evicted = _insert_locked(key, entry)
        _STATS["compiles"] += 1
    if hook is not None:
        hook("compile", None)
        if evicted is not None:
            hook("evict", None)
    if cost_hook is not None and evicted is not None:
        cost_hook("evict", evicted)


def mark_uncacheable(key) -> None:
    """Poison a signature whose fn failed to trace/compile (e.g. it branches
    on concrete array values, legal eagerly but not under jit). Later calls
    take the uncached path immediately instead of re-tracing every time."""
    cost_hook = _cost_hook
    with _LOCK:
        evicted = _insert_locked(key, _UNCACHEABLE)
    if cost_hook is not None and evicted is not None:
        cost_hook("evict", evicted)


def note_compile_failure(key) -> None:
    """A compile attempt failed with a non-trace error (transient runtime
    fault, input-dependent failure). Retrying on a later call is desirable
    — ONCE or twice; a key that keeps failing gets poisoned so dispatch
    stops paying a doomed re-trace on every call. Each attempt is counted
    (``bypass{compile_retry}``) so the retry loop is diagnosable."""
    cost_hook = _cost_hook
    displaced = []
    with _LOCK:
        n = _FAILS.get(key, 0) + 1
        if n >= _MAX_COMPILE_RETRIES:
            displaced.append(_insert_locked(key, _UNCACHEABLE))
        else:
            _FAILS[key] = n
            _FAILS.move_to_end(key)
            if len(_FAILS) > 64:
                # displaced under pressure: poison instead of forgetting —
                # dropping the count would let >64 rotating failing
                # signatures each re-trace forever without ever reaching
                # the retry cap (poisoning early is always safe, it only
                # costs that signature the cached fast path)
                old_key, _n = _FAILS.popitem(last=False)
                displaced.append(_insert_locked(old_key, _UNCACHEABLE))
    if cost_hook is not None:
        for k in displaced:
            if k is not None:
                cost_hook("evict", k)
    note_bypass("compile_retry")


def note_bypass(reason: str) -> None:
    # no lock: this runs per op while a capture seam is live (to_static
    # trace, EVERY op of a lazy segment re-record), where the promise is
    # "unchanged dispatch" — a GIL-racy dict bump that can rarely lose a
    # count is the right trade for a diagnostic; the observability
    # counters (when enabled) take their own per-family lock and stay
    # exact
    b = _STATS["bypass"]
    b[reason] = b.get(reason, 0) + 1
    hook = _obs_hook
    if hook is not None:
        hook("bypass", reason)


# ---------------------------------------------------------------------------
# control surface
# ---------------------------------------------------------------------------

def configure(enabled: Optional[bool] = None, maxsize: Optional[int] = None,
              warmup: Optional[int] = None) -> None:
    """Runtime override of the env-derived settings (tests, tuning)."""
    global _ENABLED, _MAXSIZE, _WARMUP
    cost_hook = _cost_hook
    shrunk = []
    with _LOCK:
        if enabled is not None:
            _ENABLED = bool(enabled)
        if maxsize is not None:
            _MAXSIZE = max(1, int(maxsize))
            while len(_CACHE) > _MAXSIZE:
                old_key, _old = _CACHE.popitem(last=False)
                shrunk.append(old_key)
                _STATS["evictions"] += 1
            while len(_PENDING) > _MAXSIZE:
                _PENDING.popitem(last=False)
                _STATS["pending_drops"] += 1
        if warmup is not None:
            _WARMUP = max(1, int(warmup))
    if cost_hook is not None:
        for k in shrunk:
            cost_hook("evict", k)


def enabled() -> bool:
    return _ENABLED


def cache_clear(reset_stats: bool = True) -> None:
    cost_hook = _cost_hook
    with _LOCK:
        _CACHE.clear()
        _PENDING.clear()
        _FAILS.clear()
        if reset_stats:
            _STATS.update(hits=0, misses=0, compiles=0, evictions=0,
                          pending_drops=0, bypass={})
    if cost_hook is not None:
        cost_hook("clear", None)


def stats_clear() -> None:
    """Zero the counters without dropping compiled entries (benchmarks
    measure hit_rate over a window that starts warm)."""
    with _LOCK:
        _STATS.update(hits=0, misses=0, compiles=0, evictions=0,
                      pending_drops=0, bypass={})


def cache_info() -> Dict[str, Any]:
    with _LOCK:
        compiled = sum(1 for v in _CACHE.values() if v.__class__ is CachedOp)
        hits, misses = _STATS["hits"], _STATS["misses"]
        total = hits + misses
        return {
            "enabled": _ENABLED,
            "maxsize": _MAXSIZE,
            "warmup": _WARMUP,
            "size": len(_CACHE),
            "pending": len(_PENDING),
            "compiled": compiled,
            "hits": hits,
            "misses": misses,
            "compiles": _STATS["compiles"],
            "evictions": _STATS["evictions"],
            "pending_drops": _STATS["pending_drops"],
            "bypass": dict(_STATS["bypass"]),
            "hit_rate": (hits / total) if total else 0.0,
        }
