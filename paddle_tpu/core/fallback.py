"""Backend-fallback dispatch: graceful CPU degradation for missing lowerings.

Parity surface: upstream KernelFactory backend fallback
(paddle/phi/core/kernel_factory.cc ``SelectKernelOrThrowError``): when an op
has no kernel registered for the requested place, the factory selects the
CPU kernel and inserts H2D/D2H transfers instead of aborting the program.
TPU-native design: the "kernel registration probe" is the XLA lowering
itself. A primitive with no TPU implementation surfaces
``NotImplementedError`` (missing lowering rule at trace time) or a jaxlib
``XlaRuntimeError`` marked UNIMPLEMENTED/unsupported (compile/first
execution). This module classifies those failures, re-executes the op's
pure fn on the host CPU devices, transfers the results back to the default
device, and records the op in a process-level registry so every later
dispatch of that op skips the doomed TPU compile entirely.

Control surface:

* ``PADDLE_TPU_FALLBACK=auto`` (default) — degrade per-op: one-time
  warning (:class:`BackendFallbackWarning`), ``dispatch.fallbacks_total{op}``
  counter, ``dispatch.fallback_ops`` gauge, registry short-circuit.
* ``PADDLE_TPU_FALLBACK=off`` — today's hard-fail surface, for debugging:
  you want the crash, not the degradation.

``DEFAULT_DENYLIST`` pre-seeds the known-bad families on current libtpu
(``linalg.eig``, complex ``sgn``, ``fft.hfft2``) so a real-chip run never
pays their doomed compile even once. The denylist only engages when an
accelerator is actually present — on a CPU-only host there is nothing to
degrade FROM, and tier-1 semantics stay byte-identical.

Composition contracts:

* dispatch cache (PR 2): the backend token joins the signature key
  (``core/dispatch_cache.py::make_key``), so a TPU-compiled callable is
  never served for an op that has since fallen back; the fallen-back
  signature compiles its own CPU executable and hits the cache normally.
* resilience (PR 5): ``core/tensor.py::_dispatch_execute`` wraps the
  execution in ``fault_point("dispatch.lower")`` /
  ``fault_point("dispatch.execute")`` seams, so CPU-only CI can inject a
  lowering failure and drive the full degrade-warn-count-cache sequence
  deterministically.

This module (together with ``paddle_tpu/device.py``) is the only place
allowed to touch ``jax.devices``/``jax.device_put`` directly — enforced by
the ``device-access`` lint rule (tools/lint/rules/device_access.py).
"""

from __future__ import annotations

import os
import sys
import threading
import warnings
from typing import Any, Callable, Optional, Tuple

import jax

from .. import device as _device
from .. import observability as _obs

__all__ = [
    "BackendFallbackWarning", "DEFAULT_DENYLIST", "XlaRuntimeError",
    "enabled", "configure", "reset", "fallback_ops", "should_fallback",
    "backend_token", "is_lowering_failure", "note_fallback", "run_cpu",
    "to_cpu", "from_cpu", "wrap_vjp",
]

# public alias of jaxlib's XlaRuntimeError (same class object) — using the
# supported surface instead of jax._src keeps the classifier working (and
# the whole fallback layer live) across jaxlib-internal relayouts
XlaRuntimeError = jax.errors.JaxRuntimeError


class BackendFallbackWarning(RuntimeWarning):
    """Emitted exactly once per op the first time it degrades to CPU."""


# Known-bad families on current libtpu (ROADMAP item 2 / VERDICT Missing
# #1): eig has no TPU lowering at all, complex sgn hits an UNIMPLEMENTED
# elementwise lowering, hfft2's C2R path is rejected by the TPU fft rule.
DEFAULT_DENYLIST = frozenset({"eig", "sgn", "hfft2"})


def _env_mode() -> str:
    v = os.environ.get("PADDLE_TPU_FALLBACK", "auto").strip().lower()
    return "off" if v in ("off", "0", "false", "no") else "auto"


_MODE: str = _env_mode()
_LOCK = threading.Lock()
_REGISTRY: set = set()   # ops that have fallen back (process-level)
_WARNED: set = set()     # ops whose one-time warning has fired
_DENYLIST: frozenset = DEFAULT_DENYLIST

# Families pre-created so the series carry help text in the Prometheus
# exposition; the helpers below still no-op while observability is
# disabled (the standard zero-overhead contract).
_obs.counter("dispatch.fallbacks_total",
             "dispatches executed on the CPU fallback path",
             labelnames=("op",))
_obs.gauge("dispatch.fallback_ops",
           "ops currently registered on the CPU fallback path")


# ---------------------------------------------------------------------------
# mode / registry surface
# ---------------------------------------------------------------------------

def enabled() -> bool:
    """True when fallback may engage (``PADDLE_TPU_FALLBACK`` != off)."""
    return _MODE == "auto"


def configure(mode: Optional[str] = None,
              denylist: Optional[frozenset] = None) -> None:
    """Runtime override of the env-derived settings (tests, debugging)."""
    global _MODE, _DENYLIST
    with _LOCK:
        if mode is not None:
            if mode not in ("auto", "off"):
                raise ValueError(f"PADDLE_TPU_FALLBACK mode must be "
                                 f"'auto' or 'off', got {mode!r}")
            _MODE = mode
        if denylist is not None:
            _DENYLIST = frozenset(denylist)


def reset() -> None:
    """Drop all fallback state and re-read the env knob (test isolation)."""
    global _MODE, _DENYLIST
    with _LOCK:
        _REGISTRY.clear()
        _WARNED.clear()
        _MODE = _env_mode()
        _DENYLIST = DEFAULT_DENYLIST
        _obs.set_gauge("dispatch.fallback_ops", 0.0)


def fallback_ops() -> frozenset:
    """Snapshot of the ops currently registered on the fallback path."""
    with _LOCK:
        return frozenset(_REGISTRY)


def should_fallback(op_name: str) -> bool:
    """True when this op must skip the TPU compile and run on CPU: it
    already fell back once (registry), or it is denylisted and an
    accelerator is present (on a CPU-only host there is nothing to
    degrade from, so the denylist stays inert and tier-1 is unchanged)."""
    if _MODE != "auto":
        return False
    if op_name in _REGISTRY:
        return True
    return op_name in _DENYLIST and _device.is_compiled_with_tpu()


def backend_token(op_name: str) -> str:
    """The backend component of the dispatch-cache signature key: ``"cpu"``
    for an op on the fallback path, ``""`` for normal placement. Keying on
    this retires any TPU-compiled entry the moment its op falls back."""
    return "cpu" if should_fallback(op_name) else ""


# ---------------------------------------------------------------------------
# failure classification
# ---------------------------------------------------------------------------

# Substrings (lower-cased) marking an XlaRuntimeError as a missing/broken
# lowering rather than a transient runtime fault. RESOURCE_EXHAUSTED (OOM)
# and connection-ish failures are deliberately NOT fallback-eligible:
# silently re-running an OOM'd batch on host CPU would hide a capacity
# problem behind a 100x slowdown.
_MSG_MARKERS = ("unimplemented", "not implemented", "unsupported",
                "not supported", "no registered lowering", "could not lower",
                "unable to lower")
_MSG_EXCLUDE = ("resource_exhausted", "out of memory")


def is_lowering_failure(exc: BaseException) -> bool:
    """Classify one dispatch failure: may this op degrade to CPU?"""
    if isinstance(exc, NotImplementedError):
        return True
    if isinstance(exc, XlaRuntimeError):
        msg = str(exc).lower()
        if any(m in msg for m in _MSG_EXCLUDE):
            return False
        return any(m in msg for m in _MSG_MARKERS)
    return False


_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _user_stacklevel() -> int:
    """Stacklevel of the nearest frame outside paddle_tpu, so the one-time
    fallback warning names the USER call site regardless of which dispatch
    path (cached/uncached, varying wrapper depth) reached us."""
    f, level = sys._getframe(1), 1
    while f is not None and f.f_code.co_filename.startswith(_PKG_DIR):
        f, level = f.f_back, level + 1
    return level


def note_fallback(op_name: str, exc: Optional[BaseException] = None) -> None:
    """Register ``op_name`` on the fallback path; warn exactly once per op
    per process and publish the ``dispatch.fallback_ops`` gauge."""
    with _LOCK:
        new = op_name not in _REGISTRY
        if new:
            _REGISTRY.add(op_name)
            # gauge published under the lock: a later registration's
            # set_gauge can't be overwritten by an earlier (smaller) one
            _obs.set_gauge("dispatch.fallback_ops", float(len(_REGISTRY)))
        warn = op_name not in _WARNED
        if warn:
            _WARNED.add(op_name)
    if warn:
        cause = (f"{type(exc).__name__}: {exc}" if exc is not None
                 else "denylisted for this backend")
        warnings.warn(
            f"op '{op_name}' has no working TPU lowering ({cause}); "
            f"falling back to CPU for this op from now on. Set "
            f"PADDLE_TPU_FALLBACK=off to restore the hard failure.",
            BackendFallbackWarning, stacklevel=_user_stacklevel())


# ---------------------------------------------------------------------------
# CPU re-execution + transfers
# ---------------------------------------------------------------------------

def _cpu_device():
    return _device.CPUPlace().jax_device()


def _put(a, dev):
    """One transfer, skipping what must not (or need not) move: ``None``
    and float0 cotangents pass through, and an array already resident on
    ``dev`` keeps its (un)committed placement instead of being re-committed
    — on a CPU-only host the fallback path is then placement-neutral."""
    if a is None or getattr(a, "dtype", None) == jax.dtypes.float0:
        return a
    devs = getattr(a, "devices", None)
    if devs is not None:
        try:
            if a.devices() == {dev}:
                return a
        except Exception:
            pass  # multi-device/sharded array: let device_put decide
    return jax.device_put(a, dev)


def to_cpu(arrays: Tuple[Any, ...]) -> Tuple[Any, ...]:
    """Transfer op inputs to the host CPU device (D2H leg)."""
    cpu = _cpu_device()
    return tuple(_put(a, cpu) for a in arrays)


def from_cpu(arrays: Tuple[Any, ...]) -> Tuple[Any, ...]:
    """Transfer op results back to the default device (H2D leg)."""
    tgt = _device.default_jax_device()
    return tuple(_put(a, tgt) for a in arrays)


def wrap_vjp(cpu_vjp: Callable) -> Callable:
    """Wrap a CPU-resident vjp for the tape: cotangents arrive wherever the
    consumer produced them (usually the accelerator), move to CPU for the
    pull-back, and the input grads move back to the default device so the
    rest of the backward pass stays on the accelerator."""
    def vjp_fn(cts):
        if isinstance(cts, tuple):
            cts = to_cpu(cts)
        else:
            cts = to_cpu((cts,))[0]
        return from_cpu(tuple(cpu_vjp(cts)))
    return vjp_fn


def count_cpu_dispatch(op_name: str) -> None:
    """Count one dispatch served by the CPU fallback path (both the eager
    re-execution and the cached-CPU-callable route report here)."""
    _obs.inc("dispatch.fallbacks_total", op=op_name)


def run_cpu(op_name: str, f: Callable, arrays: Tuple[Any, ...],
            needs_grad: bool, exc: Optional[BaseException] = None):
    """Execute one op's pure fn on host CPU and transfer results back.

    Returns ``(outs, vjp_fn)`` with the ``jax.vjp`` contract
    (``vjp_fn`` is None when ``needs_grad`` is false). If the CPU backend
    is unreachable (``JAX_PLATFORMS`` pinned accelerator-only) the original
    failure — when there was one — is re-raised instead of masked.

    The registry/warning/counter commit only AFTER the CPU execution
    succeeds: an op whose fn fails on CPU too must keep its real error
    surface, not get pinned to a fallback path that can never serve it.
    """
    try:
        cpu_arrays = to_cpu(arrays)
    except RuntimeError:
        if exc is not None:
            raise exc
        raise
    if needs_grad:
        outs, cpu_vjp = jax.vjp(f, *cpu_arrays)
        vjp_fn = wrap_vjp(cpu_vjp)
    else:
        outs, vjp_fn = f(*cpu_arrays), None
    note_fallback(op_name, exc)
    count_cpu_dispatch(op_name)
    if isinstance(outs, tuple):
        outs = from_cpu(outs)
    else:
        outs = from_cpu((outs,))[0]
    return outs, vjp_fn
