"""Data types.

Parity surface: the reference's ``phi::DataType`` / ``paddle.float32`` style
dtype taxonomy (upstream: paddle/phi/common/data_type.h, python/paddle dtype
exports). Here every dtype is a thin alias of a ``jnp.dtype`` so tensors
interoperate with jax with zero conversion.
"""

from __future__ import annotations

from types import MappingProxyType

import jax.numpy as jnp
import numpy as np

# Canonical dtype objects (numpy dtype instances — what jax uses natively).
bfloat16 = jnp.dtype(jnp.bfloat16)
float16 = jnp.dtype(jnp.float16)
float32 = jnp.dtype(jnp.float32)
float64 = jnp.dtype(jnp.float64)
int8 = jnp.dtype(jnp.int8)
int16 = jnp.dtype(jnp.int16)
int32 = jnp.dtype(jnp.int32)
int64 = jnp.dtype(jnp.int64)
uint8 = jnp.dtype(jnp.uint8)
uint16 = jnp.dtype(jnp.uint16)
uint32 = jnp.dtype(jnp.uint32)
uint64 = jnp.dtype(jnp.uint64)
bool_ = jnp.dtype(jnp.bool_)
complex64 = jnp.dtype(jnp.complex64)
complex128 = jnp.dtype(jnp.complex128)
float8_e4m3fn = jnp.dtype(jnp.float8_e4m3fn)
float8_e5m2 = jnp.dtype(jnp.float8_e5m2)

# Read-only by construction: convert_dtype is called inside traced op
# bodies, so a writable alias table would be baked into compiled
# executables and silently served stale after any mutation.
_ALIASES = MappingProxyType({
    "bfloat16": bfloat16, "bf16": bfloat16,
    "float16": float16, "fp16": float16, "half": float16,
    "float32": float32, "fp32": float32, "float": float32,
    "float64": float64, "fp64": float64, "double": float64,
    "int8": int8, "int16": int16, "int32": int32, "int64": int64,
    "uint8": uint8, "uint16": uint16, "uint32": uint32, "uint64": uint64,
    "bool": bool_, "complex64": complex64, "complex128": complex128,
    "float8_e4m3fn": float8_e4m3fn, "float8_e5m2": float8_e5m2,
})

_FLOATS = (bfloat16, float16, float32, float64, float8_e4m3fn, float8_e5m2)
_INTS = (int8, int16, int32, int64, uint8, uint16, uint32, uint64)


def convert_dtype(dtype) -> jnp.dtype:
    """Normalize any dtype spec (str, np/jnp dtype, python type) to jnp.dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        d = _ALIASES.get(dtype)
        if d is None:
            raise ValueError(f"unknown dtype {dtype!r}")
        return d
    if dtype is float:
        return float32
    if dtype is int:
        return int64
    if dtype is bool:
        return bool_
    return jnp.dtype(dtype)


def is_floating_point(dtype) -> bool:
    return convert_dtype(dtype) in _FLOATS


def is_integer(dtype) -> bool:
    return convert_dtype(dtype) in _INTS


def is_complex(dtype) -> bool:
    d = convert_dtype(dtype)
    return d in (complex64, complex128)


def dtype_name(dtype) -> str:
    d = convert_dtype(dtype)
    return "bool" if d == bool_ else d.name


# Default dtype handling (parity: paddle.get_default_dtype/set_default_dtype).
_default_dtype = float32


def set_default_dtype(d) -> None:
    global _default_dtype
    d = convert_dtype(d)
    if d not in (float16, bfloat16, float32, float64):
        raise TypeError(f"default dtype must be floating point, got {d}")
    _default_dtype = d


def get_default_dtype():
    return _default_dtype


def finfo(dtype):
    return jnp.finfo(convert_dtype(dtype))


def iinfo(dtype):
    return jnp.iinfo(convert_dtype(dtype))


def promote_types(a, b):
    return jnp.promote_types(convert_dtype(a), convert_dtype(b))


def canonicalize(dtype):
    """Map 64-bit dtypes to their 32-bit forms when x64 is disabled (jax
    default). Keeps paddle's int64-by-default API surface warning-free; on
    TPU 32-bit is the native width anyway."""
    import jax
    d = convert_dtype(dtype)
    if d is None or jax.config.jax_enable_x64:
        return d
    return {int64: int32, uint64: uint32, float64: float32,
            complex128: complex64}.get(d, d)
