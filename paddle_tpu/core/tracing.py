"""Execution-mode contexts shared by the whole framework.

Three orthogonal modes thread through every op dispatch (the analogue of the
thread-local state the reference keeps in its eager engine — upstream:
paddle/fluid/eager/ tracer + amp state):

* grad mode   — whether ops record autograd tape nodes (``no_grad``).
* amp state   — autocast level/dtype and op allow/deny lists.
* trace state — active while ``to_static`` functionalizes a user function:
  records which concrete tensors were *read* (future jit inputs) and which
  tensor locations were *mutated* (future jit outputs).
"""

from __future__ import annotations

import contextlib
import threading
import weakref
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "grad_enabled", "no_grad", "enable_grad", "set_grad_enabled",
    "amp_state", "AmpState", "push_amp_state", "pop_amp_state",
    "trace_state", "TraceState", "push_trace_state", "pop_trace_state",
]


class _ModeStack(threading.local):
    def __init__(self):
        self.grad = [True]
        self.amp: List["AmpState"] = []
        self.trace: List["TraceState"] = []


_modes = _ModeStack()


# --- grad mode ---------------------------------------------------------------

def grad_enabled() -> bool:
    return _modes.grad[-1]


class _GradMode(contextlib.ContextDecorator):
    def __init__(self, enabled: bool):
        self._enabled = enabled

    def __enter__(self):
        _modes.grad.append(self._enabled)
        return self

    def __exit__(self, *exc):
        _modes.grad.pop()
        return False


def no_grad(func=None):
    """Context manager / decorator disabling tape recording (paddle.no_grad)."""
    if func is not None:
        return _GradMode(False)(func)
    return _GradMode(False)


def enable_grad(func=None):
    if func is not None:
        return _GradMode(True)(func)
    return _GradMode(True)


@contextlib.contextmanager
def set_grad_enabled(enabled: bool):
    with _GradMode(enabled):
        yield


# --- amp state ---------------------------------------------------------------

# interned AmpState.cache_key tuples: every auto_cast scope with the same
# config shares ONE tuple object, so per-op dispatch-cache key equality
# short-circuits on element identity (PyObject_RichCompareBool) instead of
# walking two ~50-entry frozensets. Bounded: a workload cycling through
# more distinct amp configs than this simply stops sharing.
_amp_key_intern: Dict[tuple, tuple] = {}
_AMP_KEY_INTERN_MAX = 256


class AmpState:
    __slots__ = ("enable", "dtype", "level", "white_set", "black_set",
                 "cache_key")

    def __init__(self, enable, dtype, level, white_set, black_set):
        self.enable = enable
        self.dtype = dtype
        self.level = level  # 'O1' | 'O2'
        self.white_set = white_set
        self.black_set = black_set
        # hashable token for the dispatch-cache key, computed ONCE per
        # autocast scope: op dispatch must not re-hash the op lists per call
        key = (bool(enable), str(dtype), str(level),
               frozenset(white_set), frozenset(black_set))
        if len(_amp_key_intern) < _AMP_KEY_INTERN_MAX:
            key = _amp_key_intern.setdefault(key, key)
        else:
            key = _amp_key_intern.get(key, key)
        self.cache_key = key


def amp_state() -> Optional[AmpState]:
    return _modes.amp[-1] if _modes.amp else None


def push_amp_state(s: AmpState) -> None:
    _modes.amp.append(s)


def pop_amp_state() -> None:
    _modes.amp.pop()


# --- to_static trace state ---------------------------------------------------

class TraceState:
    """Read/mutation log for functionalization.

    ``reads``: id(tensor) -> tensor, for tensors whose concrete ``_data`` was
    consumed while tracing (these must become jit inputs or they would be baked
    into the compiled program as constants).
    ``mutations``: ordered unique locations written while tracing. A location
    is ('data', ref) — tensor._data replaced in place — or ('grad', ref) —
    tensor.grad re-assigned. Locations are resolved again at rebind time so a
    ``.grad`` slot that received a brand-new Tensor during tracing still maps
    back onto whatever object currently occupies the slot.
    """

    def __init__(self):
        self.reads: Dict[int, Any] = {}
        self._mut_keys: set = set()
        self.mutations: List[Tuple[str, Any]] = []
        self._saved: List[Tuple[str, Any, Any]] = []  # (kind, tensor, old value)

    def record_read(self, tensor) -> None:
        self.reads.setdefault(id(tensor), tensor)

    def record_mutation(self, kind: str, tensor) -> None:
        key = (kind, id(tensor))
        if key in self._mut_keys:
            return
        self._mut_keys.add(key)
        self.mutations.append((kind, weakref.ref(tensor)))
        old = tensor._data if kind == "data" else tensor._grad
        self._saved.append((kind, tensor, old))

    def restore(self) -> None:
        """Undo every mutation made under this trace (leaves no tracers
        behind in live tensors)."""
        for kind, tensor, old in reversed(self._saved):
            if kind == "data":
                tensor._data = old
            else:
                tensor._grad = old


def trace_state() -> Optional[TraceState]:
    return _modes.trace[-1] if _modes.trace else None


def push_trace_state(s: TraceState) -> None:
    _modes.trace.append(s)


def pop_trace_state() -> TraceState:
    return _modes.trace.pop()
