"""Lazy segment executor: partial-graph capture for ``full_graph=False``.

Parity surface: upstream SOT (``python/paddle/jit/sot/`` — bytecode-level
graph capture with guards; on a data-dependent branch it compiles the
subgraphs AROUND the break instead of abandoning compilation). The
TPU-native equivalent is the lazy-tensor design (the torch_xla/LTC model):

* Python runs the user function EVERY call (it is the control-flow
  interpreter, so tensor-dependent ``if``/``while`` just work);
* each op dispatched through ``apply()`` is RECORDED, not executed — its
  outputs are ``LazyValue`` placeholders carrying only shape/dtype
  (abstract eval, cached per op signature);
* a concrete read (``float(x)``, ``.numpy()``, a raw-jnp touch via
  ``__jax_array__``) FLUSHES the pending graph: the recorded segment is
  compiled as ONE XLA program (cached by a structural signature: op code
  objects + hashable closure state + topology + input avals) and executed,
  rebinding every escaping placeholder to a real array;
* the read value feeds the Python branch, and recording resumes — the ops
  after the break land in the next segment.

So a function with one data-dependent branch executes as [compiled
segment] -> host read -> [compiled segment]: the guard set of the
reference's SOT collapses into "Python re-executes", and the compiled
cache keys replace its per-break graph cache. Per-call Python overhead is
the op-recording walk (microseconds per op); device work runs in fused
segments, which is where the throughput is.

Memory semantics of a flush (what materializes):

* only ESCAPING values — pending values still owned by a live tensor —
  become compiled-program outputs; intermediates whose tensors died
  (e.g. inference under ``no_grad``, or a model whose params are frozen,
  where no tape exists) are fused away by XLA like any full-graph run.
  ``last_escape_counts()`` exposes the per-flush output count for tests.
* with a tape (grad-enabled forward over trainable params), the tape's
  strong refs keep every intermediate's tensor alive, so every
  intermediate materializes — IDENTICAL to upstream eager semantics
  (the autograd graph pins activations until released there too), not a
  segment-mode regression. The fused optimum for inference remains
  ``no_grad`` (or ``full_graph=True``), same as the reference.
"""

from __future__ import annotations

import weakref
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

__all__ = ["LazyValue", "active", "segment_mode", "suspended", "flush",
           "flush_if_active", "record", "last_segment_hlos"]


class LazyValue:
    """Placeholder for a not-yet-executed op output."""

    __slots__ = ("seq", "aval", "array", "owners", "__weakref__")

    def __init__(self, seq: int, aval):
        self.seq = seq
        self.aval = aval
        self.array = None  # filled by flush
        self.owners: "weakref.WeakSet" = weakref.WeakSet()

    # --- duck-typed array surface (shape/dtype consumers) -------------------
    @property
    def shape(self):
        return self.aval.shape

    @property
    def dtype(self):
        return self.aval.dtype

    @property
    def ndim(self):
        return len(self.aval.shape)

    @property
    def size(self):
        out = 1
        for s in self.aval.shape:
            out *= int(s)
        return out

    def __jax_array__(self):
        # a raw jnp consumer touched a pending value: that is an implicit
        # segment boundary — materialize and hand over the array
        if self.array is None:
            flush()
        return self.array

    def _materialize(self):
        if self.array is None:
            flush()
            if self.array is None:
                raise RuntimeError(
                    "lazy value was never materialized: its recorded segment "
                    "failed to flush or flushed without a live owner")
        return self.array

    def __int__(self):
        return int(self._materialize())

    def __float__(self):
        return float(self._materialize())

    def __bool__(self):
        return bool(self._materialize())

    def __repr__(self):
        state = "pending" if self.array is None else "ready"
        return f"LazyValue<{self.seq}:{state} {self.aval.shape}:{self.aval.dtype}>"


class _Record:
    __slots__ = ("fn", "inputs", "out_lazies", "fn_sig", "lifted")

    def __init__(self, fn, inputs, out_lazies, fn_sig, lifted):
        self.fn = fn                # the op's pure array fn (may close over arrays)
        self.inputs = inputs        # per input: LazyValue | jax.Array
        self.out_lazies = out_lazies
        self.fn_sig = fn_sig        # hashable structural signature of fn
        self.lifted = lifted        # [(setter, array)] closure-held arrays


class _State:
    def __init__(self):
        self.active = False
        self.records: List[_Record] = []
        self.seq = 0
        self.aval_cache: Dict[Any, Any] = {}     # (fn_sig, in_avals) -> out avals
        self.compiled: Dict[Any, Any] = {}       # segment signature -> jitted
        self.last_hlos: List[str] = []           # debug: per-flush compiled HLO
        self.capture_hlo = False
        self.last_escapes: List[int] = []        # per-flush escaping-output count


_state = _State()


def active() -> bool:
    return _state.active


class segment_mode:
    """Context manager enabling lazy segment recording."""

    def __enter__(self):
        if _state.active:
            raise RuntimeError("lazy segment mode is not reentrant")
        _state.active = True
        _state.records = []
        _state.last_hlos = []
        _state.last_escapes = []
        return self

    def __exit__(self, exc_type, exc, tb):
        try:
            # flush on BOTH paths: the recorded ops were "executed" from the
            # Python program's point of view, so an exception must still
            # materialize their side effects (state mutations) — otherwise
            # tensors are left holding dead placeholders and a caller-level
            # eager retry would double-apply whatever had already flushed
            try:
                flush()
            except Exception:
                if exc_type is None:
                    raise  # don't swallow a flush failure on the clean path
                # already unwinding: keep the original exception
        finally:
            _state.active = False
            _state.records = []
        return False


class suspended:
    """Temporarily disable recording inside an already-active segment.

    Used by staged meta-ops (the optimizer-update record) whose ``fn``
    re-runs eager-style jnp math when the replay trace calls it: with
    recording suspended, any nested ``apply()`` executes inline on the
    tracers — i.e. it becomes part of the SAME traced segment instead of
    appending spurious records to the in-flight segment list."""

    def __enter__(self):
        self._was = _state.active
        _state.active = False
        return self

    def __exit__(self, exc_type, exc, tb):
        _state.active = self._was
        return False


# ---------------------------------------------------------------------------
# fn structural signatures (stable across per-call closure objects)
# ---------------------------------------------------------------------------

def _walk_fn(fn, depth=0):
    """Return (hashable signature, [(rebind, array), ...]) for a function,
    recursing into closure cells and defaults. Arrays found there are
    LIFTED: the signature marks their position and the rebind callback lets
    the replay trace substitute a traced value (cells are writable)."""
    if depth > 4:
        return ("deep", repr(fn)), []
    sig: List[Any] = [getattr(fn, "__code__", None) and fn.__code__.co_code,
                      getattr(fn, "__code__", None) and fn.__code__.co_consts]
    lifted: List[Tuple[Any, Any]] = []

    def classify(value, rebind):
        if isinstance(value, jax.Array) or isinstance(value, np.ndarray):
            sig.append(("ARR", tuple(np.shape(value)), str(np.asarray(value).dtype)
                        if isinstance(value, np.ndarray) else str(value.dtype)))
            lifted.append((rebind, value))
        elif callable(value) and hasattr(value, "__code__"):
            sub_sig, sub_lifted = _walk_fn(value, depth + 1)
            sig.append(("FN", sub_sig))
            lifted.extend(sub_lifted)
        else:
            try:
                hash(value)
                sig.append(("C", value))
            except TypeError:
                sig.append(("R", repr(value)))

    cells = getattr(fn, "__closure__", None) or ()
    for cell in cells:
        try:
            v = cell.cell_contents
        except ValueError:
            sig.append(("EMPTY",))
            continue

        def rebind(x, _cell=cell):
            _cell.cell_contents = x

        classify(v, rebind)
    defaults = getattr(fn, "__defaults__", None) or ()
    for i, v in enumerate(defaults):
        def rebind(x, _fn=fn, _i=i):
            d = list(_fn.__defaults__)
            d[_i] = x
            _fn.__defaults__ = tuple(d)

        classify(v, rebind)
    return tuple(sig), lifted


def _aval_of(x):
    if isinstance(x, LazyValue):
        return x.aval
    return jax.ShapeDtypeStruct(np.shape(x), x.dtype)


# ---------------------------------------------------------------------------
# record + flush
# ---------------------------------------------------------------------------

def record(op_name: str, fn, arrays, fn_sig=None) -> List[LazyValue]:
    """Record one op over ``arrays`` (jax arrays or LazyValues); return the
    output LazyValues (abstract-evaled, cached per signature).

    ``fn_sig``: optional explicit hashable structural signature. When given,
    the closure walk is skipped entirely — the CALLER guarantees that two
    fns carrying the same signature trace identically over same-aval inputs,
    and that every step-varying array the fn reads is passed via ``arrays``
    (nothing is lifted from closures). This is the seam for staged meta-ops
    like the optimizer-update segment."""
    st = _state
    if fn_sig is None:
        fn_sig, lifted = _walk_fn(fn)
    else:
        lifted = []
    in_avals = tuple(
        (a.aval.shape, str(a.aval.dtype)) if isinstance(a, LazyValue)
        else (np.shape(a), str(a.dtype)) for a in arrays)
    key = (op_name, fn_sig, in_avals)
    out_avals = st.aval_cache.get(key)
    if out_avals is None:
        out_avals = jax.eval_shape(fn, *[_aval_of(a) for a in arrays])
        st.aval_cache[key] = out_avals
    multi = isinstance(out_avals, tuple)
    avals = out_avals if multi else (out_avals,)
    outs = []
    for av in avals:
        lv = LazyValue(st.seq, av)
        st.seq += 1
        outs.append(lv)
    st.records.append(_Record(fn, list(arrays), outs, (op_name, fn_sig),
                              lifted))
    return outs, multi


def flush_if_active() -> None:
    if _state.active and _state.records:
        flush()


def flush() -> None:
    """Compile + execute the pending segment; rebind escaping values."""
    st = _state
    records, st.records = st.records, []
    if not records:
        return

    # classify inputs: external arrays (dedup by id) vs internal lazy refs
    ext_arrays: List[Any] = []
    ext_index: Dict[int, int] = {}
    topo = []  # per record: (("x", ext_idx) | ("l", producer_pos, out_slot))
    produced: Dict[int, Tuple[int, int]] = {}  # id(LazyValue) -> (rec, slot)
    for ri, rec in enumerate(records):
        for si, lv in enumerate(rec.out_lazies):
            produced[id(lv)] = (ri, si)
    lifted_arrays: List[Any] = []
    lifted_rebinds: List[Any] = []
    sig_parts: List[Any] = []
    for rec in records:
        refs = []
        for a in rec.inputs:
            if isinstance(a, LazyValue):
                if a.array is not None:  # materialized by an earlier flush
                    idx = ext_index.setdefault(id(a.array), len(ext_arrays))
                    if idx == len(ext_arrays):
                        ext_arrays.append(a.array)
                    refs.append(("x", idx))
                else:
                    pos = produced.get(id(a))
                    if pos is None:
                        raise RuntimeError(
                            "lazy value consumed before being recorded")
                    refs.append(("l",) + pos)
            else:
                idx = ext_index.setdefault(id(a), len(ext_arrays))
                if idx == len(ext_arrays):
                    ext_arrays.append(a)
                refs.append(("x", idx))
        for (_rb, arr) in rec.lifted:
            lifted_rebinds.append(_rb)
            lifted_arrays.append(arr)
        sig_parts.append((rec.fn_sig, tuple(refs), len(rec.out_lazies)))

    # which outputs escape (have a live owner tensor)?
    escaping: List[Tuple[int, int]] = []
    for ri, rec in enumerate(records):
        for si, lv in enumerate(rec.out_lazies):
            if len(lv.owners) > 0:
                escaping.append((ri, si))
    sig = (tuple(sig_parts), tuple(escaping),
           tuple((tuple(np.shape(a)), str(a.dtype)) for a in ext_arrays),
           tuple((tuple(np.shape(a)), str(a.dtype)) for a in lifted_arrays))
    st.last_escapes.append(len(escaping))
    if len(st.last_escapes) > 64:  # debug surface, not a log: keep a window
        del st.last_escapes[:-64]

    jitted = st.compiled.get(sig)
    cache_fill = jitted is None
    if cache_fill:
        n_lifted_per: List[int] = [len(r.lifted) for r in records]

        def replay(ext, lifted_vals):
            vals: List[List[Any]] = []
            li = 0
            for rec2, refs2, nl in zip(records, [s[1] for s in sig_parts],
                                       n_lifted_per):
                # substitute traced values into array-carrying closures
                for k in range(nl):
                    lifted_rebinds_local = lifted_rebinds[li + k]
                    lifted_rebinds_local(lifted_vals[li + k])
                li += nl
                args = []
                for ref in refs2:
                    if ref[0] == "x":
                        args.append(ext[ref[1]])
                    else:
                        args.append(vals[ref[1]][ref[2]])
                out = rec2.fn(*args)
                vals.append(list(out) if isinstance(out, tuple) else [out])
            return [vals[ri][si] for ri, si in escaping]

        jitted = jax.jit(replay)
        st.compiled[sig] = jitted

    # Tracing (cache fill, capture_hlo lower, or an aval-change retrace on
    # the cached path) runs ``replay``, which rebinds lifted closure
    # cells/defaults with jit TRACERS. Restore the original arrays no
    # matter what — a leaked tracer would be lifted into the NEXT segment
    # and crash it with UnexpectedTracerError.
    try:
        if st.capture_hlo:
            if cache_fill:
                st.last_hlos.append(
                    jitted.lower(ext_arrays, lifted_arrays).compile().as_text())
            else:
                st.last_hlos.append("<cached segment>")
        outs = jitted(ext_arrays, lifted_arrays)
    finally:
        for rb, arr in zip(lifted_rebinds, lifted_arrays):
            rb(arr)
    for (ri, si), arr in zip(escaping, outs):
        lv = records[ri].out_lazies[si]
        lv.array = arr
        for t in list(lv.owners):
            if t._data is lv:
                t._data = arr
            g = getattr(t, "_grad", None)
            if g is not None and getattr(g, "_data", None) is lv:
                g._data = arr

    if cache_fill:
        # the cached replay closure only ever reads rec.fn (for retraces on
        # aval change); drop the array references so the cache does not pin
        # this flush's inputs/outputs in device memory for the process
        # lifetime
        for rec in records:
            rec.inputs = None
            rec.out_lazies = None
            rec.lifted = None


def last_segment_hlos() -> List[str]:
    """Debug surface: compiled HLO text of each segment flushed in the most
    recent segment_mode (requires capture enabled via
    ``set_capture_hlo(True)``)."""
    return list(_state.last_hlos)


def set_capture_hlo(flag: bool) -> None:
    _state.capture_hlo = bool(flag)


def last_escape_counts() -> List[int]:
    """Per-flush count of escaping (materialized) outputs in the most
    recent segment_mode — the memory-assertion surface: inference under
    ``no_grad`` must materialize only what the caller actually reads."""
    return list(_state.last_escapes)
