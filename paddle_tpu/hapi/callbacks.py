"""Callbacks for the high-level ``paddle.Model`` API.

Parity surface: python/paddle/hapi/callbacks.py (Callback, ProgBarLogger,
ModelCheckpoint, EarlyStopping, LRScheduler, VisualDL/WandbCallback stubs).
"""

from __future__ import annotations

import os
import sys
import time
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "LRScheduler", "CallbackList", "StepTelemetry"]


class Callback:
    """Base callback: all hooks are no-ops; ``model`` and ``params`` are set
    by the CallbackList before training starts."""

    def __init__(self):
        self.model = None
        self.params: Dict[str, Any] = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = dict(params or {})

    # mode ∈ {train, eval, predict}
    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def append(self, cb: Callback):
        self.callbacks.append(cb)

    def set_model(self, model):
        for cb in self.callbacks:
            cb.set_model(model)

    def set_params(self, params):
        for cb in self.callbacks:
            cb.set_params(params)

    def _call(self, name, *args):
        for cb in self.callbacks:
            getattr(cb, name)(*args)

    def call_shielded(self, name, *args):
        """Invoke a hook on EVERY callback, logging (not propagating) per-
        callback failures — the abort-path teardown contract: one broken
        callback must not rob the rest of their cleanup."""
        import logging
        for cb in self.callbacks:
            try:
                getattr(cb, name)(*args)
            except Exception:
                logging.getLogger(__name__).exception(
                    "callback %s.%s failed during abort teardown",
                    type(cb).__name__, name)

    def call_all(self, name, *args):
        """Invoke a hook on EVERY callback even if one raises, then
        re-raise the FIRST failure — the success-path teardown contract:
        the caller still sees the error, but later callbacks (e.g.
        StepTelemetry restoring global metrics state) are not robbed of
        their cleanup by an earlier one."""
        import logging
        first = None
        for cb in self.callbacks:
            try:
                getattr(cb, name)(*args)
            except Exception as e:
                if first is None:
                    first = e
                else:
                    # later failures would otherwise vanish behind the
                    # re-raised first one — log them, don't swallow
                    logging.getLogger(__name__).exception(
                        "callback %s.%s also failed during teardown",
                        type(cb).__name__, name)
        if first is not None:
            raise first

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *args: self._call(name, *args)
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Per-epoch progress logging. ``verbose``: 0 silent, 1 epoch summary,
    2 per-``log_freq``-step lines (reference default)."""

    def __init__(self, log_freq: int = 10, verbose: int = 2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._t0 = time.time()
        if self.verbose and self.params.get("epochs"):
            print(f"Epoch {epoch + 1}/{self.params['epochs']}")

    def _fmt(self, logs):
        parts = []
        for k, v in (logs or {}).items():
            if isinstance(v, (list, tuple, np.ndarray)):
                parts.append(f"{k}: {np.asarray(v).ravel()}")
            elif isinstance(v, float):
                parts.append(f"{k}: {v:.4f}")
            else:
                parts.append(f"{k}: {v}")
        return " - ".join(parts)

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and step % self.log_freq == 0:
            total = self.steps if self.steps is not None else "?"
            print(f"step {step}/{total} - {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            print(f"Epoch {epoch + 1} done ({dt:.1f}s) - {self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")
            sys.stdout.flush()


class ModelCheckpoint(Callback):
    """Save model+optimizer every ``save_freq`` epochs under ``save_dir``.

    Since PR 10 each save is a VERIFIED checkpoint directory
    (``save_dir/epoch-N/``, ``save_dir/final/``) written by the PR 5
    crash-safe writer — atomic payload, CRC32 manifest committed last,
    ``latest``/``latest.prev`` pointers rotating in ``save_dir`` — instead
    of bare ``.pdparams`` saves a kill could tear. Load with
    ``Model.load_verified`` (checksums verified; a corrupt candidate
    falls back down the pointer chain). ``legacy=True`` restores the old
    ``Model.save``-based file pairs."""

    def __init__(self, save_freq: int = 1, save_dir: str = "checkpoint",
                 legacy: bool = False):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir
        self.legacy = legacy

    def _save(self, name: str) -> None:
        if self.legacy:
            self.model.save(os.path.join(self.save_dir, name))
        else:
            self.model.save_verified(os.path.join(self.save_dir, name))

    def on_epoch_end(self, epoch, logs=None):
        if self.model is not None and epoch % self.save_freq == 0:
            self._save(str(epoch) if self.legacy else f"epoch-{epoch}")

    def on_train_end(self, logs=None):
        # no "final" artifact for a crashed run: a partially-trained model
        # must not be indistinguishable from a completed one
        if self.model is not None and \
                not getattr(self.model, "_train_aborted", False):
            self._save("final")


class EarlyStopping(Callback):
    """Stop when ``monitor`` stops improving (parity: hapi EarlyStopping)."""

    def __init__(self, monitor: str = "loss", mode: str = "auto",
                 patience: int = 0, verbose: int = 1, min_delta: float = 0.0,
                 baseline=None, save_best_model: bool = True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "min" if "loss" in monitor else "max"
        self.mode = mode
        self.reset()

    def reset(self):
        self.wait = 0
        self.stopped_epoch = 0
        self.best = (np.inf if self.mode == "min" else -np.inf) \
            if self.baseline is None else self.baseline

    def _better(self, cur) -> bool:
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_train_begin(self, logs=None):
        self.reset()

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(np.asarray(cur).ravel()[0])
        if self._better(cur):
            self.best = cur
            self.wait = 0
            if self.save_best_model and self.model is not None \
                    and self.params.get("save_dir"):
                self.model.save(os.path.join(self.params["save_dir"],
                                             "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                if self.model is not None:
                    self.model.stop_training = True
                if self.verbose:
                    print(f"Early stopping: {self.monitor} has not improved "
                          f"for {self.wait} evals (best {self.best:.6f})")


class LRScheduler(Callback):
    """Step the optimizer's LRScheduler each batch and/or epoch."""

    def __init__(self, by_step: bool = True, by_epoch: bool = False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        try:
            lr = self.model._optimizer._learning_rate
        except AttributeError:
            return None
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class ReduceLROnPlateau(Callback):
    """Reduce LR when a monitored metric plateaus (reference:
    paddle.callbacks.ReduceLROnPlateau)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor, self.factor, self.patience = monitor, factor, patience
        self.min_delta, self.cooldown, self.min_lr = min_delta, cooldown, min_lr
        self.mode = "min" if mode in ("auto", "min") else "max"
        self.wait = 0
        self.cooldown_counter = 0
        self.best = float("inf") if self.mode == "min" else -float("inf")

    def _better(self, cur):
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    # exactly ONE hook fires per epoch: eval metrics step in on_eval_end,
    # train metrics in on_epoch_end — never both (double-stepping would halve
    # patience and mix two metric series in one plateau tracker)
    def on_eval_end(self, logs=None):
        if self.monitor.startswith("eval_"):
            self._step(logs or {})

    def on_epoch_end(self, epoch, logs=None):
        if not self.monitor.startswith("eval_"):
            self._step(logs or {})

    def _step(self, logs):
        cur = logs.get(self.monitor)
        if cur is None and self.monitor.startswith("eval_"):
            cur = logs.get(self.monitor[len("eval_"):])
        if cur is None:
            return
        cur = float(cur[0] if isinstance(cur, (list, tuple)) else cur)
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self._better(cur):
            self.best = cur
            self.wait = 0
            return
        self.wait += 1
        if self.wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is not None:
                try:
                    lr = opt.get_lr()
                    opt.set_lr(max(lr * self.factor, self.min_lr))
                except RuntimeError:
                    pass  # scheduler-driven LR: the scheduler owns decay
            self.wait = 0
            self.cooldown_counter = self.cooldown


def _scalar_logs(logs):
    """Float-coercible entries of a logs dict (shared by the scalar-sink
    callbacks)."""
    out = {}
    for k, v in (logs or {}).items():
        try:
            out[k] = float(v[0] if isinstance(v, (list, tuple)) else v)
        except (TypeError, ValueError):
            continue
    return out


class StepTelemetry(Callback):
    """Per-step runtime telemetry to a JSONL file (paddle_tpu extension).

    Each train batch appends one record with the step's scalar logs plus
    the observability counter DELTAS for that step (op dispatches, jit
    cache traffic, dataloader waits, ...) and current gauges — the same
    stream ``bench.py`` consumes, surfaced through the hapi loop so any
    ``Model.fit`` run gets step telemetry without a profiler session.

    ``enable_metrics=True`` (default) turns the observability registry on
    for the duration of training and restores the prior enabled state at
    train end; pass False to only record what an already-enabled registry
    collects.
    """

    def __init__(self, path: str, enable_metrics: bool = True):
        super().__init__()
        self.path = path
        self._enable_metrics = enable_metrics
        self._writer = None
        self._global_step = 0
        self._was_enabled = False
        self._began = False

    def on_train_begin(self, logs=None):
        from .. import observability as obs

        # writer first: if the path is unwritable the raise happens BEFORE
        # global state is touched
        self._writer = obs.StepTelemetryWriter(self.path)
        self._was_enabled = obs.enabled()
        if self._enable_metrics:
            obs.enable()
        self._began = True

    def on_train_batch_end(self, step, logs=None):
        self._global_step += 1
        if self._writer is not None:
            self._writer.write(self._global_step, **_scalar_logs(logs))

    def on_train_end(self, logs=None):
        from .. import observability as obs

        if not self._began:
            # a sibling callback's on_train_begin raised before ours ran
            # (fit's finally still fires every teardown hook): we changed
            # no state, so restore nothing
            return
        self._began = False
        try:
            if self._writer is not None:
                self._writer.close()
                self._writer = None
        finally:
            # restore, don't clobber: metrics the USER enabled before
            # fit() must stay on after it — and the restore must happen
            # even when the writer's close/flush raises
            if self._enable_metrics and not self._was_enabled:
                obs.disable()


class VisualDL(Callback):
    """Scalar-sink callback (parity: paddle.callbacks.VisualDL): writes
    per-step train metrics and per-epoch eval metrics through
    ``paddle_tpu.utils.logwriter.LogWriter`` (JSONL event stream)."""

    def __init__(self, log_dir: str = "vdl_log"):
        self.log_dir = log_dir
        self._writer = None
        self._global_step = 0

    def _w(self):
        if self._writer is None:
            from ..utils.logwriter import LogWriter
            self._writer = LogWriter(logdir=self.log_dir)
        return self._writer

    _scalars = staticmethod(_scalar_logs)  # back-compat alias

    def on_train_batch_end(self, step, logs=None):
        self._global_step += 1
        for k, v in self._scalars(logs).items():
            self._w().add_scalar(f"train/{k}", v, self._global_step)

    def on_epoch_end(self, epoch, logs=None):
        for k, v in self._scalars(logs).items():
            self._w().add_scalar(f"train_epoch/{k}", v, epoch)

    def on_eval_end(self, logs=None):
        for k, v in self._scalars(logs).items():
            self._w().add_scalar(f"eval/{k}", v, self._global_step)
        if self._writer is not None:
            self._writer.flush()

    def on_train_end(self, logs=None):
        if self._writer is not None:
            self._writer.close()
            self._writer = None  # a second fit() reopens cleanly
