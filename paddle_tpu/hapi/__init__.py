"""High-level API: ``paddle.Model`` — Keras-like fit/evaluate/predict.

Parity surface: python/paddle/hapi/model.py (Model, prepare/fit/evaluate/
predict/train_batch/eval_batch/predict_batch/save/load/summary) and
python/paddle/hapi/callbacks.py. The training loop is eager by design
(matching the reference's dygraph loop); performance-critical users wrap
their own step in ``paddle.jit.to_static``.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from ..observability import trace as _trace
from . import callbacks as callbacks_mod
from .callbacks import (Callback, CallbackList, ProgBarLogger,
                        ModelCheckpoint, VisualDL)

__all__ = ["Model", "summary"]


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _to_float(x):
    return float(np.asarray(x.numpy() if hasattr(x, "numpy") else x).ravel()[0])


class Model:
    """Wraps an ``nn.Layer`` with a training/eval/predict loop."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Any] = []
        self._amp_level = None
        self.stop_training = False

    # -- setup -------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _as_list(metrics)
        self._amp_level = None
        if amp_configs:
            if isinstance(amp_configs, str):
                self._amp_level = amp_configs
            else:
                self._amp_level = amp_configs.get("level", "O1")
        return self

    def parameters(self):
        return self.network.parameters()

    # -- single-batch ops --------------------------------------------------
    def _compute_loss(self, outputs, labels):
        outs = _as_list(outputs)
        lbls = _as_list(labels)
        if self._loss is None:
            raise RuntimeError("Model.prepare(loss=...) was not called")
        return self._loss(*outs, *lbls)

    def _forward(self, inputs):
        import paddle_tpu as paddle

        if self._amp_level:
            with paddle.amp.auto_cast(level=self._amp_level,
                                      dtype="bfloat16"):
                return self.network(*_as_list(inputs))
        return self.network(*_as_list(inputs))

    def train_batch(self, inputs, labels=None, update=True):
        """One eager train step; returns ([loss_value], [metric_results])."""
        self.network.train()
        outputs = self._forward(inputs)
        loss = self._compute_loss(outputs, labels)
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = self._update_metrics(outputs, labels)
        return [_to_float(loss)], metrics

    # -- whole-step static capture (ISSUE 11) ------------------------------
    def _make_captured_step(self):
        """A :class:`~paddle_tpu.core.step_capture.CapturedStep` for the
        fit loop — forward, backward and the optimizer update compiled
        into ONE donated-buffer XLA program (``PADDLE_TPU_STEP_CAPTURE``;
        ``off`` returns None and the loop stays on eager
        ``train_batch``). Outputs ride out of the program so metrics
        update on concrete arrays after each call."""
        from ..core import step_capture as _cap

        if self._optimizer is None or _cap.mode() == "off":
            return None

        def fwd_bwd(inputs, labels):
            outputs = self._forward(inputs)
            loss = self._compute_loss(outputs, labels)
            loss.backward()
            return loss, outputs

        def update():
            self._optimizer.step()
            self._optimizer.clear_grad()

        return _cap.CapturedStep(fwd_bwd, update_fn=update, label="hapi")

    def _train_batch_captured(self, cap, inputs, labels=None):
        """``train_batch`` over the captured program: one compiled
        dispatch per step instead of one per op (bypasses inside the
        wrapper keep eager semantics, so callers never branch)."""
        self.network.train()
        loss, outputs = cap(inputs, labels)
        metrics = self._update_metrics(outputs, labels)
        return [_to_float(loss)], metrics

    def eval_batch(self, inputs, labels=None):
        import paddle_tpu as paddle

        self.network.eval()
        with paddle.no_grad():
            outputs = self._forward(inputs)
            loss = self._compute_loss(outputs, labels)
        metrics = self._update_metrics(outputs, labels)
        return [_to_float(loss)], metrics

    def predict_batch(self, inputs):
        import paddle_tpu as paddle

        self.network.eval()
        with paddle.no_grad():
            out = self._forward(inputs)
        return [o.numpy() for o in _as_list(out)]

    def _update_metrics(self, outputs, labels):
        results = []
        pred = _as_list(outputs)[0]
        lbl = _as_list(labels)[0] if labels is not None else None
        for m in self._metrics:
            inputs = m.compute(pred, lbl)
            if not isinstance(inputs, (list, tuple)):
                inputs = (inputs,)
            m.update(*inputs)
            results.append(m.accumulate())
        return results

    # -- loops -------------------------------------------------------------
    def _make_loader(self, data, batch_size, shuffle, num_workers, drop_last):
        from ..io import DataLoader, Dataset

        if data is None or isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          num_workers=num_workers, drop_last=drop_last)

    def _metric_logs(self, logs):
        for m in self._metrics:
            names = m.name()
            vals = m.accumulate()
            if isinstance(names, str):
                names, vals = [names], [vals]
            if not isinstance(vals, (list, tuple)):
                vals = [vals]
            for n, v in zip(names, vals):
                logs[n] = v

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            fault_tolerance=None):
        loader = self._make_loader(train_data, batch_size, shuffle,
                                   num_workers, drop_last)
        eval_loader = self._make_loader(eval_data, batch_size, False,
                                        num_workers, False)
        cbks = CallbackList(_as_list(callbacks))
        if verbose:
            cbks.append(ProgBarLogger(log_freq, verbose=verbose))
        if save_dir:
            cbks.append(ModelCheckpoint(save_freq, save_dir))
        cbks.set_model(self)
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        cbks.set_params({"epochs": epochs, "steps": steps,
                         "verbose": verbose, "save_dir": save_dir,
                         "metrics": ["loss"]})
        self.stop_training = False
        self._train_aborted = False
        if fault_tolerance is not None:
            return self._fit_supervised(loader, eval_loader, cbks, epochs,
                                        eval_freq, fault_tolerance)

        history: Dict[str, List[Any]] = {"loss": []}
        logs: Dict[str, Any] = {}
        captured = self._make_captured_step()
        # on_train_end runs even when training (or a sibling callback's
        # on_train_begin) raises: callbacks that hold resources or
        # process-global state (StepTelemetry's JSONL handle + metrics
        # enable) must get their teardown hook on every exit path —
        # teardown hooks are expected to tolerate a begin that never ran
        try:
            with _trace.span("hapi.fit", epochs=epochs):
                cbks.on_train_begin()
                for epoch in range(epochs):
                    cbks.on_epoch_begin(epoch)
                    for m in self._metrics:
                        m.reset()
                    for step, batch in enumerate(loader):
                        cbks.on_train_batch_begin(step)
                        ins, lbls = self._split_batch(batch)
                        with _trace.span("hapi.train_batch", step=step,
                                         epoch=epoch):
                            if captured is not None:
                                losses, _ = self._train_batch_captured(
                                    captured, ins, lbls)
                            else:
                                losses, _ = self.train_batch(ins, lbls)
                        logs = {"loss": losses[0]}
                        self._metric_logs(logs)
                        cbks.on_train_batch_end(step, logs)
                        if self.stop_training:
                            break
                    history["loss"].append(logs.get("loss"))
                    cbks.on_epoch_end(epoch, logs)
                    if eval_loader is not None \
                            and (epoch + 1) % eval_freq == 0:
                        eval_logs = self._run_eval(eval_loader, cbks)
                        for k, v in eval_logs.items():
                            history.setdefault("eval_" + k, []).append(v)
                    if self.stop_training:
                        break
        except BaseException:
            # teardown on the failure path, but never let a teardown error
            # MASK the real training exception; callbacks can see
            # model._train_aborted to skip success-only work (e.g.
            # ModelCheckpoint's "final" save)
            self._train_aborted = True
            cbks.call_shielded("on_train_end", logs)
            raise
        cbks.call_all("on_train_end", logs)
        return history

    def _fit_supervised(self, loader, eval_loader, cbks: CallbackList,
                        epochs: int, eval_freq: int, fault_tolerance):
        """The ``fit(fault_tolerance=...)`` path: the epoch/step loop runs
        under the :class:`~paddle_tpu.resilience.trainer.TrainingSupervisor`
        (per-step retry, watchdog, NaN skip-or-rollback,
        restart-from-last-good, resumable TrainState) while every callback
        hook still fires. The NaN-skip path withholds the optimizer update
        entirely (``train_batch(update=False)`` + a supervisor-driven
        update), so a skipped batch leaves the parameters untouched.

        On an in-process restart the supervisor re-enters the interrupted
        epoch; ``on_epoch_begin`` (and per-epoch metric resets) re-fire for
        it. The loss trajectory is the invariant — bitwise identical to an
        uninterrupted run.
        """
        from ..resilience.trainer import FaultTolerance, TrainingSupervisor

        if isinstance(fault_tolerance, dict):
            fault_tolerance = FaultTolerance(**fault_tolerance)
        if not isinstance(fault_tolerance, FaultTolerance):
            raise TypeError(
                "fault_tolerance must be a resilience.FaultTolerance (or a "
                f"kwargs dict for one), got {type(fault_tolerance).__name__}")
        if self._optimizer is None:
            raise RuntimeError(
                "Model.prepare(optimizer=...) is required for supervised "
                "training")
        sup = TrainingSupervisor(self.network, self._optimizer, loader,
                                 config=fault_tolerance)
        history: Dict[str, List[Any]] = {"loss": []}
        last_logs: Dict[str, Any] = {}

        def update_fn():
            self._optimizer.step()
            self._optimizer.clear_grad()

        def clear_fn():
            self._optimizer.clear_grad()

        from ..core import step_capture as _cap
        if _cap.mode() != "off" and not self._metrics:
            # ISSUE 11: the whole supervised step — fwd, bwd, NaN-gated
            # optimizer update — rides ONE donated compiled program. The
            # gate replaces train_batch(update=False)'s host-side split:
            # a non-finite loss withholds the update in-program, so a
            # skipped batch still leaves the parameters bitwise untouched.
            # (Metrics need eager access to the step's outputs, so a
            # metric-configured fit keeps the eager split step.)
            def fwd_bwd(batch):
                ins, lbls = self._split_batch(batch)
                self.network.train()
                outputs = self._forward(ins)
                loss = self._compute_loss(outputs, lbls)
                loss.backward()
                return loss

            step_fn = _cap.CapturedStep(fwd_bwd, update_fn=update_fn,
                                        clear_fn=clear_fn, nan_gate=True,
                                        label="hapi")
            run_update_fn = None
        else:
            def step_fn(batch):
                ins, lbls = self._split_batch(batch)
                losses, _ = self.train_batch(ins, lbls, update=False)
                return losses[0]

            # metrics accumulate INSIDE this step: the supervisor must not
            # speculatively trace it (a failed trace re-runs eagerly and
            # would double-count the first batch's metric update)
            step_fn.__step_capture__ = False
            run_update_fn = update_fn

        def on_epoch_begin(epoch):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()

        def on_batch_begin(step):
            cbks.on_train_batch_begin(step)

        def on_batch_end(step, loss):
            logs = {"loss": loss}
            self._metric_logs(logs)
            last_logs.clear()
            last_logs.update(logs)
            cbks.on_train_batch_end(step, logs)

        ended_epochs = set()

        def on_epoch_end(epoch):
            if epoch in ended_epochs:
                # a restore rolled the run back INTO an already-completed
                # epoch; its replay ends in a bitwise-identical state, so
                # re-recording it would only duplicate history entries,
                # re-run eval, and double-count EarlyStopping patience
                return
            ended_epochs.add(epoch)
            history["loss"].append(last_logs.get("loss"))
            cbks.on_epoch_end(epoch, dict(last_logs))
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self._run_eval(eval_loader, cbks)
                for k, v in eval_logs.items():
                    history.setdefault("eval_" + k, []).append(v)

        try:
            cbks.on_train_begin()
            report = sup.run(
                step_fn, loader, epochs=epochs, update_fn=run_update_fn,
                clear_fn=clear_fn, on_epoch_begin=on_epoch_begin,
                on_epoch_end=on_epoch_end, on_batch_begin=on_batch_begin,
                on_batch_end=on_batch_end,
                should_stop=lambda: self.stop_training)
        except BaseException:
            self._train_aborted = True
            cbks.call_shielded("on_train_end", dict(last_logs))
            raise
        cbks.call_all("on_train_end", dict(last_logs))
        history["supervisor"] = report
        return history

    def _split_batch(self, batch):
        if isinstance(batch, (list, tuple)):
            if len(batch) == 1:  # unlabeled (predict-style) dataset
                return batch[0], None
            if len(batch) == 2:
                return batch[0], batch[1]
            return list(batch[:-1]), batch[-1]
        return batch, None

    def _run_eval(self, loader, cbks: CallbackList) -> Dict[str, Any]:
        with _trace.span("hapi.eval"):
            return self._run_eval_traced(loader, cbks)

    def _run_eval_traced(self, loader, cbks: CallbackList) -> Dict[str, Any]:
        cbks.on_eval_begin()
        for m in self._metrics:
            m.reset()
        total, n = 0.0, 0
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            ins, lbls = self._split_batch(batch)
            losses, _ = self.eval_batch(ins, lbls)
            total += losses[0]
            n += 1
            cbks.on_eval_batch_end(step, {"loss": losses[0]})
        logs: Dict[str, Any] = {"loss": total / max(n, 1)}
        self._metric_logs(logs)
        cbks.on_eval_end(logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        loader = self._make_loader(eval_data, batch_size, False, num_workers,
                                   False)
        cbks = CallbackList(_as_list(callbacks))
        if verbose:
            cbks.append(ProgBarLogger(log_freq, verbose=min(verbose, 1)))
        cbks.set_model(self)
        cbks.set_params({"metrics": ["loss"]})
        return self._run_eval(loader, cbks)

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._make_loader(test_data, batch_size, False, num_workers,
                                   False)
        cbks = CallbackList(_as_list(callbacks))
        cbks.set_model(self)
        cbks.on_predict_begin()
        outputs: List[List[np.ndarray]] = []
        for step, batch in enumerate(loader):
            cbks.on_predict_batch_begin(step)
            ins, _ = self._split_batch(batch)
            outs = self.predict_batch(ins)
            outputs.append(outs)
            cbks.on_predict_batch_end(step)
        cbks.on_predict_end()
        # regroup: list over model outputs, each a list (or stack) of batches
        n_out = len(outputs[0]) if outputs else 0
        grouped = [[b[i] for b in outputs] for i in range(n_out)]
        if stack_outputs:
            grouped = [np.concatenate(g, axis=0) for g in grouped]
        return grouped

    # -- persistence -------------------------------------------------------
    def save(self, path: str, training: bool = True):
        from ..framework.io import save as _save

        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path: str, skip_mismatch: bool = False, reset_optimizer=False):
        from ..framework.io import load as _load

        self.network.set_state_dict(_load(path + ".pdparams"))
        opt_path = path + ".pdopt"
        if (not reset_optimizer and self._optimizer is not None
                and os.path.exists(opt_path)):
            self._optimizer.set_state_dict(_load(opt_path))

    def _verified_tree(self):
        """model(+optimizer) tensor tree for the crash-safe checkpoint
        writer; the LR-scheduler dict is runtime plumbing the tensor
        loader can't restore and is deliberately excluded (full training
        resume is ``resilience.TrainState``'s job)."""
        tree: Dict[str, Any] = {"model": self.network.state_dict()}
        if self._optimizer is not None:
            od = dict(self._optimizer.state_dict())
            od.pop("LR_Scheduler", None)
            tree["opt"] = od
        return tree

    def save_verified(self, path: str) -> str:
        """Save model+optimizer as one VERIFIED checkpoint directory:
        atomic writes, a CRC32 manifest committed last, and
        ``latest``/``latest.prev`` pointer rotation in the parent
        directory (the PR 5 crash-safe writer). A kill at any point
        leaves the previous checkpoint loadable. Counterpart:
        :meth:`load_verified`."""
        from ..distributed import checkpoint as _ckpt

        _ckpt.save_state_dict(self._verified_tree(), path)
        return path

    def load_verified(self, path: str) -> None:
        """Load a :meth:`save_verified` checkpoint INTO the live
        model/optimizer tensors, verifying the manifest CRCs; a corrupt
        or interrupted candidate falls back down the pointer chain to the
        last-good checkpoint (``checkpoint.fallbacks_total``)."""
        from ..distributed import checkpoint as _ckpt

        if self._optimizer is not None and \
                hasattr(self._optimizer, "_materialize_state"):
            # moments/masters are created lazily on first step(); a fresh
            # model must materialize the load destinations first
            self._optimizer._materialize_state()
        _ckpt.load_state_dict(self._verified_tree(), path)

    def summary(self, input_size=None, dtype=None):
        return summary(self.network)


def summary(net, input_size=None, dtypes=None):
    """Parameter-count summary (parity: paddle.summary). Returns the dict the
    reference returns and prints a per-layer table."""
    rows = []
    total = 0
    trainable = 0
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total += n
        if getattr(p, "trainable", True) and not p.stop_gradient:
            trainable += n
        rows.append((name, tuple(p.shape), n))
    w = max([len(r[0]) for r in rows], default=20) + 2
    lines = [f"{'Layer (param)':<{w}}{'Shape':<24}{'Params':>12}"]
    lines.append("-" * (w + 36))
    for name, shape, n in rows:
        lines.append(f"{name:<{w}}{str(shape):<24}{n:>12,}")
    lines.append("-" * (w + 36))
    lines.append(f"Total params: {total:,}")
    lines.append(f"Trainable params: {trainable:,}")
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}
