"""Loss ops.

Parity surface: python/paddle/nn/functional/loss.py + phi cross_entropy
kernels. ``cross_entropy`` keeps paddle semantics: hard labels (int) or soft
labels, optional label_smoothing, ignore_index, weight, reduction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply
from ._helpers import ensure_tensor, register_op


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def f(logits, lab, *maybe_w):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis) \
            if use_softmax else jnp.log(jnp.clip(logits.astype(jnp.float32), 1e-30))
        n_classes = logits.shape[axis]
        if soft_label:
            target = lab.astype(jnp.float32)
        else:
            li = lab.astype(jnp.int32)
            if li.ndim == logp.ndim and li.shape[axis] == 1:
                li = jnp.squeeze(li, axis=axis)
            target = jax.nn.one_hot(li, n_classes, axis=axis, dtype=jnp.float32)
        if label_smoothing > 0.0:
            target = target * (1.0 - label_smoothing) + label_smoothing / n_classes
        loss = -jnp.sum(target * logp, axis=axis)
        if maybe_w:
            w = maybe_w[0].astype(jnp.float32)
            if soft_label:
                cw = jnp.sum(target * w.reshape((1,) * (target.ndim - 1) + (-1,)), axis=axis)
            else:
                li = lab.astype(jnp.int32)
                if li.ndim == loss.ndim + 1:
                    li = jnp.squeeze(li, axis=axis)
                cw = jnp.take(w, li)
            loss = loss * cw
        if not soft_label and ignore_index >= 0:
            li = lab.astype(jnp.int32)
            if li.ndim == loss.ndim + 1:
                li = jnp.squeeze(li, axis=axis)
            valid = (li != ignore_index)
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
                return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    args = [input, label]
    if weight is not None:
        args.append(ensure_tensor(weight))
    return apply("cross_entropy", f, *args)


register_op("cross_entropy", cross_entropy)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False,
                               axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    # paddle returns loss with a trailing singleton dim on hard labels
    from .manipulation import unsqueeze
    loss = unsqueeze(loss, axis if axis != -1 else -1)
    if return_softmax:
        from .activation import softmax as softmax_op
        return loss, softmax_op(logits, axis=axis)
    return loss


register_op("softmax_with_cross_entropy", softmax_with_cross_entropy)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def f(logp, lab, *maybe_w):
        li = lab.astype(jnp.int32)
        picked = -jnp.take_along_axis(logp, li[..., None] if logp.ndim == li.ndim + 1
                                      else li[:, None], axis=-1)[..., 0]
        if maybe_w:
            picked = picked * jnp.take(maybe_w[0], li)
        if ignore_index >= 0:
            valid = li != ignore_index
            picked = jnp.where(valid, picked, 0.0)
            if reduction == "mean":
                return jnp.sum(picked) / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
        return _reduce(picked, reduction)

    args = [input, label]
    if weight is not None:
        args.append(ensure_tensor(weight))
    return apply("nll_loss", f, *args)


register_op("nll_loss", nll_loss)


def mse_loss(input, label, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply("mse_loss", lambda a, b: _reduce(jnp.square(a - b), reduction),
                 input, label)


def l1_loss(input, label, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply("l1_loss", lambda a, b: _reduce(jnp.abs(a - b), reduction),
                 input, label)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def f(a, b):
        d = a - b
        ad = jnp.abs(d)
        loss = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
        return _reduce(loss, reduction)

    return apply("smooth_l1_loss", f, input, label)


register_op("mse_loss", mse_loss)
register_op("l1_loss", l1_loss)
register_op("smooth_l1_loss", smooth_l1_loss)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def f(p, y, *maybe_w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1.0 - y) * jnp.log(1.0 - p))
        if maybe_w:
            loss = loss * maybe_w[0]
        return _reduce(loss, reduction)

    args = [input, label]
    if weight is not None:
        args.append(ensure_tensor(weight))
    return apply("binary_cross_entropy", f, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    logit, label = ensure_tensor(logit), ensure_tensor(label)

    def f(z, y, *extra):
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = extra[i]; i += 1
        if pos_weight is not None:
            pw = extra[i]
        # stable bce-with-logits
        neg_abs = -jnp.abs(z)
        if pw is not None:
            log_w = (pw - 1.0) * y + 1.0
            loss = (1.0 - y) * z + log_w * (jnp.log1p(jnp.exp(neg_abs)) + jnp.maximum(-z, 0.0))
        else:
            loss = jnp.maximum(z, 0.0) - z * y + jnp.log1p(jnp.exp(neg_abs))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    args = [logit, label]
    if weight is not None:
        args.append(ensure_tensor(weight))
    if pos_weight is not None:
        args.append(ensure_tensor(pos_weight))
    return apply("binary_cross_entropy_with_logits", f, *args)


register_op("binary_cross_entropy", binary_cross_entropy)
register_op("binary_cross_entropy_with_logits", binary_cross_entropy_with_logits)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def f(logp, y):
        if log_target:
            loss = jnp.exp(y) * (y - logp)
        else:
            loss = y * (jnp.log(jnp.clip(y, 1e-12)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)

    return apply("kl_div", f, input, label)


register_op("kl_div", kl_div)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def f(a, y):
        loss = jnp.where(y == 1.0, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)

    return apply("hinge_embedding_loss", f, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    input, other, label = ensure_tensor(input), ensure_tensor(other), ensure_tensor(label)

    def f(a, b, y):
        return _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction)

    return apply("margin_ranking_loss", f, input, other, label)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    input1, input2, label = ensure_tensor(input1), ensure_tensor(input2), ensure_tensor(label)

    def f(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / (
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1) + 1e-12)
        loss = jnp.where(y == 1, 1.0 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return apply("cosine_embedding_loss", f, input1, input2, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6,
                        swap=False, reduction="mean", name=None):
    input, positive, negative = (ensure_tensor(input), ensure_tensor(positive),
                                 ensure_tensor(negative))

    def f(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return apply("triplet_margin_loss", f, input, positive, negative)


register_op("hinge_embedding_loss", hinge_embedding_loss)
register_op("margin_ranking_loss", margin_ranking_loss)
register_op("cosine_embedding_loss", cosine_embedding_loss)
register_op("triplet_margin_loss", triplet_margin_loss)


def square_error_cost(input, label):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply("square_error_cost", lambda a, b: jnp.square(a - b), input, label)


register_op("square_error_cost", square_error_cost)


def log_loss(input, label, epsilon=1e-4, name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply("log_loss",
                 lambda p, y: -y * jnp.log(p + epsilon) - (1.0 - y) * jnp.log(1.0 - p + epsilon),
                 input, label)


register_op("log_loss", log_loss)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    logit, label = ensure_tensor(logit), ensure_tensor(label)

    def f(z, y, *maybe_n):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0.0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1.0 - p) * (1.0 - y)
        a_t = alpha * y + (1.0 - alpha) * (1.0 - y)
        loss = a_t * jnp.power(1.0 - p_t, gamma) * ce
        if maybe_n:
            loss = loss / maybe_n[0]
        return _reduce(loss, reduction)

    args = [logit, label]
    if normalizer is not None:
        args.append(ensure_tensor(normalizer))
    return apply("sigmoid_focal_loss", f, *args)


register_op("sigmoid_focal_loss", sigmoid_focal_loss)
