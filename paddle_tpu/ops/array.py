"""TensorArray ops: ``create_array`` / ``array_write`` / ``array_read`` /
``array_length`` / ``tensor_array_to_tensor``.

Parity surface: python/paddle/tensor/array.py backed by the reference's
``phi::TensorArray`` (paddle/phi/core/ — a vector-of-DenseTensor used by the
legacy while_op to carry per-iteration values).

TPU-native design: in eager mode a TensorArray is a host-side Python list of
device arrays (no device-side dynamic container exists on XLA, same reason
the reference keeps TensorArray on the host). Inside ``jit``/``lax`` loops a
dynamic-length array cannot exist — use ``lax.scan`` via ``paddle.jit`` or
pre-size the array; ``tensor_array_to_tensor`` stacks/concats to a dense
Tensor for compiled consumption.
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp

from ..core.tensor import Tensor
from ._helpers import ensure_tensor, register_op


class TensorArray(list):
    """List-of-Tensor with the reference's write/read/length surface."""

    def write(self, i: int, value: Tensor) -> "TensorArray":
        i = int(i)
        if i < len(self):
            self[i] = value
        else:
            self.extend([None] * (i - len(self)))  # sparse writes pad w/ None
            self.append(value)
        return self

    def read(self, i: int) -> Tensor:
        return self[int(i)]


def create_array(dtype: str = "float32", initialized_list=None) -> TensorArray:
    arr = TensorArray()
    if initialized_list:
        for v in initialized_list:
            arr.append(ensure_tensor(v))
    return arr


def array_write(x, i, array: Optional[TensorArray] = None) -> TensorArray:
    if array is None:
        array = TensorArray()
    array.write(int(i), ensure_tensor(x))
    return array


def array_read(array: TensorArray, i) -> Tensor:
    return array.read(int(i))


def array_length(array: TensorArray) -> Tensor:
    return Tensor(jnp.asarray(len(array), jnp.int32))


def tensor_array_to_tensor(array: TensorArray, axis: int = 1,
                           use_stack: bool = False):
    """Dense-ify: stack (new axis) or concat along ``axis``. Returns
    (tensor, index) like the reference (index = per-element sizes)."""
    datas = [ensure_tensor(t)._data for t in array if t is not None]
    if use_stack:
        out = jnp.stack(datas, axis=axis)
        sizes = jnp.asarray([1] * len(datas), jnp.int32)
    else:
        out = jnp.concatenate(datas, axis=axis)
        sizes = jnp.asarray([d.shape[axis] for d in datas], jnp.int32)
    return Tensor(out), Tensor(sizes)


for _name, _fn in [("create_array", create_array), ("array_write", array_write),
                   ("array_read", array_read), ("array_length", array_length),
                   ("tensor_array_to_tensor", tensor_array_to_tensor)]:
    register_op(_name, _fn, methods=())
