"""Tensor-API wave 4: trace/view/polar/pdist/igamma/sinc/reduce_as &co.

Parity: python/paddle/tensor/ (math.py, manipulation.py, random.py — the
2.6/3.0-era additions). Pure jnp/lax bodies dispatched through ``apply``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply
from ._helpers import ensure_tensor, register_op

__all__ = [
    "trace", "view", "polar", "pdist", "igamma", "igammac", "log_normal",
    "sinc", "reduce_as",
]


def trace(x, offset: int = 0, axis1: int = 0, axis2: int = 1, name=None):
    """Sum of diagonal elements (paddle.trace)."""
    def f(a):
        return jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2)
    return apply("trace", f, ensure_tensor(x))


def view(x, shape_or_dtype, name=None):
    """paddle.view: zero-copy reshape (list/tuple) or dtype reinterpret
    (str/dtype). On an immutable jax payload this is a pure op; XLA emits a
    bitcast/reshape with no data movement."""
    from ..core import dtype as _dtype

    x = ensure_tensor(x)
    if isinstance(shape_or_dtype, (list, tuple)):
        shape = tuple(int(s) for s in shape_or_dtype)

        def f(a):
            return a.reshape(shape)
        return apply("view", f, x)
    dt = _dtype.convert_dtype(shape_or_dtype)
    src_size = x._data.dtype.itemsize
    dst_size = jnp.dtype(dt).itemsize
    if x._data.ndim == 0 and dst_size != src_size:
        raise ValueError(
            "view: dtype reinterpret of a 0-d tensor with a different "
            "byte width is undefined; reshape to (1,) first")

    def f(a):
        # paddle.view(dtype) rescales the LAST dim by the byte-width ratio;
        # lax.bitcast adds/removes a trailing axis, so reshape around it
        if dst_size < src_size:
            out = jax.lax.bitcast_convert_type(a, dt)  # (..., k)
            return out.reshape(a.shape[:-1] +
                               (a.shape[-1] * (src_size // dst_size),))
        if dst_size > src_size:
            k = dst_size // src_size
            if a.shape[-1] % k != 0:
                raise ValueError(
                    f"view: last dim {a.shape[-1]} not divisible by the "
                    f"dtype width ratio {k}")
            return jax.lax.bitcast_convert_type(
                a.reshape(a.shape[:-1] + (a.shape[-1] // k, k)), dt)
        return jax.lax.bitcast_convert_type(a, dt)
    return apply("view", f, x, differentiable=False)


def polar(abs, angle, name=None):
    """Complex from magnitude and phase (paddle.polar)."""
    def f(r, t):
        return jax.lax.complex(r * jnp.cos(t), r * jnp.sin(t))
    return apply("polar", f, ensure_tensor(abs), ensure_tensor(angle))


def pdist(x, p: float = 2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    """Condensed pairwise distances of an (N, D) matrix — the upper
    triangle of cdist(x, x), shape (N*(N-1)/2,) (paddle.pdist)."""
    x = ensure_tensor(x)
    n = int(x._data.shape[0])
    iu, ju = jnp.triu_indices(n, k=1)

    def f(a):
        d = a[iu] - a[ju]
        if p == 2.0:
            return jnp.sqrt(jnp.sum(d * d, axis=-1) + 1e-30)
        if p == float("inf"):
            return jnp.max(jnp.abs(d), axis=-1)
        return jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)

    return apply("pdist", f, x)


def igamma(x, a, name=None):
    """UPPER regularized incomplete gamma Q(x, a) — the reference's naming
    is inverted relative to scipy (paddle.igamma == gammaincc)."""
    def f(xx, aa):
        return jax.scipy.special.gammaincc(xx, aa)
    return apply("igamma", f, ensure_tensor(x), ensure_tensor(a))


def igammac(x, a, name=None):
    """LOWER regularized incomplete gamma P(x, a) (paddle.igammac ==
    scipy gammainc)."""
    def f(xx, aa):
        return jax.scipy.special.gammainc(xx, aa)
    return apply("igammac", f, ensure_tensor(x), ensure_tensor(a))


def log_normal(mean: float = 1.0, std: float = 2.0, shape=None, dtype=None,
               name=None):
    """Samples where log(x) ~ N(mean, std) (paddle.log_normal)."""
    from ..core import dtype as _dtype
    from ..core.random import default_generator

    dt = _dtype.convert_dtype(dtype) if dtype is not None else jnp.float32
    key = default_generator.split_key()
    shape = tuple(shape or ())

    def f():
        return jnp.exp(mean + std * jax.random.normal(key, shape, dt))

    return apply("log_normal", f, differentiable=False)


def sinc(x, name=None):
    """Normalized sinc: sin(pi x)/(pi x), 1 at 0 (paddle.sinc)."""
    def f(a):
        return jnp.sinc(a)
    return apply("sinc", f, ensure_tensor(x))


def reduce_as(x, target, name=None):
    """Sum-reduce ``x`` down to ``target``'s shape (paddle.reduce_as —
    the broadcast-adjoint used by custom grads)."""
    x, target = ensure_tensor(x), ensure_tensor(target)
    tgt_shape = tuple(target._data.shape)
    x_shape = tuple(x._data.shape)
    trail = x_shape[len(x_shape) - len(tgt_shape):] if tgt_shape else ()
    if len(tgt_shape) > len(x_shape) or any(
            t != s and t != 1 for s, t in zip(trail, tgt_shape)):
        raise ValueError(
            f"reduce_as: target shape {tgt_shape} is not broadcast-"
            f"reducible from input shape {x_shape}")

    def f(a, _t):
        extra = a.ndim - len(tgt_shape)
        if extra > 0:
            a = jnp.sum(a, axis=tuple(range(extra)))
        keep = tuple(i for i, (s, t) in enumerate(zip(a.shape, tgt_shape))
                     if s != t and t == 1)
        if keep:
            a = jnp.sum(a, axis=keep, keepdims=True)
        return a

    return apply("reduce_as", f, x, target)


register_op("trace", trace, methods=("trace",))
register_op("view", view, methods=("view",))
register_op("polar", polar)
register_op("pdist", pdist)
register_op("igamma", igamma, methods=("igamma",))
register_op("igammac", igammac, methods=("igammac",))
register_op("log_normal", log_normal)
register_op("sinc", sinc, methods=("sinc",))
register_op("reduce_as", reduce_as)


def cartesian_prod(x, name=None):
    """Cartesian product of 1-D tensors (paddle.cartesian_prod)."""
    xs = [ensure_tensor(t) for t in x]

    def f(*arrs):
        grids = jnp.meshgrid(*arrs, indexing="ij")
        return jnp.stack([g.reshape(-1) for g in grids], axis=-1)

    return apply("cartesian_prod", f, *xs)


def numel(x, name=None):
    """Element count as a 0-d int64 tensor (paddle.numel)."""
    x = ensure_tensor(x)
    n = 1
    for s_ in x._data.shape:
        n *= int(s_)

    from ..core import dtype as _dtype

    def f(_a):
        # int64 when x64 is enabled, canonical int otherwise (no per-call
        # truncation warning)
        return jnp.asarray(n, _dtype.canonicalize(jnp.int64))

    return apply("numel", f, x, differentiable=False)


register_op("cartesian_prod", cartesian_prod)
register_op("numel", numel)


# ---------------------------------------------------------------------------
# method-binding wave: reference Tensor methods whose functions existed at
# module level only, plus small missing free functions
# ---------------------------------------------------------------------------

def floor_mod(x, y, name=None):
    """Alias of elementwise mod (paddle.floor_mod == paddle.mod)."""
    from ._helpers import OP_REGISTRY
    return OP_REGISTRY["mod"](x, y)


def increment(x, value=1.0, name=None):
    """In-place scalar increment (paddle.increment): returns x after
    x += value (0-d/1-element tensors in the reference)."""
    x = ensure_tensor(x)

    def f(a):
        return a + jnp.asarray(value, a.dtype)

    out = apply("increment", f, x)
    x._rebind(out)
    return x


def is_empty(x, name=None):
    """Whether the tensor has zero elements (paddle.is_empty)."""
    x = ensure_tensor(x)
    n = 1
    for d in x._data.shape:
        n *= int(d)

    def f(_a):
        return jnp.asarray(n == 0)

    return apply("is_empty", f, x, differentiable=False)


def unstack(x, axis=0, num=None, name=None):
    """Split along ``axis`` into that dimension's count of tensors, each
    with the axis removed (paddle.unstack)."""
    x = ensure_tensor(x)
    ax = int(axis)
    n = int(x._data.shape[ax]) if num is None else int(num)

    def f(a):
        return tuple(jnp.squeeze(s, axis=ax)
                     for s in jnp.split(a, n, axis=ax))

    out = apply("unstack", f, x)
    return list(out)


register_op("floor_mod", floor_mod, methods=("floor_mod",))
register_op("increment", increment)
register_op("is_empty", is_empty, methods=("is_empty",))
register_op("unstack", unstack, methods=("unstack",))

# bind existing free functions as Tensor methods (reference method surface)
from ..core.tensor import register_tensor_method as _rtm
from ._helpers import OP_REGISTRY as _REG


def _bind_existing_methods():
    from .. import linalg as _linalg
    for name in ("cholesky", "eig", "eigvals", "lu", "solve"):
        fn = _REG.get(name) or getattr(_linalg, name, None)
        if fn is not None:
            _rtm(name, fn)
    _rtm("increment", increment)


_bind_existing_methods()


# ---------------------------------------------------------------------------
# top-level tail (round-3 probe): add_n / remainder / rank / shape /
# shard_index (upstream python/paddle/tensor/ surface)
# ---------------------------------------------------------------------------

def add_n(inputs, name=None):
    """Elementwise sum of a list of tensors (reference: paddle.add_n)."""
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    ts = [ensure_tensor(t) for t in inputs]
    return apply("add_n", lambda *xs: functools.reduce(jnp.add, xs), *ts)


def remainder(x, y, name=None):
    """Python-style modulo (alias of paddle.mod)."""
    return _REG["mod"](x, y)


def rank(x, name=None):
    """Tensor of the input's rank (reference: paddle.rank returns a 0-D
    int32 tensor, usable in static graphs)."""
    from ..core.tensor import Tensor
    x = ensure_tensor(x)
    return Tensor(jnp.asarray(x._data.ndim, jnp.int32), stop_gradient=True)


def shape(x, name=None):
    """1-D int32 tensor holding the input's shape (reference: paddle.shape).
    Static shapes on XLA: the values are compile-time constants."""
    from ..core.tensor import Tensor
    x = ensure_tensor(x)
    return Tensor(jnp.asarray(x._data.shape, jnp.int32), stop_gradient=True)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1,
                name=None):
    """Recompute global ids into shard-local ids (reference:
    paddle.shard_index; the vocab-parallel embedding helper): ids whose
    shard (id // shard_size) equals ``shard_id`` map to id - shard_id *
    shard_size; everything else becomes ``ignore_value``."""
    if shard_id < 0 or shard_id >= nshards:
        raise ValueError(
            f"shard_id {shard_id} out of range for nshards {nshards}")
    x = ensure_tensor(input)
    shard_size = (index_num + nshards - 1) // nshards

    def f(ids):
        local = ids - shard_id * shard_size
        mine = (ids // shard_size) == shard_id
        return jnp.where(mine, local, ignore_value)

    return apply("shard_index", f, x, differentiable=False)


import functools  # noqa: E402  (used by add_n)

register_op("add_n", add_n)
register_op("remainder", remainder, inplace_method="remainder_")
register_op("rank", rank)
register_op("shape", shape)
register_op("shard_index", shard_index)

_rtm("rank", rank)
_rtm("shape_tensor", shape)


def is_tensor(x):
    """reference: paddle.is_tensor."""
    from ..core.tensor import Tensor
    return isinstance(x, Tensor)


register_op("is_tensor", is_tensor)


def msort(x, name=None):
    """Sort along the FIRST axis (reference: paddle.msort == sort(x, 0))."""
    return apply("msort", lambda a: jnp.sort(a, axis=0), ensure_tensor(x))


def float_power(x, y, name=None):
    """Element-wise x**y computed in the widest float (reference promotes
    to float64; TPU compute clamps to fp32 — MIGRATING.md divergence #7)."""
    x = ensure_tensor(x)
    if isinstance(y, Tensor):
        return apply("float_power",
                     lambda a, b: jnp.power(a.astype(jnp.float32),
                                            b.astype(jnp.float32)), x, y)
    return apply("float_power",
                 lambda a: jnp.power(a.astype(jnp.float32), float(y)), x)


def binomial(count, prob, name=None):
    """Draw Binomial(count, prob) samples (reference: paddle.binomial;
    int64 output, per-element n/p broadcasting)."""
    from ..core.random import default_generator
    count = ensure_tensor(count)
    prob = ensure_tensor(prob)
    key = default_generator.split_key()

    def f(n, p):
        out = jax.random.binomial(key, n.astype(jnp.float32),
                                  p.astype(jnp.float32))
        # reference returns int64; x64-off canonicalizes to int32 (same
        # policy as every integer-output op here)
        return out.astype(jnp.int32)

    return apply("binomial", f, count, prob, differentiable=False)


register_op("msort", msort, methods=("msort",))
register_op("float_power", float_power, methods=("float_power",))
register_op("binomial", binomial)
