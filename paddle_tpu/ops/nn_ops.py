"""Core nn-functional ops: linear, embedding, dropout, normalization, attention.

Parity surface: python/paddle/nn/functional/common.py + norm.py + input.py and
the phi fused kernels (fused_attention, fused_feedforward — upstream
paddle/phi/kernels/fusion/). TPU-native: these stay as composed jnp ops; XLA
fuses them, and the flash-attention Pallas kernel (ops/flash_attention.py)
covers the long-context case.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.random import default_generator
from ..core.tensor import Tensor, apply
from ._helpers import ensure_tensor, register_op
from .. import flags as _flags


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b, W stored (in_features, out_features) as in paddle."""
    x, weight = ensure_tensor(x), ensure_tensor(weight)
    prec = None if _flags.flag("tpu_matmul_precision") == "default" else \
        _flags.flag("tpu_matmul_precision")
    if bias is not None:
        return apply("linear",
                     lambda a, w, b: jnp.matmul(a, w, precision=prec) + b,
                     x, weight, ensure_tensor(bias))
    return apply("linear", lambda a, w: jnp.matmul(a, w, precision=prec), x, weight)


register_op("linear", linear)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    x, weight = ensure_tensor(x), ensure_tensor(weight)

    def f(i, w):
        out = jnp.take(w, i.astype(jnp.int32), axis=0)
        if padding_idx is not None:
            mask = (i == padding_idx)[..., None]
            out = jnp.where(mask, jnp.zeros_like(out), out)
        return out

    if sparse:
        return _sparse_embedding(x, weight, f, padding_idx)
    return apply("embedding", f, x, weight)


def _sparse_embedding(x, weight, f, padding_idx):
    """``sparse=True``: the weight gradient is emitted as SelectedRows
    (rows=the looked-up ids, values=the output cotangent rows) instead of a
    dense (vocab, dim) scatter — upstream lookup_table's sparse-grad path
    (paddle/phi/core/selected_rows.h). Only leaf weights qualify (a derived
    weight needs the dense vjp to keep flowing); non-leaf or no-grad cases
    fall back to the dense path."""
    from ..core import lazy as _lazy
    from ..core import tracing as _tracing
    from ..core.autograd import GradNode
    from ..core.selected_rows import SelectedRows
    from ..core.tensor import Tensor

    needs_grad = (_tracing.grad_enabled() and not weight.stop_gradient
                  and weight._grad_node is None)
    if not needs_grad or _lazy.active():
        # segment mode stages ops through apply(); the manual sparse node
        # reads ids eagerly, so it densifies there (correct, just dense)
        return apply("embedding", f, x, weight)

    ts = _tracing.trace_state()
    for t in (x, weight):
        from ..core.tensor import _is_tracer
        if ts is not None and not _is_tracer(t._data):
            ts.record_read(t)
    ids = x._data.astype(jnp.int32)
    out_arr = f(ids, weight._data)
    dim_nd = weight._data.ndim - 1  # trailing embedding dims
    vocab_shape = tuple(weight._data.shape)

    def sparse_vjp(cot):
        rows = ids.reshape(-1)
        vals = cot.reshape((-1,) + cot.shape[cot.ndim - dim_nd:])
        if padding_idx is not None:
            vals = jnp.where((rows == padding_idx)[:, None],
                             jnp.zeros_like(vals), vals)
        return (None, SelectedRows(rows, vals, vocab_shape))

    node = GradNode("embedding_sparse", sparse_vjp, (x, weight), 1,
                    ((out_arr.shape, out_arr.dtype),), pure_fn=None,
                    multi_out=False)
    out = Tensor(out_arr, stop_gradient=False)
    out._grad_node = node
    out._grad_index = 0
    return out


register_op("embedding", embedding)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    x = ensure_tensor(x)
    if not training or p == 0.0:
        return apply("dropout_noop", lambda a: a, x)
    key = default_generator.split_key()

    def f(a):
        shape = list(a.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in [ax % a.ndim for ax in axes] else 1
                     for i, s in enumerate(a.shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), jnp.zeros_like(a))
        return jnp.where(keep, a, jnp.zeros_like(a))

    return apply("dropout", f, x)


register_op("dropout", dropout)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=ax, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=ax, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = ensure_tensor(x)
    if not training or p == 0.0:
        return apply("dropout_noop", lambda a: a, x)
    key = default_generator.split_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def f(a):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p ** 2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return a_coef * jnp.where(keep, a, alpha_p) + b_coef

    return apply("alpha_dropout", f, x)


register_op("dropout2d", dropout2d)
register_op("dropout3d", dropout3d)
register_op("alpha_dropout", alpha_dropout)


# --- normalization -----------------------------------------------------------

def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    x = ensure_tensor(x)
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(list(normalized_shape))

    def core(a, *wb):
        axes = tuple(range(a.ndim - n_axes, a.ndim))
        mu = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = (a.astype(jnp.float32) - mu) * jax.lax.rsqrt(var + epsilon)
        out = out.astype(a.dtype)
        i = 0
        if weight is not None:
            out = out * wb[i]
            i += 1
        if bias is not None:
            out = out + wb[i]
        return out

    args = [x]
    if weight is not None:
        args.append(ensure_tensor(weight))
    if bias is not None:
        args.append(ensure_tensor(bias))
    return apply("layer_norm", core, *args)


register_op("layer_norm", layer_norm)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    x = ensure_tensor(x)

    def core(a, *w):
        ms = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=-1, keepdims=True)
        out = (a.astype(jnp.float32) * jax.lax.rsqrt(ms + epsilon)).astype(a.dtype)
        if w:
            out = out * w[0]
        return out

    if weight is not None:
        return apply("rms_norm", core, x, ensure_tensor(weight))
    return apply("rms_norm", core, x)


register_op("rms_norm", rms_norm)


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NCHW", use_global_stats=None,
               name=None):
    """Functional batch norm. In training mode computes batch stats, updates
    the running buffers in place (trace-visible mutation), and normalizes with
    batch stats; in eval mode uses the running buffers."""
    x = ensure_tensor(x)
    rm, rv = ensure_tensor(running_mean), ensure_tensor(running_var)
    use_batch_stats = training and not use_global_stats

    ch_axis = 1 if data_format.startswith("NC") else x._data.ndim - 1
    reduce_axes = tuple(i for i in range(x._data.ndim) if i != ch_axis)

    def shape_for(b, nd):
        s = [1] * nd
        s[ch_axis] = b.size
        return s

    if use_batch_stats:
        # compute batch stats through apply so grads flow; update buffers
        def stats(a):
            a32 = a.astype(jnp.float32)
            mu = jnp.mean(a32, axis=reduce_axes)
            var = jnp.var(a32, axis=reduce_axes)
            return mu, var

        mu_t, var_t = apply("batch_norm_stats", stats, x)
        # momentum update of running buffers (paddle: r = m*r + (1-m)*batch)
        rm._set_data(momentum * rm._data + (1.0 - momentum) * mu_t._data.astype(rm._data.dtype))
        n = int(np.prod([x._data.shape[i] for i in reduce_axes]))
        unbiased = var_t._data * (n / max(n - 1, 1))
        rv._set_data(momentum * rv._data + (1.0 - momentum) * unbiased.astype(rv._data.dtype))
        mean_used, var_used = mu_t, var_t
    else:
        mean_used, var_used = rm, rv

    def norm_fn(a, mu, var, *wb):
        nd = a.ndim
        mu = mu.reshape(shape_for(mu, nd)).astype(jnp.float32)
        var = var.reshape(shape_for(var, nd)).astype(jnp.float32)
        out = (a.astype(jnp.float32) - mu) * jax.lax.rsqrt(var + epsilon)
        out = out.astype(a.dtype)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape_for(wb[i], nd))
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape_for(wb[i], nd))
        return out

    args = [x, mean_used, var_used]
    if weight is not None:
        args.append(ensure_tensor(weight))
    if bias is not None:
        args.append(ensure_tensor(bias))
    return apply("batch_norm", norm_fn, *args)


register_op("batch_norm", batch_norm)


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW",
                  name=None):
    x = ensure_tensor(x)

    def core(a, *wb):
        axes = tuple(range(2, a.ndim))
        mu = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = ((a.astype(jnp.float32) - mu) * jax.lax.rsqrt(var + eps)).astype(a.dtype)
        i = 0
        if weight is not None:
            shape = [1, -1] + [1] * (a.ndim - 2)
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            shape = [1, -1] + [1] * (a.ndim - 2)
            out = out + wb[i].reshape(shape)
        return out

    args = [x]
    if weight is not None:
        args.append(ensure_tensor(weight))
    if bias is not None:
        args.append(ensure_tensor(bias))
    return apply("instance_norm", core, *args)


register_op("instance_norm", instance_norm)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    x = ensure_tensor(x)

    def core(a, *wb):
        n, c = a.shape[0], a.shape[1]
        g = num_groups
        rest = a.shape[2:]
        ag = a.reshape((n, g, c // g) + rest).astype(jnp.float32)
        axes = tuple(range(2, ag.ndim))
        mu = jnp.mean(ag, axis=axes, keepdims=True)
        var = jnp.var(ag, axis=axes, keepdims=True)
        out = ((ag - mu) * jax.lax.rsqrt(var + epsilon)).reshape(a.shape).astype(a.dtype)
        shape = [1, c] + [1] * (a.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = [x]
    if weight is not None:
        args.append(ensure_tensor(weight))
    if bias is not None:
        args.append(ensure_tensor(bias))
    return apply("group_norm", core, *args)


register_op("group_norm", group_norm)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                        name=None):
    x = ensure_tensor(x)

    def f(a):
        sq = jnp.square(a)
        half = size // 2
        pad = [(0, 0)] * a.ndim
        pad[1] = (half, size - half - 1)
        padded = jnp.pad(sq, pad)
        win = [1] * a.ndim
        win[1] = size
        s = jax.lax.reduce_window(padded, 0.0, jax.lax.add, tuple(win),
                                  (1,) * a.ndim, "VALID")
        return a / jnp.power(k + alpha * s, beta)

    return apply("local_response_norm", f, x)


register_op("local_response_norm", local_response_norm)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    x = ensure_tensor(x)

    def f(a):
        if p == 2:
            n = jnp.sqrt(jnp.sum(a * a, axis=axis, keepdims=True))
        else:
            n = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(n, epsilon)

    return apply("normalize", f, x)


register_op("normalize", normalize)


# --- attention ---------------------------------------------------------------

_flags.define_flag(
    "sdpa_flash_min_seqlen", 0,
    "scaled_dot_product_attention routes to the flash kernel above this "
    "query length (default 0 = always flash when mask/dropout-free: with the "
    "dedicated Pallas backward the flash path beats stored-probs XLA "
    "attention at every measured length — see benchmarks/RESULTS.md)")

def _sdpa_flash_backend_ok():
    """Routing predicate only (seam for tests): the kernel picks its own
    interpret mode from the REAL backend inside _flash_dispatch."""
    return jax.default_backend() not in ("cpu",)


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    """Paddle SDPA parity. Inputs (B, L, H, D) as in paddle's flash-attn API.

    Uses the Pallas flash-attention kernel on TPU for long sequences when
    available; falls back to the fused XLA softmax-attention otherwise.
    """
    query, key, value = ensure_tensor(query), ensure_tensor(key), ensure_tensor(value)
    flash_ok = (_sdpa_flash_backend_ok()
                and query._data.shape[1] >= int(
                    _flags.flag("sdpa_flash_min_seqlen")))
    # training-time dropout STAYS on the flash path: the round-5 in-kernel
    # attention-prob dropout (stateless coordinate-hash keep mask, regenerated
    # bit-exactly by the backward kernels) — the old predicate here routed it
    # to stored-probs XLA attention, re-materializing (Lq, Lk) probs and
    # OOMing at seq 8192 (VERDICT r5 Weak #1)
    flash_dropout = dropout_p if training else 0.0
    if attn_mask is None and flash_ok:
        # (CPU keeps the fused XLA path — the Pallas kernel would run in
        # interpret mode there; call F.flash_attention directly to force it)
        # mask-free attention takes the flash path: Pallas online-softmax
        # forward + dedicated dq/dkv backward kernels — O(L) activation
        # memory and faster than stored-probs XLA attention at every
        # measured length (flip FLAGS_sdpa_flash_min_seqlen to re-threshold)
        from .flash_attention import flash_attention
        return flash_attention(query, key, value, dropout=flash_dropout,
                               causal=is_causal, training=training)
    if attn_mask is not None and flash_ok:
        # KEY-PADDING masks stay on the flash path as segment ids: a boolean
        # mask that is constant across query rows and heads — (B, Lk),
        # (B, 1, Lk) or (B, 1|H->1, 1, Lk) — means "key j is visible to every
        # row or to none", i.e. kv_segment_ids. Anything row-varying falls
        # through to the fused XLA path below. (Divergence note: a row with
        # ALL keys padded emits 0 on the flash path; XLA softmax would emit
        # the uniform average — such rows are padding and discarded anyway.)
        m = ensure_tensor(attn_mask)._data
        kv_valid = None
        if m.dtype == jnp.bool_:
            # NOTE: a 2-D bool mask is (Lq, Lk) under upstream broadcast
            # semantics (row-varying) — it must NOT take this route
            if m.ndim == 3 and m.shape[1] == 1:
                kv_valid = m[:, 0, :]
            elif m.ndim == 4 and m.shape[1] == 1 and m.shape[2] == 1:
                kv_valid = m[:, 0, 0, :]
        if kv_valid is not None:
            from .flash_attention import flash_attention
            b = query._data.shape[0]
            lq = query._data.shape[1]
            q_segs = Tensor(jnp.ones((b, lq), jnp.int32))
            kv_segs = Tensor(kv_valid.astype(jnp.int32))
            return flash_attention(query, key, value, dropout=flash_dropout,
                                   causal=is_causal, training=training,
                                   q_segment_ids=q_segs,
                                   kv_segment_ids=kv_segs)
    dkey = default_generator.split_key() if (dropout_p > 0.0 and training) else None

    def f(q, k, v, *maybe_mask):
        # (B, L, H, D) -> (B, H, L, D)
        qh = jnp.swapaxes(q, 1, 2)
        kh = jnp.swapaxes(k, 1, 2)
        vh = jnp.swapaxes(v, 1, 2)
        scale = 1.0 / np.sqrt(qh.shape[-1])
        # GQA: broadcast kv heads if fewer than q heads
        if kh.shape[1] != qh.shape[1]:
            rep = qh.shape[1] // kh.shape[1]
            kh = jnp.repeat(kh, rep, axis=1)
            vh = jnp.repeat(vh, rep, axis=1)
        logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
        if is_causal:
            ql, kl = logits.shape[-2], logits.shape[-1]
            causal = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
            logits = jnp.where(causal, logits, jnp.finfo(logits.dtype).min)
        if maybe_mask:
            m = maybe_mask[0]
            if m.dtype == jnp.bool_:
                logits = jnp.where(m, logits, jnp.finfo(logits.dtype).min)
            else:
                logits = logits + m
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(qh.dtype)
        if dkey is not None:
            keep = jax.random.bernoulli(dkey, 1.0 - dropout_p, probs.shape)
            probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
        return jnp.swapaxes(out, 1, 2)

    if attn_mask is not None:
        return apply("scaled_dot_product_attention", f, query, key, value,
                     ensure_tensor(attn_mask))
    return apply("scaled_dot_product_attention", f, query, key, value)


register_op("scaled_dot_product_attention", scaled_dot_product_attention)


def softmax_mask_fuse_upper_triangle(x):
    x = ensure_tensor(x)

    def f(a):
        l = a.shape[-1]
        mask = jnp.tril(jnp.ones((l, l), bool))
        masked = jnp.where(mask, a, jnp.finfo(a.dtype).min)
        return jax.nn.softmax(masked, axis=-1)

    return apply("softmax_mask_fuse_upper_triangle", f, x)


register_op("softmax_mask_fuse_upper_triangle", softmax_mask_fuse_upper_triangle)
