"""Flash attention: Pallas TPU kernel + paddle-parity API.

Parity surface: the reference's flash_attn kernels
(upstream paddle/phi/kernels/gpu/flash_attn_kernel.cu + vendored
third_party/flashattn; python surface paddle.nn.functional.flash_attention).

TPU-native design: a Pallas kernel tiles Q into MXU-sized blocks held in
VMEM, streams K/V blocks, and keeps the online-softmax running max/denominator
in fp32 scratch — the standard TPU flash pattern (cf. the public
jax.experimental.pallas.ops.tpu.flash_attention, which can be selected with
FLAGS_flash_impl=jax). Backward recomputes attention (flash-style remat) under
``jax.custom_vjp``.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only resolves on TPU-enabled jaxlib (always true here)
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from .. import flags as _flags
from ..core.tensor import Tensor, apply
from ._helpers import ensure_tensor, register_op

_flags.define_flag("flash_impl", "pallas", "pallas | jax (shipped kernel) | xla")
_flags.define_flag("flash_block_q", 512, "flash attention Q tile")
_flags.define_flag("flash_block_k", 512, "flash attention K/V tile")
# 512x512 tiles measured fastest on v5e across seq 1024-8192 (vs the 256
# default: +13% tokens/s at seq 1024, +36% at 4096 — fewer grid programs and
# better MXU occupancy per K/V stream step). Lengths the preferred tile
# doesn't divide (768, 1280, ...) fit a smaller divisor via _fit_block
# instead of losing the flash path.

_NEG_INF = -1e30


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                      sm_scale: float, kv_len: int, q_len: int):
    """One (batch*head, q-block) program: stream K/V blocks, online softmax.

    Refs: q (1, Bq, D), k/v (1, Lk, D) in VMEM; o (1, Bq, D).

    Causal masking is bottom-right aligned (row i attends keys
    ``k <= i + kv_len - q_len``), matching ``_xla_attention`` and the
    KV-cache decode convention — lq != lk must agree with the backward path.
    """
    q = q_ref[0].astype(jnp.float32) * sm_scale  # (Bq, D)
    bq = q.shape[0]
    qi = pl.program_id(1)  # q-block index
    q_offset = qi * bq
    causal_shift = kv_len - q_len  # bottom-right alignment offset

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, q.shape[1]), jnp.float32)

    num_kb = kv_len // block_k

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)  # (Bq, Bk)
        if causal:
            q_ids = q_offset + causal_shift + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_ids = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = alpha * acc + jnp.dot(p, v_blk,
                                        preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        # skip fully-masked K blocks beyond this Q block
        last_kb = jnp.clip(
            (q_offset + bq + causal_shift + block_k - 1) // block_k, 0, num_kb)
    else:
        last_kb = num_kb
    m, l, acc = jax.lax.fori_loop(0, last_kb, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd_kernel_lse(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                          block_k: int, causal: bool, sm_scale: float,
                          kv_len: int, q_len: int):
    """Forward that also emits the per-row logsumexp (the flash residual the
    dedicated backward kernels consume). Same math as _flash_fwd_kernel."""
    q = q_ref[0].astype(jnp.float32) * sm_scale
    bq = q.shape[0]
    qi = pl.program_id(1)
    q_offset = qi * bq
    causal_shift = kv_len - q_len

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, q.shape[1]), jnp.float32)
    num_kb = kv_len // block_k

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if causal:
            q_ids = q_offset + causal_shift + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_ids = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = alpha * acc + jnp.dot(p, v_blk,
                                        preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        last_kb = jnp.clip(
            (q_offset + bq + causal_shift + block_k - 1) // block_k, 0, num_kb)
    else:
        last_kb = num_kb
    m, l, acc = jax.lax.fori_loop(0, last_kb, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    # fully-masked rows get lse=+big so exp(s - lse) -> 0 in the backward
    lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), 1e30)
    lse_ref[0, 0] = lse[:, 0]


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_k: int, causal: bool,
                         sm_scale: float, kv_len: int, q_len: int):
    """dq for one (batch*head, q-block): stream K/V, recompute p from lse."""
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0].astype(jnp.float32)[:, None]
    delta = delta_ref[0, 0].astype(jnp.float32)[:, None]
    bq = q.shape[0]
    qi = pl.program_id(1)
    q_offset = qi * bq
    causal_shift = kv_len - q_len
    num_kb = kv_len // block_k

    def body(kb, acc):
        k_blk = k_ref[0, pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_ids = q_offset + causal_shift + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_ids = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        return acc + jnp.dot(ds, k_blk, preferred_element_type=jnp.float32)

    if causal:
        last_kb = jnp.clip(
            (q_offset + bq + causal_shift + block_k - 1) // block_k, 0, num_kb)
    else:
        last_kb = num_kb
    acc = jax.lax.fori_loop(0, last_kb, body,
                            jnp.zeros((bq, q.shape[1]), jnp.float32))
    dq_ref[0] = acc.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q: int, causal: bool,
                          sm_scale: float, kv_len: int, q_len: int):
    """dk/dv for one (batch*head, k-block): stream Q/dO blocks."""
    k_blk = k_ref[0].astype(jnp.float32)  # (Bk, D)
    v_blk = v_ref[0].astype(jnp.float32)
    bk = k_blk.shape[0]
    ki = pl.program_id(1)
    k_offset = ki * bk
    causal_shift = kv_len - q_len
    num_qb = q_len // block_q

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.dslice(qb * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.dslice(qb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.dslice(qb * block_q, block_q)].astype(
            jnp.float32)[:, None]
        delta = delta_ref[0, 0, pl.dslice(qb * block_q, block_q)].astype(
            jnp.float32)[:, None]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_ids = qb * block_q + causal_shift + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            k_ids = k_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 1)
            s = jnp.where(q_ids >= k_ids, s, _NEG_INF)
        p = jnp.exp(s - lse)  # (Bq, Bk)
        dv = dv + jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        return dk, dv

    if causal:
        # first q block whose rows can attend this k block
        first_qb = jnp.clip((k_offset - causal_shift) // block_q, 0, num_qb)
    else:
        first_qb = 0
    d = k_blk.shape[1]
    dk, dv = jax.lax.fori_loop(
        first_qb, num_qb, body,
        (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _fit_block(length: int, want: int, floor: int = 128):
    """Largest lane-aligned tile <= ``want`` dividing ``length``.

    Sequences shorter than the preferred tile use one full-length block
    (the pre-tuning ``min(bq, lq)`` behavior); longer ones scan 128-multiple
    divisors (768 -> 384, 1280 -> 256). Unaligned lengths (1000, 1001)
    return None and stay on the XLA fallback — Mosaic needs lane/sublane
    aligned trailing block dims."""
    length, want = int(length), int(want)
    if length <= want:
        # full-length single tile: must be LANE-aligned (128) — the
        # backward kernels slice the (B, H, L) lse/delta refs along their
        # minor dimension in block_q steps, and Mosaic on real TPUs
        # rejects sub-128 strides there ("cannot statically prove that
        # index in dimension 2 is a multiple of 128"; found by the
        # bench --smoke run of train_llama_hybrid at seq 64). Short
        # sequences lose nothing on the XLA fallback.
        return length if length % 128 == 0 else None
    b0 = min(want, length)
    for b in range(b0 - b0 % floor, floor - 1, -floor):
        if length % b == 0:
            return b
    return None


def _pallas_tileable(lq, lk, d, bq, bk):
    return (_fit_block(lq, bq) is not None
            and _fit_block(lk, bk) is not None and d % 8 == 0)


def _pallas_flash(q, k, v, causal: bool, sm_scale: float, block_q: int,
                  block_k: int, interpret: bool, with_lse: bool = False):
    """q/k/v: (B, H, L, D) -> (B, H, L, D) [, lse (B, H, L) fp32]."""
    b, h, lq, d = q.shape
    lk = k.shape[2]
    block_q = min(block_q, lq)
    block_k = min(block_k, lk)
    assert lq % block_q == 0 and lk % block_k == 0, (lq, lk, block_q, block_k)
    qf = q.reshape(b * h, lq, d)
    kf = k.reshape(b * h, lk, d)
    vf = v.reshape(b * h, lk, d)

    grid = (b * h, lq // block_q)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        pl.BlockSpec((1, lk, d), lambda bh, qi: (bh, 0, 0)),
        pl.BlockSpec((1, lk, d), lambda bh, qi: (bh, 0, 0)),
    ]
    if not with_lse:
        kernel = functools.partial(_flash_fwd_kernel, block_k=block_k,
                                   causal=causal, sm_scale=sm_scale,
                                   kv_len=lk, q_len=lq)
        out = pl.pallas_call(
            kernel, grid=grid, in_specs=in_specs,
            out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            out_shape=jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
            interpret=interpret,
        )(qf, kf, vf)
        return out.reshape(b, h, lq, d)
    kernel = functools.partial(_flash_fwd_kernel_lse, block_k=block_k,
                               causal=causal, sm_scale=sm_scale, kv_len=lk,
                               q_len=lq)
    out, lse = pl.pallas_call(
        kernel, grid=grid, in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            # (BH, 1, Lq) keeps the trailing dims (1, block_q) TPU-tileable
            pl.BlockSpec((1, 1, block_q), lambda bh, qi: (bh, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 1, lq), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, lq, d), lse.reshape(b, h, lq)


def _pallas_flash_bwd(q, k, v, out, lse, g, causal: bool, sm_scale: float,
                      block_q: int, block_k: int, interpret: bool):
    """Dedicated flash backward: dq then fused dk/dv, both streaming."""
    b, h, lq, d = q.shape
    lk = k.shape[2]
    block_q = min(block_q, lq)
    block_k = min(block_k, lk)
    qf = q.reshape(b * h, lq, d)
    kf = k.reshape(b * h, lk, d)
    vf = v.reshape(b * h, lk, d)
    dof = g.reshape(b * h, lq, d)
    lsef = lse.reshape(b * h, 1, lq)
    # delta = rowsum(dO * O): tiny elementwise+reduce, XLA fuses it
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).reshape(b * h, 1, lq)

    dq_kernel = functools.partial(_flash_bwd_dq_kernel, block_k=block_k,
                                  causal=causal, sm_scale=sm_scale,
                                  kv_len=lk, q_len=lq)
    dq = pl.pallas_call(
        dq_kernel, grid=(b * h, lq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, lk, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, lk, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bh, qi: (bh, 0, qi)),
            pl.BlockSpec((1, 1, block_q), lambda bh, qi: (bh, 0, qi)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, delta)

    dkv_kernel = functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                                   causal=causal, sm_scale=sm_scale,
                                   kv_len=lk, q_len=lq)
    dk, dv = pl.pallas_call(
        dkv_kernel, grid=(b * h, lk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, lq, d), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, lq, d), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, 1, lq), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, 1, lq), lambda bh, ki: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, lk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, lk, d), v.dtype),
        ],
        interpret=interpret,
    )(kf, vf, qf, dof, lsef, delta)
    return (dq.reshape(b, h, lq, d), dk.reshape(b, h, lk, d),
            dv.reshape(b, h, lk, d))


def _xla_attention(q, k, v, causal: bool, sm_scale: float):
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(mask, logits, _NEG_INF)
        p_raw = jax.nn.softmax(logits, axis=-1)
        # fully-masked rows (lq > lk bottom-right) emit 0, flash convention
        p_raw = jnp.where(mask.any(-1)[..., None], p_raw, 0.0)
    else:
        p_raw = jax.nn.softmax(logits, axis=-1)
    p = p_raw.astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_core(q, k, v, causal: bool, sm_scale: float):
    return _flash_dispatch(q, k, v, causal, sm_scale)


def _flash_dispatch(q, k, v, causal, sm_scale):
    impl = _flags.flag("flash_impl")
    on_tpu = jax.default_backend() not in ("cpu",)
    interpret = not on_tpu
    lq, lk, d = q.shape[2], k.shape[2], q.shape[3]
    bq = _fit_block(lq, int(_flags.flag("flash_block_q")))
    bk = _fit_block(lk, int(_flags.flag("flash_block_k")))
    if impl == "xla" or bq is None or bk is None or d % 8 != 0:
        return _xla_attention(q, k, v, causal, sm_scale)
    if impl == "jax" and on_tpu:
        from jax.experimental.pallas.ops.tpu import flash_attention as _fa
        return _fa.flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    return _pallas_flash(q, k, v, causal, sm_scale, bq, bk, interpret)


def _bwd_kernel_eligible(q, k):
    """Eligibility AND the fitted tiles, so callers use the same blocks the
    check was made with: (use_kernel, interpret, bq, bk)."""
    impl = _flags.flag("flash_impl")
    on_tpu = jax.default_backend() not in ("cpu",)
    lq, lk, d = q.shape[2], k.shape[2], q.shape[3]
    bq = _fit_block(lq, int(_flags.flag("flash_block_q")))
    bk = _fit_block(lk, int(_flags.flag("flash_block_k")))
    use = (impl == "pallas" and bq is not None and bk is not None
           and d % 8 == 0)
    return use, (not on_tpu), bq, bk


def _flash_fwd(q, k, v, causal, sm_scale):
    use_kernel, interpret, bq, bk = _bwd_kernel_eligible(q, k)
    if use_kernel:
        out, lse = _pallas_flash(q, k, v, causal, sm_scale, bq, bk,
                                 interpret, with_lse=True)
        return out, (q, k, v, out, lse)
    out = _flash_dispatch(q, k, v, causal, sm_scale)
    return out, (q, k, v, None, None)


def _chunked_attention(q, k, v, causal: bool, sm_scale: float, block: int):
    """Blockwise attention over Q chunks with per-chunk remat.

    Same math (and bottom-right causal alignment) as ``_xla_attention`` but
    peak memory is O(block × Lk) per (B, H): the lax.map body runs one Q block
    at a time and ``jax.checkpoint`` drops its logits for the backward,
    which recomputes them blockwise — this is what makes the backward of the
    flash path O(L) memory instead of materializing the (Lq, Lk) matrix.
    """
    b, h, lq, d = q.shape
    lk = k.shape[2]
    nb = lq // block
    qb = jnp.moveaxis(q.reshape(b, h, nb, block, d), 2, 0)  # (nb,B,H,blk,D)
    offsets = jnp.arange(nb, dtype=jnp.int32) * block
    shift = lk - lq

    def one(args):
        qi, off = args  # (B,H,blk,D), scalar
        logits = jnp.einsum("bhqd,bhkd->bhqk", qi, k).astype(
            jnp.float32) * sm_scale
        if causal:
            rows = off + shift + jax.lax.broadcasted_iota(
                jnp.int32, (block, lk), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block, lk), 1)
            keep = rows >= cols
            logits = jnp.where(keep, logits, _NEG_INF)
            p_raw = jax.nn.softmax(logits, axis=-1)
            p_raw = jnp.where(keep.any(-1)[..., None], p_raw, 0.0)
        else:
            p_raw = jax.nn.softmax(logits, axis=-1)
        p = p_raw.astype(q.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    out = jax.lax.map(jax.checkpoint(one), (qb, offsets))  # (nb,B,H,blk,D)
    return jnp.moveaxis(out, 0, 2).reshape(b, h, lq, d)


def _flash_bwd(causal, sm_scale, res, g):
    q, k, v, out, lse = res
    if lse is not None:
        # dedicated Pallas backward (dq streaming K/V; fused dk/dv streaming
        # Q/dO) — recompute-from-lse, never materializes (Lq, Lk)
        _, interpret, bq, bk = _bwd_kernel_eligible(q, k)
        return _pallas_flash_bwd(q, k, v, out, lse, g, causal, sm_scale,
                                 bq, bk, interpret)
    # fallback: AD through the blockwise-remat form so the (Lq, Lk) matrix is
    # never materialized (O(block x Lk) peak)
    block = _fit_block(q.shape[2], int(_flags.flag("flash_block_q")))
    if block is not None:
        fn = lambda a, b, c: _chunked_attention(a, b, c, causal, sm_scale,
                                                block)
    else:
        fn = lambda a, b, c: _xla_attention(a, b, c, causal, sm_scale)
    _, vjp = jax.vjp(fn, q, k, v)
    return vjp(g)


_flash_core.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(query, key, value, dropout: float = 0.0, causal: bool = False,
                    return_softmax: bool = False, fixed_seed_offset=None,
                    rng_name: str = "", training: bool = True, name=None):
    """paddle.nn.functional.flash_attention parity. Inputs (B, L, H, D)."""
    query, key, value = ensure_tensor(query), ensure_tensor(key), ensure_tensor(value)
    if dropout > 0.0 and training:
        # attention-prob dropout breaks the flash formulation; use the fused
        # XLA path (parity with reference behavior under dropout)
        from .nn_ops import scaled_dot_product_attention
        out = scaled_dot_product_attention(query, key, value, None, dropout,
                                           causal, training)
        return (out, None) if return_softmax else out

    d = query._data.shape[-1]
    sm_scale = 1.0 / math.sqrt(d)

    def f(q, k, v):
        qh = jnp.swapaxes(q, 1, 2)
        kh = jnp.swapaxes(k, 1, 2)
        vh = jnp.swapaxes(v, 1, 2)
        if kh.shape[1] != qh.shape[1]:  # GQA
            rep = qh.shape[1] // kh.shape[1]
            kh = jnp.repeat(kh, rep, axis=1)
            vh = jnp.repeat(vh, rep, axis=1)
        out = _flash_core(qh, kh, vh, causal, sm_scale)
        return jnp.swapaxes(out, 1, 2)

    out = apply("flash_attention", f, query, key, value)
    return (out, None) if return_softmax else out


def flash_attn_unpadded(q, k, v, cu_seqlens_q, cu_seqlens_k, max_seqlen_q,
                        max_seqlen_k, scale=None, dropout=0.0, causal=False,
                        return_softmax=False, **kw):
    """Varlen parity shim: reshapes the packed layout to padded batches is the
    caller's job on TPU (static shapes); provided for API compatibility."""
    raise NotImplementedError(
        "varlen flash attention: pad to fixed lengths on TPU (static shapes) "
        "and call flash_attention with a mask")


register_op("flash_attention", flash_attention)
