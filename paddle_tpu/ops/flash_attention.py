"""Flash attention: Pallas TPU kernel + paddle-parity API.

Parity surface: the reference's flash_attn kernels
(upstream paddle/phi/kernels/gpu/flash_attn_kernel.cu + vendored
third_party/flashattn; python surface paddle.nn.functional.flash_attention).

TPU-native design: a Pallas kernel tiles Q into MXU-sized blocks held in
VMEM, streams K/V blocks, and keeps the online-softmax running max/denominator
in fp32 scratch — the standard TPU flash pattern (cf. the public
jax.experimental.pallas.ops.tpu.flash_attention, which can be selected with
FLAGS_flash_impl=jax). Backward recomputes attention (flash-style remat) under
``jax.custom_vjp``.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pltpu only resolves on TPU-enabled jaxlib (always true here)
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from .. import flags as _flags
from ..core.tensor import Tensor, apply
from ._helpers import ensure_tensor, register_op

_flags.define_flag("flash_impl", "pallas", "pallas | jax (shipped kernel) | xla")
_flags.define_flag("flash_block_q", 512, "flash attention Q tile")
_flags.define_flag("flash_block_k", 512, "flash attention K/V tile")
# 512x512 tiles measured fastest on v5e across seq 1024-8192 (vs the 256
# default: +13% tokens/s at seq 1024, +36% at 4096 — fewer grid programs and
# better MXU occupancy per K/V stream step). Lengths the preferred tile
# doesn't divide (768, 1280, ...) fit a smaller divisor via _fit_block
# instead of losing the flash path.

_NEG_INF = -1e30


def _keep_tile(seed, bh, q0, k0, bq, bk, keep_prob):
    """Deterministic per-ELEMENT dropout keep mask for a (bq, bk) tile at
    absolute coordinates (q0, k0), identical wherever it is regenerated.

    Stateless "lowbias32" hash of (seed, bh, absolute row, col) — NOT the
    on-core PRNG. Why: keyed on absolute position, the mask is identical
    under ANY tiling by construction (the fwd/dq/dkv kernels walk the
    (Lq, Lk) plane in different tile geometries), it runs under the CPU
    Pallas interpreter (pltpu.prng_* has no CPU lowering) so gradient
    parity is pinned in CI, and an on-chip fp32 finite-difference-vs-AD
    check confirms fwd/bwd mask consistency (~3% FD noise, v5e
    2026-07-31). prng_random_bits would need per-tile re-seeding plus a
    layout-stability assumption across differently-compiled kernels that
    buys nothing here: the hash's cost is in the kernels' VPU noise floor
    (masked seq-8192 fwd with and without dropout measured within relay
    variance of each other; the early '5x slower' reading was ~100
    ms/dispatch relay noise, not kernel time)."""
    i = (q0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)) \
        .astype(jnp.uint32)
    j = (k0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)) \
        .astype(jnp.uint32)
    h = (i * jnp.uint32(0x9E3779B1)) ^ (j * jnp.uint32(0x85EBCA77))
    h = h ^ (seed.astype(jnp.uint32) * jnp.uint32(0xC2B2AE3D))
    h = h ^ (jnp.uint32(bh) * jnp.uint32(0x27D4EB2F))
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x7FEB352D)
    h = h ^ (h >> 15)
    h = h * jnp.uint32(0x846CA68B)
    h = h ^ (h >> 16)
    # top 24 bits -> uniform [0, 1); via int32 (fits: < 2^24) because
    # Mosaic has no uint32->float cast
    u = (h >> 8).astype(jnp.int32).astype(jnp.float32) * (1.0 / 16777216.0)
    return u < keep_prob


def _flash_fwd_kernel(q_ref, k_ref, v_ref, *rest, block_k: int, causal: bool,
                      sm_scale: float, kv_len: int, q_len: int,
                      with_segs: bool = False, dropout_p: float = 0.0):
    """One (batch*head, q-block) program: stream K/V blocks, online softmax.

    Refs: q (1, Bq, D), k/v (1, Lk, D) in VMEM; o (1, Bq, D). With
    ``with_segs``, two extra int32 refs qseg (1, 1, Bq) / kseg (1, 1, Lk)
    carry segment ids: row i may attend key j only when their ids match —
    the TPU-native form of padding masks (pad id never matches) and packed
    sequences (per-sequence ids). Fully-masked rows emit 0 (flash
    convention; the XLA softmax would emit uniform rows there).

    With ``dropout_p > 0`` a trailing SMEM (1,) int32 seed ref follows the
    seg refs: attention-prob dropout runs IN the streaming kernel — the
    keep mask comes from `_keep_tile`'s absolute-coordinate hash, so the
    backward kernels regenerate it exactly; the softmax normalizer uses
    the UNdropped probabilities (dropout applies to normalized probs).

    Causal masking is bottom-right aligned (row i attends keys
    ``k <= i + kv_len - q_len``), matching ``_xla_attention`` and the
    KV-cache decode convention — lq != lk must agree with the backward path.
    """
    rest = list(rest)
    qs = None
    if with_segs:
        qseg_ref, kseg_ref = rest.pop(0), rest.pop(0)
        qs = qseg_ref[0, 0].astype(jnp.int32)  # (Bq,)
    seed_ref = rest.pop(0) if dropout_p > 0.0 else None
    (o_ref,) = rest
    q = q_ref[0].astype(jnp.float32) * sm_scale  # (Bq, D)
    bq = q.shape[0]
    qi = pl.program_id(1)  # q-block index
    bh = pl.program_id(0)
    q_offset = qi * bq
    causal_shift = kv_len - q_len  # bottom-right alignment offset

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, q.shape[1]), jnp.float32)

    num_kb = kv_len // block_k

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)  # (Bq, Bk)
        if causal:
            q_ids = q_offset + causal_shift + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_ids = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, _NEG_INF)
        if with_segs:
            ks = kseg_ref[0, 0, pl.dslice(kb * block_k, block_k)].astype(
                jnp.int32)
            s = jnp.where(qs[:, None] == ks[None, :], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        # normalizer l uses the UNdropped p: out_i = sum_j D_ij p~_ij v_j
        # with p~ the full softmax and D the scaled keep mask
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_p > 0.0:
            keep = _keep_tile(seed_ref[0], bh, q_offset, kb * block_k,
                              bq, block_k, 1.0 - dropout_p)
            p = jnp.where(keep, p * (1.0 / (1.0 - dropout_p)), 0.0)
        acc_new = alpha * acc + jnp.dot(p, v_blk,
                                        preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        # skip fully-masked K blocks beyond this Q block
        last_kb = jnp.clip(
            (q_offset + bq + causal_shift + block_k - 1) // block_k, 0, num_kb)
    else:
        last_kb = num_kb
    m, l, acc = jax.lax.fori_loop(0, last_kb, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd_kernel_lse(q_ref, k_ref, v_ref, *rest,
                          block_k: int, causal: bool, sm_scale: float,
                          kv_len: int, q_len: int, with_segs: bool = False,
                          dropout_p: float = 0.0):
    """Forward that also emits the per-row logsumexp (the flash residual the
    dedicated backward kernels consume). Same math as _flash_fwd_kernel;
    the lse is the FULL softmax normalizer (dropout never touches it)."""
    rest = list(rest)
    qs = None
    if with_segs:
        qseg_ref, kseg_ref = rest.pop(0), rest.pop(0)
        qs = qseg_ref[0, 0].astype(jnp.int32)
    seed_ref = rest.pop(0) if dropout_p > 0.0 else None
    o_ref, lse_ref = rest
    q = q_ref[0].astype(jnp.float32) * sm_scale
    bq = q.shape[0]
    qi = pl.program_id(1)
    bh = pl.program_id(0)
    q_offset = qi * bq
    causal_shift = kv_len - q_len

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, q.shape[1]), jnp.float32)
    num_kb = kv_len // block_k

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if causal:
            q_ids = q_offset + causal_shift + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_ids = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, _NEG_INF)
        if with_segs:
            ks = kseg_ref[0, 0, pl.dslice(kb * block_k, block_k)].astype(
                jnp.int32)
            s = jnp.where(qs[:, None] == ks[None, :], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_p > 0.0:
            keep = _keep_tile(seed_ref[0], bh, q_offset, kb * block_k,
                              bq, block_k, 1.0 - dropout_p)
            p = jnp.where(keep, p * (1.0 / (1.0 - dropout_p)), 0.0)
        acc_new = alpha * acc + jnp.dot(p, v_blk,
                                        preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        last_kb = jnp.clip(
            (q_offset + bq + causal_shift + block_k - 1) // block_k, 0, num_kb)
    else:
        last_kb = num_kb
    m, l, acc = jax.lax.fori_loop(0, last_kb, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    # fully-masked rows get lse=+big so exp(s - lse) -> 0 in the backward
    lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), 1e30)
    lse_ref[0, 0] = lse[:, 0]


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         *rest, block_k: int, causal: bool,
                         sm_scale: float, kv_len: int, q_len: int,
                         with_segs: bool = False, dropout_p: float = 0.0):
    """dq for one (batch*head, q-block): stream K/V, recompute p from lse.

    Dropout backward (mask regenerated via `_keep_tile`, bit-identical to
    the forward's): dS_ij = P_ij (D_ij (dO V^T)_ij - delta_i) where
    D = keep/(1-p) and delta = rowsum(dO * O) over the DROPPED output."""
    rest = list(rest)
    qs = None
    if with_segs:
        qseg_ref, kseg_ref = rest.pop(0), rest.pop(0)
        qs = qseg_ref[0, 0].astype(jnp.int32)
    seed_ref = rest.pop(0) if dropout_p > 0.0 else None
    (dq_ref,) = rest
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0].astype(jnp.float32)[:, None]
    delta = delta_ref[0, 0].astype(jnp.float32)[:, None]
    bq = q.shape[0]
    qi = pl.program_id(1)
    bh = pl.program_id(0)
    q_offset = qi * bq
    causal_shift = kv_len - q_len
    num_kb = kv_len // block_k

    def body(kb, acc):
        k_blk = k_ref[0, pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_ids = q_offset + causal_shift + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_ids = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, _NEG_INF)
        if with_segs:
            ks = kseg_ref[0, 0, pl.dslice(kb * block_k, block_k)].astype(
                jnp.int32)
            s = jnp.where(qs[:, None] == ks[None, :], s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            keep = _keep_tile(seed_ref[0], bh, q_offset, kb * block_k,
                              bq, block_k, 1.0 - dropout_p)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_p)), 0.0)
        ds = p * (dp - delta) * sm_scale
        return acc + jnp.dot(ds, k_blk, preferred_element_type=jnp.float32)

    if causal:
        last_kb = jnp.clip(
            (q_offset + bq + causal_shift + block_k - 1) // block_k, 0, num_kb)
    else:
        last_kb = num_kb
    acc = jax.lax.fori_loop(0, last_kb, body,
                            jnp.zeros((bq, q.shape[1]), jnp.float32))
    dq_ref[0] = acc.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                          *rest, block_q: int, causal: bool,
                          sm_scale: float, kv_len: int, q_len: int,
                          with_segs: bool = False, dropout_p: float = 0.0):
    """dk/dv for one (batch*head, k-block): stream Q/dO blocks.

    Dropout: dV consumes the DROPPED probs (dV = P'^T dO); dK's dS uses
    the dropped dP (see _flash_bwd_dq_kernel). `_keep_tile` is keyed on
    absolute (row, col), so this kernel's (block_q, Bk) tiling regenerates
    the same mask the forward drew under its (Bq, block_k) tiling."""
    rest = list(rest)
    ks = None
    if with_segs:
        qseg_ref, kseg_ref = rest.pop(0), rest.pop(0)
        ks = kseg_ref[0, 0].astype(jnp.int32)  # (Bk,)
    seed_ref = rest.pop(0) if dropout_p > 0.0 else None
    dk_ref, dv_ref = rest
    k_blk = k_ref[0].astype(jnp.float32)  # (Bk, D)
    v_blk = v_ref[0].astype(jnp.float32)
    bk = k_blk.shape[0]
    ki = pl.program_id(1)
    bh = pl.program_id(0)
    k_offset = ki * bk
    causal_shift = kv_len - q_len
    num_qb = q_len // block_q

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.dslice(qb * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.dslice(qb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.dslice(qb * block_q, block_q)].astype(
            jnp.float32)[:, None]
        delta = delta_ref[0, 0, pl.dslice(qb * block_q, block_q)].astype(
            jnp.float32)[:, None]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_ids = qb * block_q + causal_shift + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            k_ids = k_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 1)
            s = jnp.where(q_ids >= k_ids, s, _NEG_INF)
        if with_segs:
            qs = qseg_ref[0, 0, pl.dslice(qb * block_q, block_q)].astype(
                jnp.int32)
            s = jnp.where(qs[:, None] == ks[None, :], s, _NEG_INF)
        p = jnp.exp(s - lse)  # (Bq, Bk)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            keep = _keep_tile(seed_ref[0], bh, qb * block_q, k_offset,
                              block_q, bk, 1.0 - dropout_p)
            inv = 1.0 / (1.0 - dropout_p)
            p_drop = jnp.where(keep, p * inv, 0.0)
            dp = jnp.where(keep, dp * inv, 0.0)
        else:
            p_drop = p
        dv = dv + jnp.dot(p_drop.T, do, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        return dk, dv

    if causal:
        # first q block whose rows can attend this k block
        first_qb = jnp.clip((k_offset - causal_shift) // block_q, 0, num_qb)
    else:
        first_qb = 0
    d = k_blk.shape[1]
    dk, dv = jax.lax.fori_loop(
        first_qb, num_qb, body,
        (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _fit_block(length: int, want: int, floor: int = 128):
    """Largest lane-aligned tile <= ``want`` dividing ``length``.

    Sequences shorter than the preferred tile use one full-length block
    (the pre-tuning ``min(bq, lq)`` behavior); longer ones scan 128-multiple
    divisors (768 -> 384, 1280 -> 256). Unaligned lengths (1000, 1001)
    return None and stay on the XLA fallback — Mosaic needs lane/sublane
    aligned trailing block dims."""
    length, want = int(length), int(want)
    if length <= want:
        # full-length single tile: must be LANE-aligned (128) — the
        # backward kernels slice the (B, H, L) lse/delta refs along their
        # minor dimension in block_q steps, and Mosaic on real TPUs
        # rejects sub-128 strides there ("cannot statically prove that
        # index in dimension 2 is a multiple of 128"; found by the
        # bench --smoke run of train_llama_hybrid at seq 64). Short
        # sequences lose nothing on the XLA fallback.
        return length if length % 128 == 0 else None
    b0 = min(want, length)
    for b in range(b0 - b0 % floor, floor - 1, -floor):
        if length % b == 0:
            return b
    return None


def _pallas_tileable(lq, lk, d, bq, bk):
    return (_fit_block(lq, bq) is not None
            and _fit_block(lk, bk) is not None and d % 8 == 0)


def _flatten_segs(segs, b, h, length):
    """(B, L) int32 segment ids -> (B*H, 1, L) rank-3 refs for the kernels."""
    s = jnp.broadcast_to(segs.astype(jnp.int32)[:, None, None, :],
                         (b, h, 1, length))
    return s.reshape(b * h, 1, length)


def _pallas_flash(q, k, v, causal: bool, sm_scale: float, block_q: int,
                  block_k: int, interpret: bool, with_lse: bool = False,
                  q_segs=None, kv_segs=None, dropout_p: float = 0.0,
                  seed=None):
    """q/k/v: (B, H, L, D) -> (B, H, L, D) [, lse (B, H, L) fp32].

    ``q_segs``/``kv_segs``: optional (B, L) int32 segment ids (see the
    kernel docstring) — both or neither. ``dropout_p``/``seed`` ((1,)
    int32): in-kernel attention-prob dropout."""
    b, h, lq, d = q.shape
    lk = k.shape[2]
    block_q = min(block_q, lq)
    block_k = min(block_k, lk)
    assert lq % block_q == 0 and lk % block_k == 0, (lq, lk, block_q, block_k)
    qf = q.reshape(b * h, lq, d)
    kf = k.reshape(b * h, lk, d)
    vf = v.reshape(b * h, lk, d)
    with_segs = q_segs is not None

    grid = (b * h, lq // block_q)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        pl.BlockSpec((1, lk, d), lambda bh, qi: (bh, 0, 0)),
        pl.BlockSpec((1, lk, d), lambda bh, qi: (bh, 0, 0)),
    ]
    inputs = [qf, kf, vf]
    if with_segs:
        in_specs += [
            pl.BlockSpec((1, 1, block_q), lambda bh, qi: (bh, 0, qi)),
            pl.BlockSpec((1, 1, lk), lambda bh, qi: (bh, 0, 0)),
        ]
        inputs += [_flatten_segs(q_segs, b, h, lq),
                   _flatten_segs(kv_segs, b, h, lk)]
    if dropout_p > 0.0:
        in_specs.append(pl.BlockSpec((1,), lambda bh, qi: (0,),
                                     memory_space=pltpu.SMEM))
        inputs.append(jnp.asarray(seed, jnp.int32).reshape(1))
    if not with_lse:
        kernel = functools.partial(_flash_fwd_kernel, block_k=block_k,
                                   causal=causal, sm_scale=sm_scale,
                                   kv_len=lk, q_len=lq, with_segs=with_segs,
                                   dropout_p=dropout_p)
        out = pl.pallas_call(
            kernel, grid=grid, in_specs=in_specs,
            out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            out_shape=jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
            interpret=interpret,
        )(*inputs)
        return out.reshape(b, h, lq, d)
    kernel = functools.partial(_flash_fwd_kernel_lse, block_k=block_k,
                               causal=causal, sm_scale=sm_scale, kv_len=lk,
                               q_len=lq, with_segs=with_segs,
                               dropout_p=dropout_p)
    out, lse = pl.pallas_call(
        kernel, grid=grid, in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            # (BH, 1, Lq) keeps the trailing dims (1, block_q) TPU-tileable
            pl.BlockSpec((1, 1, block_q), lambda bh, qi: (bh, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 1, lq), jnp.float32),
        ],
        interpret=interpret,
    )(*inputs)
    return out.reshape(b, h, lq, d), lse.reshape(b, h, lq)


def _pallas_flash_bwd(q, k, v, out, lse, g, causal: bool, sm_scale: float,
                      block_q: int, block_k: int, interpret: bool,
                      q_segs=None, kv_segs=None, dropout_p: float = 0.0,
                      seed=None):
    """Dedicated flash backward: dq then fused dk/dv, both streaming."""
    b, h, lq, d = q.shape
    lk = k.shape[2]
    block_q = min(block_q, lq)
    block_k = min(block_k, lk)
    qf = q.reshape(b * h, lq, d)
    kf = k.reshape(b * h, lk, d)
    vf = v.reshape(b * h, lk, d)
    dof = g.reshape(b * h, lq, d)
    lsef = lse.reshape(b * h, 1, lq)
    # delta = rowsum(dO * O): tiny elementwise+reduce, XLA fuses it
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).reshape(b * h, 1, lq)
    with_segs = q_segs is not None
    qsf = _flatten_segs(q_segs, b, h, lq) if with_segs else None
    ksf = _flatten_segs(kv_segs, b, h, lk) if with_segs else None
    seed_spec = pl.BlockSpec((1,), lambda bh, i: (0,),
                             memory_space=pltpu.SMEM)
    seed_in = jnp.asarray(seed, jnp.int32).reshape(1) \
        if dropout_p > 0.0 else None

    dq_kernel = functools.partial(_flash_bwd_dq_kernel, block_k=block_k,
                                  causal=causal, sm_scale=sm_scale,
                                  kv_len=lk, q_len=lq, with_segs=with_segs,
                                  dropout_p=dropout_p)
    dq_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        pl.BlockSpec((1, lk, d), lambda bh, qi: (bh, 0, 0)),
        pl.BlockSpec((1, lk, d), lambda bh, qi: (bh, 0, 0)),
        pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        pl.BlockSpec((1, 1, block_q), lambda bh, qi: (bh, 0, qi)),
        pl.BlockSpec((1, 1, block_q), lambda bh, qi: (bh, 0, qi)),
    ]
    dq_inputs = [qf, kf, vf, dof, lsef, delta]
    if with_segs:
        dq_specs += [
            pl.BlockSpec((1, 1, block_q), lambda bh, qi: (bh, 0, qi)),
            pl.BlockSpec((1, 1, lk), lambda bh, qi: (bh, 0, 0)),
        ]
        dq_inputs += [qsf, ksf]
    if dropout_p > 0.0:
        dq_specs.append(seed_spec)
        dq_inputs.append(seed_in)
    dq = pl.pallas_call(
        dq_kernel, grid=(b * h, lq // block_q),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
        interpret=interpret,
    )(*dq_inputs)

    dkv_kernel = functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                                   causal=causal, sm_scale=sm_scale,
                                   kv_len=lk, q_len=lq, with_segs=with_segs,
                                   dropout_p=dropout_p)
    dkv_specs = [
        pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
        pl.BlockSpec((1, lq, d), lambda bh, ki: (bh, 0, 0)),
        pl.BlockSpec((1, lq, d), lambda bh, ki: (bh, 0, 0)),
        pl.BlockSpec((1, 1, lq), lambda bh, ki: (bh, 0, 0)),
        pl.BlockSpec((1, 1, lq), lambda bh, ki: (bh, 0, 0)),
    ]
    dkv_inputs = [kf, vf, qf, dof, lsef, delta]
    if with_segs:
        dkv_specs += [
            pl.BlockSpec((1, 1, lq), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, 1, block_k), lambda bh, ki: (bh, 0, ki)),
        ]
        dkv_inputs += [qsf, ksf]
    if dropout_p > 0.0:
        dkv_specs.append(seed_spec)
        dkv_inputs.append(seed_in)
    dk, dv = pl.pallas_call(
        dkv_kernel, grid=(b * h, lk // block_k),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, lk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, lk, d), v.dtype),
        ],
        interpret=interpret,
    )(*dkv_inputs)
    return (dq.reshape(b, h, lq, d), dk.reshape(b, h, lk, d),
            dv.reshape(b, h, lk, d))


def _dropout_seed(fixed_seed_offset):
    """(1,) int32 dropout seed Tensor: the upstream fixed_seed_offset when
    given (deterministic-dropout contract), else a fold of the global
    generator's next key (advances RNG state; trace-safe)."""
    if fixed_seed_offset is not None:
        return ensure_tensor(fixed_seed_offset).astype("int32")
    from ..core.random import default_generator
    kd = jnp.asarray(default_generator.split_key(), jnp.uint32).reshape(-1)
    return Tensor((kd[0] ^ kd[-1]).astype(jnp.int32).reshape(1))


def _xla_probs(q, k, causal, sm_scale, q_segs, kv_segs):
    """Shared probability computation for the XLA fallbacks: logits,
    bottom-right-aligned causal tril, segment mask, softmax with the
    flash fully-masked-rows-emit-0 convention."""
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    ql, kl = logits.shape[-2], logits.shape[-1]
    mask = None
    if causal:
        mask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
    if q_segs is not None:
        seg = (q_segs[:, None, :, None] == kv_segs[:, None, None, :])
        mask = seg if mask is None else jnp.logical_and(mask, seg)
    if mask is not None:
        logits = jnp.where(mask, logits, _NEG_INF)
        p = jax.nn.softmax(logits, axis=-1)
        return jnp.where(mask.any(-1)[..., None], p, 0.0)
    return jax.nn.softmax(logits, axis=-1)


def _xla_attention(q, k, v, causal: bool, sm_scale: float,
                   q_segs=None, kv_segs=None):
    p = _xla_probs(q, k, causal, sm_scale, q_segs, kv_segs).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_core(q, k, v, causal: bool, sm_scale: float):
    return _flash_dispatch(q, k, v, causal, sm_scale)


def _flash_dispatch(q, k, v, causal, sm_scale):
    impl = _flags.flag("flash_impl")
    on_tpu = jax.default_backend() not in ("cpu",)
    interpret = not on_tpu
    lq, lk, d = q.shape[2], k.shape[2], q.shape[3]
    bq = _fit_block(lq, int(_flags.flag("flash_block_q")))
    bk = _fit_block(lk, int(_flags.flag("flash_block_k")))
    if impl == "xla" or bq is None or bk is None or d % 8 != 0:
        return _xla_attention(q, k, v, causal, sm_scale)
    if impl == "jax" and on_tpu:
        from jax.experimental.pallas.ops.tpu import flash_attention as _fa
        return _fa.flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    return _pallas_flash(q, k, v, causal, sm_scale, bq, bk, interpret)


def _bwd_kernel_eligible(q, k):
    """Eligibility AND the fitted tiles, so callers use the same blocks the
    check was made with: (use_kernel, interpret, bq, bk)."""
    impl = _flags.flag("flash_impl")
    on_tpu = jax.default_backend() not in ("cpu",)
    lq, lk, d = q.shape[2], k.shape[2], q.shape[3]
    bq = _fit_block(lq, int(_flags.flag("flash_block_q")))
    bk = _fit_block(lk, int(_flags.flag("flash_block_k")))
    use = (impl == "pallas" and bq is not None and bk is not None
           and d % 8 == 0)
    return use, (not on_tpu), bq, bk


def _flash_fwd(q, k, v, causal, sm_scale):
    use_kernel, interpret, bq, bk = _bwd_kernel_eligible(q, k)
    if use_kernel:
        out, lse = _pallas_flash(q, k, v, causal, sm_scale, bq, bk,
                                 interpret, with_lse=True)
        return out, (q, k, v, out, lse)
    out = _flash_dispatch(q, k, v, causal, sm_scale)
    return out, (q, k, v, None, None)


def _chunked_attention(q, k, v, causal: bool, sm_scale: float, block: int,
                       q_segs=None, kv_segs=None):
    """Blockwise attention over Q chunks with per-chunk remat.

    Same math (and bottom-right causal alignment) as ``_xla_attention`` but
    peak memory is O(block × Lk) per (B, H): the lax.map body runs one Q block
    at a time and ``jax.checkpoint`` drops its logits for the backward,
    which recomputes them blockwise — this is what makes the backward of the
    flash path O(L) memory instead of materializing the (Lq, Lk) matrix.
    """
    b, h, lq, d = q.shape
    lk = k.shape[2]
    nb = lq // block
    qb = jnp.moveaxis(q.reshape(b, h, nb, block, d), 2, 0)  # (nb,B,H,blk,D)
    offsets = jnp.arange(nb, dtype=jnp.int32) * block
    shift = lk - lq

    seg_blocks = None
    if q_segs is not None:
        seg_blocks = jnp.moveaxis(
            q_segs.reshape(b, nb, block), 1, 0)  # (nb, B, blk)

    def one(args):
        qi, off, qs = args  # (B,H,blk,D), scalar, (B,blk) | scalar 0
        logits = jnp.einsum("bhqd,bhkd->bhqk", qi, k).astype(
            jnp.float32) * sm_scale
        keep = None
        if causal:
            rows = off + shift + jax.lax.broadcasted_iota(
                jnp.int32, (block, lk), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block, lk), 1)
            keep = jnp.broadcast_to(rows >= cols, (b, 1, block, lk))
        if seg_blocks is not None:
            seg = qs[:, None, :, None] == kv_segs[:, None, None, :]
            keep = seg if keep is None else jnp.logical_and(keep, seg)
        if keep is not None:
            logits = jnp.where(keep, logits, _NEG_INF)
            p_raw = jax.nn.softmax(logits, axis=-1)
            p_raw = jnp.where(keep.any(-1)[..., None], p_raw, 0.0)
        else:
            p_raw = jax.nn.softmax(logits, axis=-1)
        p = p_raw.astype(q.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    dummy = jnp.zeros((nb,), jnp.int32)
    out = jax.lax.map(jax.checkpoint(one),
                      (qb, offsets,
                       seg_blocks if seg_blocks is not None else dummy))
    return jnp.moveaxis(out, 0, 2).reshape(b, h, lq, d)


def _flash_bwd(causal, sm_scale, res, g):
    q, k, v, out, lse = res
    if lse is not None:
        # dedicated Pallas backward (dq streaming K/V; fused dk/dv streaming
        # Q/dO) — recompute-from-lse, never materializes (Lq, Lk)
        _, interpret, bq, bk = _bwd_kernel_eligible(q, k)
        return _pallas_flash_bwd(q, k, v, out, lse, g, causal, sm_scale,
                                 bq, bk, interpret)
    # fallback: AD through the blockwise-remat form so the (Lq, Lk) matrix is
    # never materialized (O(block x Lk) peak)
    block = _fit_block(q.shape[2], int(_flags.flag("flash_block_q")))
    if block is not None:
        fn = lambda a, b, c: _chunked_attention(a, b, c, causal, sm_scale,
                                                block)
    else:
        fn = lambda a, b, c: _xla_attention(a, b, c, causal, sm_scale)
    _, vjp = jax.vjp(fn, q, k, v)
    return vjp(g)


_flash_core.defvjp(_flash_fwd, _flash_bwd)


# --- segment-masked core (padding / packed sequences) -----------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _flash_core_seg(q, k, v, q_segs, kv_segs, causal: bool, sm_scale: float):
    """Segment-id flash attention: like _flash_core but row i attends key j
    only when q_segs[b, i] == kv_segs[b, j] (padding masks and packed
    sequences stay on the streaming kernel — the fallback the reference's
    varlen flash kernels serve on GPU, upstream
    paddle/phi/kernels/gpu/flash_attn_ kernels, SURVEY §5 long-context)."""
    use_kernel, interpret, bq, bk = _bwd_kernel_eligible(q, k)
    if use_kernel:
        return _pallas_flash(q, k, v, causal, sm_scale, bq, bk, interpret,
                             q_segs=q_segs, kv_segs=kv_segs)
    return _xla_attention(q, k, v, causal, sm_scale, q_segs, kv_segs)


def _flash_fwd_seg(q, k, v, q_segs, kv_segs, causal, sm_scale):
    use_kernel, interpret, bq, bk = _bwd_kernel_eligible(q, k)
    if use_kernel:
        out, lse = _pallas_flash(q, k, v, causal, sm_scale, bq, bk,
                                 interpret, with_lse=True,
                                 q_segs=q_segs, kv_segs=kv_segs)
        return out, (q, k, v, out, lse, q_segs, kv_segs)
    out = _xla_attention(q, k, v, causal, sm_scale, q_segs, kv_segs)
    return out, (q, k, v, None, None, q_segs, kv_segs)


def _flash_bwd_seg(causal, sm_scale, res, g):
    q, k, v, out, lse, q_segs, kv_segs = res
    zero_seg = (np.zeros(q_segs.shape, jax.dtypes.float0),
                np.zeros(kv_segs.shape, jax.dtypes.float0))
    if lse is not None:
        _, interpret, bq, bk = _bwd_kernel_eligible(q, k)
        dq, dk, dv = _pallas_flash_bwd(q, k, v, out, lse, g, causal,
                                       sm_scale, bq, bk, interpret,
                                       q_segs=q_segs, kv_segs=kv_segs)
        return (dq, dk, dv) + zero_seg
    block = _fit_block(q.shape[2], int(_flags.flag("flash_block_q")))
    if block is not None:
        fn = lambda a, b, c: _chunked_attention(a, b, c, causal, sm_scale,
                                                block, q_segs, kv_segs)
    else:
        fn = lambda a, b, c: _xla_attention(a, b, c, causal, sm_scale,
                                            q_segs, kv_segs)
    _, vjp = jax.vjp(fn, q, k, v)
    return tuple(vjp(g)) + zero_seg


_flash_core_seg.defvjp(_flash_fwd_seg, _flash_bwd_seg)


# --- dropout core (in-kernel attention-prob dropout, round 5) ---------------

def _xla_attention_dropout(q, k, v, causal, sm_scale, q_segs, kv_segs, seed,
                           dropout_p):
    """Parity fallback (CPU / untileable shapes): materialized attention
    with prob dropout. Deterministic in ``seed``, so the custom-vjp
    backward's re-run reproduces the forward's mask exactly."""
    p = _xla_probs(q, k, causal, sm_scale, q_segs, kv_segs)
    key_ = jax.random.PRNGKey(jnp.asarray(seed).reshape(()))
    keep = jax.random.bernoulli(key_, 1.0 - dropout_p, p.shape)
    p = jnp.where(keep, p * (1.0 / (1.0 - dropout_p)), 0.0).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def _flash_core_drop(q, k, v, q_segs, kv_segs, seed, causal, sm_scale,
                     dropout_p):
    """Attention with in-kernel prob dropout (upstream flash_attn takes
    dropout natively: paddle/phi/kernels/gpu/flash_attn_kernel.cu). The
    keep mask is `_keep_tile`'s absolute-coordinate hash of ``seed`` —
    the backward kernels regenerate it bit-exactly under their own
    tiling, so dropout_p > 0 stays on the streaming kernels instead of
    materializing (Lq, Lk). Segment ids are required (pass zeros for
    unmasked attention); seed is a (1,) int32 array."""
    use_kernel, interpret, bq, bk = _bwd_kernel_eligible(q, k)
    if use_kernel:
        return _pallas_flash(q, k, v, causal, sm_scale, bq, bk, interpret,
                             q_segs=q_segs, kv_segs=kv_segs,
                             dropout_p=dropout_p, seed=seed)
    return _xla_attention_dropout(q, k, v, causal, sm_scale, q_segs,
                                  kv_segs, seed, dropout_p)


def _flash_fwd_drop(q, k, v, q_segs, kv_segs, seed, causal, sm_scale,
                    dropout_p):
    use_kernel, interpret, bq, bk = _bwd_kernel_eligible(q, k)
    if use_kernel:
        out, lse = _pallas_flash(q, k, v, causal, sm_scale, bq, bk,
                                 interpret, with_lse=True, q_segs=q_segs,
                                 kv_segs=kv_segs, dropout_p=dropout_p,
                                 seed=seed)
        return out, (q, k, v, out, lse, q_segs, kv_segs, seed)
    out = _xla_attention_dropout(q, k, v, causal, sm_scale, q_segs, kv_segs,
                                 seed, dropout_p)
    return out, (q, k, v, None, None, q_segs, kv_segs, seed)


def _flash_bwd_drop(causal, sm_scale, dropout_p, res, g):
    q, k, v, out, lse, q_segs, kv_segs, seed = res
    zero_tail = (np.zeros(q_segs.shape, jax.dtypes.float0),
                 np.zeros(kv_segs.shape, jax.dtypes.float0),
                 np.zeros(seed.shape, jax.dtypes.float0))
    if lse is not None:
        _, interpret, bq, bk = _bwd_kernel_eligible(q, k)
        dq, dk, dv = _pallas_flash_bwd(q, k, v, out, lse, g, causal,
                                       sm_scale, bq, bk, interpret,
                                       q_segs=q_segs, kv_segs=kv_segs,
                                       dropout_p=dropout_p, seed=seed)
        return (dq, dk, dv) + zero_tail
    fn = lambda a, b, c: _xla_attention_dropout(
        a, b, c, causal, sm_scale, q_segs, kv_segs, seed, dropout_p)
    _, vjp = jax.vjp(fn, q, k, v)
    return tuple(vjp(g)) + zero_tail


_flash_core_drop.defvjp(_flash_fwd_drop, _flash_bwd_drop)


def flash_attention(query, key, value, dropout: float = 0.0, causal: bool = False,
                    return_softmax: bool = False, fixed_seed_offset=None,
                    rng_name: str = "", training: bool = True,
                    q_segment_ids=None, kv_segment_ids=None, name=None):
    """paddle.nn.functional.flash_attention parity. Inputs (B, L, H, D).

    TPU-native extension beyond the upstream signature (trailing kwargs, so
    upstream positional calls are unaffected): ``q_segment_ids`` /
    ``kv_segment_ids`` (B, L) int tensors keep PADDING-MASKED and
    PACKED-sequence attention on the streaming Pallas kernel — attention is
    allowed only where ids match (combined with ``causal`` if set). This is
    the role the reference's varlen flash kernels play on GPU
    (paddle/phi/kernels/gpu/flash_attn_*). Masked long-sequence attention
    previously fell back to materializing (Lq, Lk) logits in XLA, which
    OOMs one chip at seq 8192."""
    query, key, value = ensure_tensor(query), ensure_tensor(key), ensure_tensor(value)
    if (q_segment_ids is None) != (kv_segment_ids is None):
        raise ValueError("pass both q_segment_ids and kv_segment_ids, or "
                         "neither")
    if dropout > 0.0 and training:
        # round 5: attention-prob dropout stays IN the streaming kernel
        # (_flash_core_drop) — the keep mask is a stateless hash of
        # absolute coordinates, regenerated bit-exactly by the backward
        # kernels. fixed_seed_offset gives the upstream deterministic-
        # dropout contract; otherwise the seed advances the global
        # generator.
        d = query._data.shape[-1]
        sm_scale = 1.0 / math.sqrt(d)
        seed_t = _dropout_seed(fixed_seed_offset)

        def fdrop(q, k, v, seed, *segs):
            qh = jnp.swapaxes(q, 1, 2)
            kh = jnp.swapaxes(k, 1, 2)
            vh = jnp.swapaxes(v, 1, 2)
            if kh.shape[1] != qh.shape[1]:  # GQA
                rep = qh.shape[1] // kh.shape[1]
                kh = jnp.repeat(kh, rep, axis=1)
                vh = jnp.repeat(vh, rep, axis=1)
            if segs:
                qs, ks = segs[0].astype(jnp.int32), segs[1].astype(jnp.int32)
            else:  # zeros = "all one segment": no masking effect
                qs = jnp.zeros(qh.shape[:1] + qh.shape[2:3], jnp.int32)
                ks = jnp.zeros(kh.shape[:1] + kh.shape[2:3], jnp.int32)
            out = _flash_core_drop(qh, kh, vh, qs, ks,
                                   jnp.asarray(seed, jnp.int32).reshape(1),
                                   causal, sm_scale, float(dropout))
            return jnp.swapaxes(out, 1, 2)

        if q_segment_ids is not None:
            out = apply("flash_attention_dropout", fdrop, query, key, value,
                        seed_t, ensure_tensor(q_segment_ids),
                        ensure_tensor(kv_segment_ids))
        else:
            out = apply("flash_attention_dropout", fdrop, query, key, value,
                        seed_t)
        return (out, None) if return_softmax else out

    d = query._data.shape[-1]
    sm_scale = 1.0 / math.sqrt(d)

    def f(q, k, v, *segs):
        qh = jnp.swapaxes(q, 1, 2)
        kh = jnp.swapaxes(k, 1, 2)
        vh = jnp.swapaxes(v, 1, 2)
        if kh.shape[1] != qh.shape[1]:  # GQA
            rep = qh.shape[1] // kh.shape[1]
            kh = jnp.repeat(kh, rep, axis=1)
            vh = jnp.repeat(vh, rep, axis=1)
        if segs:
            out = _flash_core_seg(qh, kh, vh, segs[0].astype(jnp.int32),
                                  segs[1].astype(jnp.int32), causal, sm_scale)
        else:
            out = _flash_core(qh, kh, vh, causal, sm_scale)
        return jnp.swapaxes(out, 1, 2)

    if q_segment_ids is not None:
        out = apply("flash_attention", f, query, key, value,
                    ensure_tensor(q_segment_ids),
                    ensure_tensor(kv_segment_ids))
    else:
        out = apply("flash_attention", f, query, key, value)
    return (out, None) if return_softmax else out


def flash_attn_unpadded(q, k, v, cu_seqlens_q, cu_seqlens_k, max_seqlen_q,
                        max_seqlen_k, scale=None, dropout=0.0, causal=False,
                        return_softmax=False, fixed_seed_offset=None,
                        rng_name="", training=True, name=None):
    """Varlen (packed) flash attention — upstream
    paddle.nn.functional.flash_attn_unpadded over the GPU varlen kernels.

    TPU-native design: the packed (total, H, D) layout IS the natural static
    shape — run it as one batch row with per-sequence SEGMENT IDS derived
    from ``cu_seqlens`` on-device (no host read, trace-safe). ``causal``
    composes with the segment mask, which restricts global causality to
    within each packed sequence — exactly the varlen-causal contract.
    ``max_seqlen_*`` only size upstream's workspace; unused here (static
    shapes already known)."""
    q, k, v = ensure_tensor(q), ensure_tensor(k), ensure_tensor(v)
    cu_q = ensure_tensor(cu_seqlens_q)
    cu_k = ensure_tensor(cu_seqlens_k)
    total_q, nheads, d = q._data.shape
    total_k = k._data.shape[0]
    sm_scale = float(scale) if scale else 1.0 / math.sqrt(d)
    # hoisted OUTSIDE the traced fn so the seed rides the carried RNG state
    # instead of baking as a trace-time constant (same pattern as SDPA)
    dseed = None
    if dropout > 0.0 and training:
        dseed = _dropout_seed(fixed_seed_offset)

    def seg_ids(cu, total):
        # token i belongs to sequence searchsorted(cu[1:], i, 'right');
        # tokens past cu[-1] get an id beyond any q/k pair -> masked out
        ids = jnp.arange(total, dtype=jnp.int32)
        return jnp.searchsorted(cu[1:].astype(jnp.int32), ids,
                                side="right").astype(jnp.int32)[None, :]

    def f(qa, ka, va, cq, ck, *maybe_seed):
        qh = qa[None].swapaxes(1, 2)  # (1, H, Tq, D)
        kh = ka[None].swapaxes(1, 2)
        vh = va[None].swapaxes(1, 2)
        qsegs = seg_ids(cq, total_q)
        # offset k ids by a non-colliding base only for padding tail:
        ksegs = seg_ids(ck, total_k)
        # tail tokens (>= cu[-1]) must never match: push them out of range
        qs = jnp.where(jnp.arange(total_q)[None, :] < cq[-1], qsegs,
                       jnp.int32(2147483646))
        ks = jnp.where(jnp.arange(total_k)[None, :] < ck[-1], ksegs,
                       jnp.int32(2147483647))
        if maybe_seed:
            # round 5: varlen dropout stays on the streaming kernel too —
            # the (Tq, Tk) materialization VERDICT r4 flagged is gone
            # (parity fallback for untileable shapes lives inside the core)
            out = _flash_core_drop(
                qh, kh, vh, qs, ks,
                jnp.asarray(maybe_seed[0], jnp.int32).reshape(1),
                causal, sm_scale, float(dropout))
        else:
            out = _flash_core_seg(qh, kh, vh, qs, ks, causal, sm_scale)
        return out.swapaxes(1, 2)[0]  # (Tq, H, D)

    args = [q, k, v, cu_q, cu_k] + ([dseed] if dseed is not None else [])
    out = apply("flash_attn_unpadded", f, *args)
    return (out, None) if return_softmax else out


register_op("flash_attention", flash_attention)
