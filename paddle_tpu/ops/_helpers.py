"""Op-definition helpers.

The analogue of the reference's YAML op codegen (upstream:
paddle/phi/ops/yaml/ops.yaml + generators): instead of generating C++ from
YAML, ops here are declared with tiny factories over pure jax functions and
installed onto both the ``paddle_tpu`` namespace and the ``Tensor`` method
surface. ``OP_REGISTRY`` is the runtime op registry (KernelFactory parity).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax.numpy as jnp

from ..core.tensor import Tensor, apply, register_tensor_method, to_tensor

OP_REGISTRY: Dict[str, Callable] = {}


def register_op(name: str, fn: Callable, methods=(), inplace_method: Optional[str] = None):
    """Register a paddle-level op function and optional Tensor methods."""
    OP_REGISTRY[name] = fn
    fn.__name__ = name
    for m in methods:
        register_tensor_method(m, fn)
    if inplace_method:
        def _inplace(self, *args, **kwargs):
            out = fn(self, *args, **kwargs)
            return self._rebind(out)
        _inplace.__name__ = inplace_method
        register_tensor_method(inplace_method, _inplace)
    return fn


def ensure_tensor(x, ref: Optional[Tensor] = None) -> Tensor:
    if isinstance(x, Tensor):
        return x
    return to_tensor(x)


def make_unary(name: str, jfn: Callable, methods=(), differentiable: bool = True,
               inplace: Optional[str] = None):
    def op(x, name=None):
        return apply(op.__name__, jfn, ensure_tensor(x), differentiable=differentiable)
    op.__name__ = name
    return register_op(name, op, methods=methods or (name,), inplace_method=inplace)


def make_binary(name: str, jfn: Callable, methods=(), differentiable: bool = True,
                inplace: Optional[str] = None):
    def op(x, y, name=None):
        x = ensure_tensor(x)
        if isinstance(y, Tensor):
            return apply(op.__name__, jfn, x, y, differentiable=differentiable)
        # python scalar second operand: keep weak typing, close over it
        return apply(op.__name__, lambda a: jfn(a, y), x, differentiable=differentiable)
    op.__name__ = name
    return register_op(name, op, methods=methods or (name,), inplace_method=inplace)


def make_reduction(name: str, jfn: Callable, methods=(), bool_out: bool = False,
                   dtype_pos: Optional[str] = None):
    """Reduction op factory. ``dtype_pos`` pins the upstream positional slot
    of the optional ``dtype`` parameter — upstream is inconsistent about it
    (sum/nansum: dtype BEFORE keepdim; prod: dtype AFTER keepdim; mean and
    the extremum/bool reductions: no dtype at all), and positional callers
    migrating from upstream depend on the exact order."""

    def _run(x, axis, keepdim, dtype):
        x = ensure_tensor(x)
        if isinstance(axis, (list, tuple)):
            axis = tuple(int(a) for a in axis)
        elif axis is not None and not isinstance(axis, int):
            axis = int(axis)

        def f(a):
            r = jfn(a, axis=axis, keepdims=keepdim)
            if dtype is not None:
                r = r.astype(jnp.dtype(dtype))
            return r

        f.__name__ = name
        return apply(name, f, x, differentiable=not bool_out)

    if dtype_pos == "after_axis":
        def op(x, axis=None, dtype=None, keepdim=False, name=None):
            return _run(x, axis, keepdim, dtype)
    elif dtype_pos == "last":
        def op(x, axis=None, keepdim=False, dtype=None, name=None):
            return _run(x, axis, keepdim, dtype)
    else:
        def op(x, axis=None, keepdim=False, name=None):
            return _run(x, axis, keepdim, None)
    op.__name__ = name
    return register_op(name, op, methods=methods or (name,))
