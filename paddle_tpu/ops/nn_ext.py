"""Extended nn functional ops: sampling grids, unpooling, shift ops, and the
long tail of loss functions (CTC / RNN-T / margin family).

Parity surface: python/paddle/nn/functional/{vision,pooling,loss,common}.py.
TPU notes: CTC/RNN-T are log-space DP over ``lax.scan`` (static trip counts,
AD-differentiable — the reference binds warpctc/warprnnt CUDA kernels);
grid_sample/affine_grid are pure gather/matmul forms that XLA fuses.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.random import default_generator
from ..core.tensor import Tensor, apply, register_tensor_method
from ._helpers import ensure_tensor, register_op
from .loss_ops import _reduce


# --- sampling grids ----------------------------------------------------------

def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Generate a 2D sampling grid from batched affine matrices (N, 2, 3)."""
    theta = ensure_tensor(theta)
    if isinstance(out_shape, Tensor):
        out_shape = [int(v) for v in np.asarray(out_shape._data)]
    n, c, h, w = (int(s) for s in out_shape)

    def f(th):
        def lin(size):
            if align_corners:
                return jnp.linspace(-1.0, 1.0, size, dtype=th.dtype)
            step = 2.0 / size
            return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size,
                                dtype=th.dtype)
        ys, xs = jnp.meshgrid(lin(h), lin(w), indexing="ij")
        base = jnp.stack([xs, ys, jnp.ones_like(xs)], axis=-1)  # (H, W, 3)
        # full precision: bf16 grid coordinates would shift every sampled
        # pixel; this contraction is tiny so there is no MXU win to trade
        return jnp.einsum("hwk,njk->nhwj", base, th, precision="highest")

    return apply("affine_grid", f, theta)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample ``x`` (N,C,H,W) at normalized ``grid`` (N,Hg,Wg,2) locations."""
    x, grid = ensure_tensor(x), ensure_tensor(grid)

    def unnormalize(coord, size):
        if align_corners:
            return (coord + 1.0) * (size - 1) / 2.0
        return ((coord + 1.0) * size - 1.0) / 2.0

    def reflect(coord, size):
        if align_corners:
            span = 2.0 * (size - 1)
            if size == 1:
                return jnp.zeros_like(coord)
            c = jnp.abs(coord) % span
            return jnp.where(c > size - 1, span - c, c)
        span = 2.0 * size
        c = jnp.abs(coord + 0.5) % span
        c = jnp.where(c > size, span - c, c) - 0.5
        return jnp.clip(c, 0, size - 1)

    def f(a, g):
        n, c, h, w = a.shape
        gx = unnormalize(g[..., 0], w)
        gy = unnormalize(g[..., 1], h)
        if padding_mode == "border":
            gx, gy = jnp.clip(gx, 0, w - 1), jnp.clip(gy, 0, h - 1)
        elif padding_mode == "reflection":
            gx, gy = reflect(gx, w), reflect(gy, h)

        def gather(ix, iy):
            """Fetch a[n, :, iy, ix] with zero padding outside."""
            valid = ((ix >= 0) & (ix <= w - 1) & (iy >= 0) & (iy <= h - 1))
            ixc = jnp.clip(ix, 0, w - 1).astype(jnp.int32)
            iyc = jnp.clip(iy, 0, h - 1).astype(jnp.int32)
            # batched gather: (N, Hg, Wg) index grids into (N, C, H, W)
            out = jax.vmap(lambda img, yy, xx: img[:, yy, xx])(a, iyc, ixc)
            # out: (N, C, Hg, Wg); zero outside unless border/reflection
            if padding_mode == "zeros":
                out = out * valid[:, None, :, :].astype(a.dtype)
            return out

        if mode == "nearest":
            return gather(jnp.round(gx), jnp.round(gy))
        x0, y0 = jnp.floor(gx), jnp.floor(gy)
        x1, y1 = x0 + 1, y0 + 1
        wa = ((x1 - gx) * (y1 - gy))[:, None]
        wb = ((x1 - gx) * (gy - y0))[:, None]
        wc = ((gx - x0) * (y1 - gy))[:, None]
        wd = ((gx - x0) * (gy - y0))[:, None]
        return (gather(x0, y0) * wa + gather(x0, y1) * wb +
                gather(x1, y0) * wc + gather(x1, y1) * wd)

    return apply("grid_sample", f, x, grid)


# --- pooling with indices / unpooling ---------------------------------------

def _pool_window_indices(h, w, kh, kw, sh, sw, ph, pw):
    """Static flat window index grid over the padded plane."""
    hp, wp = h + 2 * ph, w + 2 * pw
    ho = (hp - kh) // sh + 1
    wo = (wp - kw) // sw + 1
    rows = np.arange(ho)[:, None, None, None] * sh + np.arange(kh)[None, None, :, None]
    cols = np.arange(wo)[None, :, None, None] * sw + np.arange(kw)[None, None, None, :]
    flat = (rows * wp + cols).reshape(ho, wo, kh * kw)
    return flat.astype(np.int32), hp, wp, ho, wo


def max_pool2d_with_index(x, kernel_size, stride=None, padding=0, name=None):
    """Max pool returning (out, flat-argmax-indices) — the mask the reference's
    max_pool2d(return_mask=True) yields, consumed by max_unpool2d."""
    x = ensure_tensor(x)
    kh, kw = (kernel_size, kernel_size) if isinstance(kernel_size, int) else tuple(kernel_size)
    st = stride if stride is not None else kernel_size
    sh, sw = (st, st) if isinstance(st, int) else tuple(st)
    ph, pw = (padding, padding) if isinstance(padding, int) else tuple(padding)
    n_, c_, h, w = (int(s) for s in x._data.shape)
    win, hp, wp, ho, wo = _pool_window_indices(h, w, kh, kw, sh, sw, ph, pw)
    win_j = jnp.asarray(win)

    def f(a):
        apad = jnp.pad(a, ((0, 0), (0, 0), (ph, ph), (pw, pw)),
                       constant_values=-jnp.inf)
        flat = apad.reshape(a.shape[0], a.shape[1], hp * wp)
        g = flat[..., win_j]                       # (N, C, Ho, Wo, K)
        out = jnp.max(g, axis=-1)
        arg = jnp.argmax(g, axis=-1)               # window-local
        pidx = jnp.take_along_axis(
            jnp.broadcast_to(win_j, g.shape[:-1] + win_j.shape[-1:]),
            arg[..., None], axis=-1)[..., 0]       # padded-plane flat idx
        row, col = pidx // wp - ph, pidx % wp - pw
        return out, (row * w + col).astype(jnp.int32)

    out, mask = apply("max_pool2d_with_index", f, x)
    return out, mask


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW", name=None):
    """Scatter pooled values back to their argmax positions."""
    x, indices = ensure_tensor(x), ensure_tensor(indices)
    kh, kw = (kernel_size, kernel_size) if isinstance(kernel_size, int) else tuple(kernel_size)
    st = stride if stride is not None else (kh, kw)
    sh, sw = (st, st) if isinstance(st, int) else tuple(st)
    ph, pw = (padding, padding) if isinstance(padding, int) else tuple(padding)
    n_, c_, ho, wo = (int(s) for s in x._data.shape)
    if output_size is None:
        h = (ho - 1) * sh - 2 * ph + kh
        w = (wo - 1) * sw - 2 * pw + kw
    else:
        h, w = (int(s) for s in output_size[-2:])

    def f(a, idx):
        flat_val = a.reshape(a.shape[0], a.shape[1], -1)
        flat_idx = idx.reshape(idx.shape[0], idx.shape[1], -1)
        zeros = jnp.zeros((a.shape[0], a.shape[1], h * w), a.dtype)
        out = jax.vmap(jax.vmap(lambda z, i, v: z.at[i].set(v)))(
            zeros, flat_idx, flat_val)
        return out.reshape(a.shape[0], a.shape[1], h, w)

    return apply("max_unpool2d", f, x, indices)


# --- misc activations / shifts ----------------------------------------------

def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=False, name=None):
    """Randomized leaky ReLU: slope ~ U[lower, upper] in training, the mean
    slope at inference."""
    x = ensure_tensor(x)
    if training:
        key = default_generator.split_key()

        def f(a):
            slope = jax.random.uniform(key, a.shape, a.dtype, lower, upper)
            return jnp.where(a >= 0, a, a * slope)
    else:
        mid = (lower + upper) / 2.0

        def f(a):
            return jnp.where(a >= 0, a, a * mid)

    return apply("rrelu", f, x)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    """TSM temporal shift (reference: paddle.nn.functional.temporal_shift):
    shift 1/ratio of channels one step backward/forward along time."""
    x = ensure_tensor(x)
    if data_format == "NHWC":
        x = apply("transpose", lambda a: jnp.transpose(a, (0, 3, 1, 2)), x)
    nt, c, h, w = (int(s) for s in x._data.shape)
    n = nt // seg_num
    fold = int(c * shift_ratio)

    def f(a):
        v = a.reshape(n, seg_num, c, h, w)
        back = jnp.concatenate(  # channels [0:fold) come from t+1
            [v[:, 1:, :fold], jnp.zeros_like(v[:, :1, :fold])], axis=1)
        fwd = jnp.concatenate(  # channels [fold:2fold) come from t-1
            [jnp.zeros_like(v[:, :1, fold:2 * fold]), v[:, :-1, fold:2 * fold]],
            axis=1)
        rest = v[:, :, 2 * fold:]
        return jnp.concatenate([back, fwd, rest], axis=2).reshape(nt, c, h, w)

    out = apply("temporal_shift", f, x)
    if data_format == "NHWC":
        out = apply("transpose", lambda a: jnp.transpose(a, (0, 2, 3, 1)), out)
    return out


# --- margin / probabilistic losses -------------------------------------------

def soft_margin_loss(input, label, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply("soft_margin_loss",
                 lambda a, y: _reduce(jax.nn.softplus(-y.astype(a.dtype) * a),
                                      reduction),
                 input, label)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    extras = [ensure_tensor(weight)] if weight is not None else []

    def f(a, y, *wa):
        n, c = a.shape
        correct = jnp.take_along_axis(a, y[:, None].astype(jnp.int32), axis=1)
        m = jnp.clip(margin - correct + a, 0.0, None) ** p
        if wa:
            m = m * wa[0][y.astype(jnp.int32)][:, None]
        m = m * (1 - jax.nn.one_hot(y, c, dtype=a.dtype))
        return _reduce(jnp.sum(m, axis=1) / c, reduction)

    return apply("multi_margin_loss", f, input, label, *extras)


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    anchor, positive, labels = (ensure_tensor(anchor), ensure_tensor(positive),
                                ensure_tensor(labels))

    def f(a, p, y):
        y = y.reshape(-1)
        sim = a @ p.T
        eq = (y[:, None] == y[None, :]).astype(a.dtype)
        tgt = eq / jnp.sum(eq, axis=1, keepdims=True)
        logp = jax.nn.log_softmax(sim, axis=1)
        ce_r = -jnp.mean(jnp.sum(tgt * logp, axis=1))
        logp_c = jax.nn.log_softmax(sim.T, axis=1)
        ce_c = -jnp.mean(jnp.sum(tgt * logp_c, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, axis=1)) +
                        jnp.mean(jnp.sum(p * p, axis=1))) * 0.25
        return (ce_r + ce_c) * 0.5 + reg

    return apply("npair_loss", f, anchor, positive, labels)


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def f(a, y):
        y = y.astype(a.dtype)
        if log_input:
            loss = jnp.exp(a) - y * a
        else:
            loss = a - y * jnp.log(a + epsilon)
        if full:  # Stirling approximation for log(y!)
            stir = y * jnp.log(y) - y + 0.5 * jnp.log(2 * jnp.pi * y)
            loss = loss + jnp.where(y > 1, stir, 0.0)
        return _reduce(loss, reduction)

    return apply("poisson_nll_loss", f, input, label)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    input, label, variance = (ensure_tensor(input), ensure_tensor(label),
                              ensure_tensor(variance))

    def f(mu, y, var):
        var = jnp.clip(var, epsilon, None)
        loss = 0.5 * (jnp.log(var) + (y.astype(mu.dtype) - mu) ** 2 / var)
        if full:
            loss = loss + 0.5 * jnp.log(jnp.asarray(2 * jnp.pi, mu.dtype))
        return _reduce(loss, reduction)

    return apply("gaussian_nll_loss", f, input, label, variance)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean", name=None):
    """ArcFace-style margin softmax (reference: fused margin_cross_entropy;
    the model-parallel variant shards classes over the mp group — here the
    single-shard math, sharded classes ride the TP layer)."""
    logits, label = ensure_tensor(logits), ensure_tensor(label)

    def f(z, y):
        theta = jnp.arccos(jnp.clip(z, -1.0 + 1e-7, 1.0 - 1e-7))
        yi = y.reshape(-1).astype(jnp.int32)
        onehot = jax.nn.one_hot(yi, z.shape[-1], dtype=z.dtype)
        target_theta = margin1 * theta + margin2
        zt = jnp.cos(target_theta) - margin3
        adj = onehot * zt + (1 - onehot) * z
        slog = jax.nn.log_softmax(adj * scale, axis=-1)
        loss = -jnp.sum(onehot * slog, axis=-1)
        return _reduce(loss, reduction), jnp.exp(slog)

    loss, sm = apply("margin_cross_entropy", f, logits, label)
    return (loss, sm) if return_softmax else loss


# --- CTC ---------------------------------------------------------------------

def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False, name=None):
    """Connectionist temporal classification loss.

    ``log_probs``: (T, B, C) logits (log_softmax applied internally, as the
    reference's warpctc does). ``labels``: (B, L) padded. Log-space alpha
    recursion over the extended label sequence via ``lax.scan`` — fully
    differentiable by AD, no custom backward needed.
    """
    log_probs, labels = ensure_tensor(log_probs), ensure_tensor(labels)
    input_lengths, label_lengths = (ensure_tensor(input_lengths),
                                    ensure_tensor(label_lengths))
    neg_inf = -1e30

    def f(lp, lab, ilen, llen):
        t_max, b, c = lp.shape
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        l_max = lab.shape[1]
        s_max = 2 * l_max + 1
        lab = lab.astype(jnp.int32)
        # extended sequence: blank, l1, blank, l2, ... blank
        ext = jnp.full((b, s_max), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab)
        pos = jnp.arange(s_max)[None, :]
        in_seq = pos < (2 * llen[:, None] + 1)
        # can skip from s-2 when ext[s] != blank and ext[s] != ext[s-2]
        ext_m2 = jnp.concatenate([jnp.full((b, 2), -1, jnp.int32), ext[:, :-2]],
                                 axis=1)
        can_skip = (ext != blank) & (ext != ext_m2)

        def emit(t):
            return jnp.take_along_axis(lp[t], ext, axis=1)  # (B, S)

        alpha0 = jnp.full((b, s_max), neg_inf, jnp.float32)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        first_lab = jnp.where(llen > 0, lab[:, 0], blank)
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(llen > 0,
                      lp[0, jnp.arange(b), first_lab], neg_inf))

        def step(alpha, t):
            prev1 = jnp.concatenate(
                [jnp.full((b, 1), neg_inf), alpha[:, :-1]], axis=1)
            prev2 = jnp.concatenate(
                [jnp.full((b, 2), neg_inf), alpha[:, :-2]], axis=1)
            prev2 = jnp.where(can_skip, prev2, neg_inf)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, prev1), prev2)
            new = merged + emit(t)
            new = jnp.where(in_seq, new, neg_inf)
            # freeze once past this sample's input length
            new = jnp.where((t < ilen)[:, None], new, alpha)
            return new, None

        alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, t_max))
        send = 2 * llen  # index of final blank
        a_last = jnp.take_along_axis(alpha, send[:, None].astype(jnp.int32),
                                     axis=1)[:, 0]
        a_prev = jnp.take_along_axis(
            alpha, jnp.clip(send - 1, 0)[:, None].astype(jnp.int32),
            axis=1)[:, 0]
        a_prev = jnp.where(llen > 0, a_prev, neg_inf)
        nll = -jnp.logaddexp(a_last, a_prev)
        if norm_by_times:
            nll = nll / ilen.astype(nll.dtype)
        if reduction == "mean":
            # reference semantics: divide by label length, then batch-mean
            nll = nll / jnp.clip(llen.astype(nll.dtype), 1.0, None)
        return _reduce(nll, reduction)

    return apply("ctc_loss", f, log_probs, labels, input_lengths,
                 label_lengths)


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.0, reduction="mean", name=None):
    """RNN transducer loss over the (T, U) lattice.

    ``input``: (B, T, U+1, C) joint-network logits; alpha recursion runs as a
    scan over T with an inner scan over U (the reference binds warprnnt).
    FastEmit per-arc gradient scaling needs the beta recursion and is not
    implemented — pass fastemit_lambda=0 (documented divergence).
    """
    if fastemit_lambda:
        raise NotImplementedError(
            "rnnt_loss: FastEmit regularization (fastemit_lambda != 0) is "
            "not implemented in this build; pass fastemit_lambda=0.")
    input, label = ensure_tensor(input), ensure_tensor(label)
    input_lengths, label_lengths = (ensure_tensor(input_lengths),
                                    ensure_tensor(label_lengths))
    neg_inf = -1e30

    def f(lg, lab, ilen, llen):
        b, t_max, u1, c = lg.shape
        u_max = u1 - 1
        lp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
        lab = lab.astype(jnp.int32)
        bi = jnp.arange(b)
        blank_lp = lp[..., blank]                     # (B, T, U+1)
        yidx = jnp.broadcast_to(lab[:, None, :], (b, t_max, u_max))
        y_lp = jnp.take_along_axis(lp[:, :, :u_max, :], yidx[..., None],
                                   axis=-1)[..., 0]  # (B, T, U)

        us = jnp.arange(u1)[None, :]

        def t_step(alpha_prev, t):
            # horizontal move: consume frame t-1 with blank at same u
            horiz = alpha_prev + blank_lp[:, t - 1, :]

            def u_step(carry, u):
                # vertical move inside frame t: emit label u-1
                val = jnp.where(
                    u > 0,
                    carry + y_lp[bi, t, jnp.clip(u - 1, 0)],
                    neg_inf)
                new = jnp.logaddexp(horiz[:, u], val)
                return new, new

            _, cols = jax.lax.scan(u_step, jnp.full((b,), neg_inf),
                                   jnp.arange(u1))
            alpha_t = jnp.swapaxes(cols, 0, 1)        # (B, U+1)
            alpha_t = jnp.where(us <= llen[:, None], alpha_t, neg_inf)
            alpha_t = jnp.where((t < ilen)[:, None], alpha_t, alpha_prev)
            return alpha_t, None

        # t = 0 row: only vertical moves
        def u0_step(carry, u):
            val = jnp.where(u > 0, carry + y_lp[bi, 0, jnp.clip(u - 1, 0)],
                            0.0)
            return val, val

        _, cols0 = jax.lax.scan(u0_step, jnp.zeros((b,)), jnp.arange(u1))
        alpha0 = jnp.swapaxes(cols0, 0, 1)
        alpha0 = jnp.where(us <= llen[:, None], alpha0, neg_inf)

        alpha, _ = jax.lax.scan(t_step, alpha0, jnp.arange(1, t_max))
        final = alpha[bi, llen.astype(jnp.int32)] + \
            blank_lp[bi, jnp.clip(ilen - 1, 0).astype(jnp.int32),
                     llen.astype(jnp.int32)]
        return _reduce(-final, reduction)

    return apply("rnnt_loss", f, input, label, input_lengths, label_lengths)


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """Adaptive softmax (hierarchical output layer for large vocabularies):
    frequent classes score through the head matmul, rare ones through
    down-projected tail clusters."""
    input, label = ensure_tensor(input), ensure_tensor(label)
    head_weight = ensure_tensor(head_weight)
    tails = [(ensure_tensor(w1), ensure_tensor(w2)) for w1, w2 in tail_weights]
    extras = [head_weight] + [w for pair in tails for w in pair]
    if head_bias is not None:
        extras.append(ensure_tensor(head_bias))
    n_clusters = len(cutoffs)
    shortlist = int(cutoffs[0]) if cutoffs else 0
    cut = [0] + [int(cv) for cv in cutoffs]

    def f(a, y, hw, *rest):
        # layout contract: hw (in_features, head_size); w1 (in_features, hsz);
        # w2 (hsz, cluster_size) — as the AdaptiveLogSoftmaxWithLoss layer
        # creates them. No shape sniffing: coinciding dims must not transpose.
        tw = [(rest[2 * i], rest[2 * i + 1]) for i in range(n_clusters)]
        hb = rest[2 * n_clusters] if head_bias is not None else None
        head = a @ hw
        if hb is not None:
            head = head + hb
        head_lsm = jax.nn.log_softmax(head, axis=-1)
        y = y.reshape(-1).astype(jnp.int32)
        # shortlist classes score directly from the head
        out = jnp.take_along_axis(head_lsm,
                                  jnp.clip(y, 0, shortlist - 1)[:, None],
                                  axis=1)[:, 0]
        # tail cluster i covers [cut[i+1], cut[i+1] + cluster_size)
        for i, (w1, w2) in enumerate(tw):
            lo = cut[i + 1]
            tail_lsm = jax.nn.log_softmax((a @ w1) @ w2, axis=-1)
            hi = lo + tail_lsm.shape[1]
            in_tail = (y >= lo) & (y < hi)
            cluster_lp = head_lsm[:, shortlist + i]
            rel = jnp.clip(y - lo, 0, tail_lsm.shape[1] - 1)
            tail_val = cluster_lp + jnp.take_along_axis(
                tail_lsm, rel[:, None], axis=1)[:, 0]
            out = jnp.where(in_tail, tail_val, out)
        loss = -jnp.mean(out)
        return out, loss

    out, loss = apply("adaptive_log_softmax_with_loss", f, input, label,
                      *extras)
    return out, loss


for _name in ("affine_grid", "grid_sample", "max_unpool2d", "rrelu",
              "temporal_shift", "soft_margin_loss", "multi_margin_loss",
              "npair_loss", "poisson_nll_loss", "gaussian_nll_loss",
              "margin_cross_entropy", "ctc_loss", "rnnt_loss",
              "adaptive_log_softmax_with_loss", "max_pool2d_with_index"):
    register_op(_name, globals()[_name])


def class_center_sample(label, num_classes, num_samples, group=None,
                        name=None):
    """Sample negative class centers (PartialFC): keep all positive classes
    plus random negatives up to ``num_samples`` (reference:
    paddle.nn.functional.class_center_sample). Static output: returns
    (remapped_label, sampled_class_center) with the sampled set padded to
    num_samples by the smallest unused class ids."""
    label = ensure_tensor(label)
    key = default_generator.split_key()
    from ..core.tensor import _is_tracer
    if not _is_tracer(label._data):
        uniq = int(np.unique(np.asarray(label._data)).shape[0])
        if uniq > num_samples:
            raise ValueError(
                f"class_center_sample: {uniq} distinct positive classes "
                f"exceed num_samples={num_samples}; labels could not be "
                "remapped consistently")

    def f(y):
        y = y.reshape(-1).astype(jnp.int32)
        pos_mask = jnp.zeros((num_classes,), bool).at[y].set(True)
        # random priority; positives forced to the front
        prio = jax.random.uniform(key, (num_classes,))
        prio = jnp.where(pos_mask, 2.0, prio)
        _, sampled = jax.lax.top_k(prio, num_samples)
        sampled = jnp.sort(sampled)
        # remap: position of each label inside the sampled set
        rank_in_sampled = jnp.searchsorted(sampled, y)
        return rank_in_sampled.astype(y.dtype), sampled.astype(y.dtype)

    out = apply("class_center_sample", f, label, differentiable=False)
    return tuple(out)


def sparse_attention(query, key_t, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """Block-sparse attention (reference: the cuSPARSE-backed
    sparse_attention op). The CSR pattern selects which keys each query
    attends to; TPU-native form: dense attention with the complement masked
    to -inf (XLA fuses the mask; for long sequences route to flash/ring
    attention instead — documented divergence on the compute pattern, not
    the semantics)."""
    query, key_t, value = (ensure_tensor(query), ensure_tensor(key_t),
                           ensure_tensor(value))
    offs, cols = ensure_tensor(sparse_csr_offset), ensure_tensor(sparse_csr_columns)

    def f(q, k, v, off, col):
        b, h, sq, d = q.shape
        sk = k.shape[2]

        def mask_one(off_bh, col_bh):
            m = jnp.zeros((sq, sk), bool)
            # CSR row of nnz entry e: the r with off[r] <= e < off[r+1]
            row_idx = jnp.searchsorted(off_bh[1:],
                                       jnp.arange(col_bh.shape[0]),
                                       side="right")
            return m.at[row_idx, col_bh].set(True)

        mask = jax.vmap(jax.vmap(mask_one))(
            off.reshape(b, h, sq + 1), col.reshape(b, h, -1))
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) / (d ** 0.5)
        logits = jnp.where(mask, logits, -1e30)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        # a row with NO csr entries must output zero, not uniform attention
        # (softmax of an all -1e30 row is uniform)
        row_has = jnp.any(mask, axis=-1, keepdims=True)
        probs = probs * row_has.astype(probs.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(q.dtype), v)

    return apply("sparse_attention", f, query, key_t, value, offs, cols)


register_op("class_center_sample", class_center_sample)
register_op("sparse_attention", sparse_attention)


# --- wave-3 losses / layers ops ----------------------------------------------

def dice_loss(input, label, epsilon=1e-5, name=None):
    """Dice loss over the last axis (reference: F.dice_loss)."""
    input, label = ensure_tensor(input), ensure_tensor(label)

    def f(p, y):
        y1 = jax.nn.one_hot(y.reshape(y.shape[:-1]).astype(jnp.int32),
                            p.shape[-1], dtype=p.dtype)
        red = tuple(range(1, p.ndim))
        inter = jnp.sum(p * y1, axis=red)
        union = jnp.sum(p, axis=red) + jnp.sum(y1, axis=red)
        return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))

    return apply("dice_loss", f, input, label)


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    extras = [ensure_tensor(weight)] if weight is not None else []

    def f(a, y, *w):
        y = y.astype(a.dtype)
        term = y * jax.nn.log_sigmoid(a) + (1 - y) * jax.nn.log_sigmoid(-a)
        if w:
            term = term * w[0]  # per-class weight applies BEFORE the mean
        loss = -jnp.mean(term, axis=-1)
        return _reduce(loss, reduction)

    return apply("multi_label_soft_margin_loss", f, input, label, *extras)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean", name=None):
    input, positive, negative = (ensure_tensor(input),
                                 ensure_tensor(positive),
                                 ensure_tensor(negative))
    if distance_function is None:
        def dist(a, b):
            return jnp.sqrt(jnp.sum((a - b) ** 2, axis=-1) + 1e-12)
    else:
        def dist(a, b):
            out = distance_function(Tensor(a), Tensor(b))
            return out._data if isinstance(out, Tensor) else out

    def f(a, p, n):
        dp = dist(a, p)
        dn = dist(a, n)
        if swap:
            dn = jnp.minimum(dn, dist(p, n))
        return _reduce(jnp.clip(dp - dn + margin, 0.0, None), reduction)

    return apply("triplet_margin_with_distance_loss", f, input, positive,
                 negative)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss over the default complete binary tree
    (reference: F.hsigmoid_loss; custom path tables route like the default)."""
    input, label = ensure_tensor(input), ensure_tensor(label)
    weight = ensure_tensor(weight)
    extras = [ensure_tensor(bias)] if bias is not None else []
    import math as _math
    code_len = max(1, int(_math.ceil(_math.log2(max(num_classes, 2)))))

    def f(a, y, w, *b):
        y = y.reshape(-1).astype(jnp.int32)
        # default tree: internal node ids via the heap path of (y + C)
        node = y + num_classes
        losses = jnp.zeros((a.shape[0],), a.dtype)
        for _ in range(code_len):
            parent = node // 2
            is_right = (node % 2).astype(a.dtype)
            valid = (parent >= 1) & (parent - 1 < w.shape[0])
            pidx = jnp.clip(parent - 1, 0, w.shape[0] - 1)
            logit = jnp.sum(a * w[pidx], axis=-1)
            if b:
                logit = logit + b[0].reshape(-1)[pidx]
            # code 0 (left): target sigmoid 1; code 1: target 0
            step_loss = jax.nn.softplus(jnp.where(is_right > 0, logit,
                                                  -logit))
            losses = losses + jnp.where(valid, step_loss, 0.0)
            node = parent
        return losses[:, None]  # (N, 1): the reference's per-sample output

    return apply("hsigmoid_loss", f, input, label, weight, *extras)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    pl, pr, pt, pb = (int(v) for v in padding)

    def f(a):
        if data_format == "NCHW":
            return jnp.pad(a, ((0, 0), (0, 0), (pt, pb), (pl, pr)))
        return jnp.pad(a, ((0, 0), (pt, pb), (pl, pr), (0, 0)))

    return apply("zeropad2d", f, x)


def embedding_bag(input, weight, offsets=None, mode="mean", name=None):
    """Gather + segment-reduce rows of ``weight`` (reference:
    F.embedding_bag). 2D input reduces each row's bag; 1D input + offsets
    reduces variable-length bags (eager, concrete offsets)."""
    input, weight = ensure_tensor(input), ensure_tensor(weight)
    if offsets is None:
        def f(ids, w):
            emb = w[ids.astype(jnp.int32)]          # (B, L, D)
            if mode == "sum":
                return jnp.sum(emb, axis=1)
            if mode == "max":
                return jnp.max(emb, axis=1)
            return jnp.mean(emb, axis=1)

        return apply("embedding_bag", f, input, weight)

    offsets = ensure_tensor(offsets)
    off = np.asarray(offsets._data).astype(np.int64)
    n = int(np.asarray(input._data).shape[0])
    bounds = list(off) + [n]

    def f(ids, w):
        emb = w[ids.astype(jnp.int32)]
        outs = []
        for i in range(len(bounds) - 1):
            seg = emb[int(bounds[i]): int(bounds[i + 1])]
            if mode == "sum":
                outs.append(jnp.sum(seg, axis=0))
            elif mode == "max":
                outs.append(jnp.max(seg, axis=0))
            else:
                outs.append(jnp.mean(seg, axis=0))
        return jnp.stack(outs)

    return apply("embedding_bag", f, input, weight)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """p-norm of (x - y + epsilon) — the reference perturbs the difference
    once (numerical-stability epsilon), not every |.| term."""
    x, y = ensure_tensor(x), ensure_tensor(y)

    def f(a, b):
        d = jnp.abs(a - b + epsilon)
        if p == float("inf"):
            return jnp.max(d, axis=-1, keepdims=keepdim)
        return jnp.sum(d ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)

    return apply("pairwise_distance", f, x, y)


def linear_compress(x, weight, bias=None, scale=None, algo="weight_only_int8",
                    name=None):
    """Compressed linear (reference: F.linear_compress): routes to the
    weight-only quantized matmul."""
    from ..nn.quant import weight_only_linear
    return weight_only_linear(x, weight, bias=bias, weight_scale=scale)


register_op("dice_loss", dice_loss)
register_op("multi_label_soft_margin_loss", multi_label_soft_margin_loss)
register_op("triplet_margin_with_distance_loss",
            triplet_margin_with_distance_loss)
register_op("hsigmoid_loss", hsigmoid_loss)
register_op("zeropad2d", zeropad2d)
register_op("embedding_bag", embedding_bag)
register_op("pairwise_distance", pairwise_distance)
register_op("linear_compress", linear_compress)


def _unpool_scatter(op_name, x, indices, out_spatial):
    """Shared N-D unpool kernel: scatter (N, C, *spatial) values to flat
    positions ``indices`` of an (N, C, prod(out_spatial)) zero canvas."""
    import math as _math

    total = int(_math.prod(out_spatial))

    def f(a, idx):
        flat_val = a.reshape(a.shape[0], a.shape[1], -1)
        flat_idx = idx.reshape(idx.shape[0], idx.shape[1], -1)
        zeros = jnp.zeros((a.shape[0], a.shape[1], total), a.dtype)
        out = jax.vmap(jax.vmap(lambda z, i, v: z.at[i].set(v)))(
            zeros, flat_idx, flat_val)
        return out.reshape(a.shape[:2] + tuple(out_spatial))

    return apply(op_name, f, x, indices)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCL", name=None):
    """1-D unpool: scatter pooled values to their argmax positions
    (reference: paddle.nn.functional.max_unpool1d)."""
    x, indices = ensure_tensor(x), ensure_tensor(indices)
    k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    s = (stride if stride is not None else k)
    s = s if isinstance(s, int) else s[0]
    p = padding if isinstance(padding, int) else padding[0]
    n_, c_, lo = (int(d) for d in x._data.shape)
    length = (lo - 1) * s - 2 * p + k if output_size is None \
        else int(output_size[-1])
    return _unpool_scatter("max_unpool1d", x, indices, (length,))


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCDHW", name=None):
    """3-D unpool (reference: paddle.nn.functional.max_unpool3d)."""
    x, indices = ensure_tensor(x), ensure_tensor(indices)
    kd, kh, kw = ((kernel_size,) * 3 if isinstance(kernel_size, int)
                  else tuple(kernel_size))
    st = stride if stride is not None else (kd, kh, kw)
    sd, sh, sw = (st,) * 3 if isinstance(st, int) else tuple(st)
    pd, ph, pw = ((padding,) * 3 if isinstance(padding, int)
                  else tuple(padding))
    n_, c_, do, ho, wo = (int(d) for d in x._data.shape)
    if output_size is None:
        d = (do - 1) * sd - 2 * pd + kd
        h = (ho - 1) * sh - 2 * ph + kh
        w = (wo - 1) * sw - 2 * pw + kw
    else:
        d, h, w = (int(v) for v in output_size[-3:])
    return _unpool_scatter("max_unpool3d", x, indices, (d, h, w))


def _fractional_bounds(inp, out, u):
    """Pseudo-random increasing region boundaries (Graham 2014 alpha
    sequence: ceil(alpha*(i+u)) - ceil(alpha*u))."""
    import numpy as _np

    alpha = inp / out
    base = _np.ceil(alpha * (_np.arange(out + 1) + u)) - _np.ceil(alpha * u)
    base = _np.clip(base, 0, inp).astype(_np.int32)
    base[-1] = inp
    return base


def fractional_max_pool2d(x, output_size, kernel_size=None,
                          random_u=None, return_mask=False, name=None):
    """Fractional max pooling (Graham 2014; reference:
    paddle.nn.functional.fractional_max_pool2d): pseudo-random pooling
    regions whose sizes average H/out_h. The region boundaries follow the
    reference's alpha-sequence construction from a single random u."""
    x = ensure_tensor(x)
    if kernel_size is not None:
        raise NotImplementedError(
            "fractional_max_pool2d: only the disjoint (kernel_size=None) "
            "mode is implemented; fixed-size overlapping windows are not")
    n_, c_, h, w = (int(d) for d in x._data.shape)
    oh, ow = ((output_size, output_size) if isinstance(output_size, int)
              else tuple(output_size))
    if random_u is None:
        from ..core.random import default_generator
        key = default_generator.split_key()
        u = float(jax.random.uniform(key, (), jnp.float32, 0.05, 0.95))
    else:
        u = float(random_u)

    hb, wb = _fractional_bounds(h, oh, u), _fractional_bounds(w, ow, u)

    def f(a):
        rows = []
        for i in range(oh):
            cols = []
            for j in range(ow):
                region = a[:, :, hb[i]:max(hb[i + 1], hb[i] + 1),
                           wb[j]:max(wb[j + 1], wb[j] + 1)]
                cols.append(jnp.max(region, axis=(2, 3)))
            rows.append(jnp.stack(cols, axis=-1))
        return jnp.stack(rows, axis=-2)

    out = apply("fractional_max_pool2d", f, x)
    if return_mask:
        # reference returns flat argmax indices into the input plane
        def fm(a):
            rows = []
            for i in range(oh):
                cols = []
                for j in range(ow):
                    h0, h1 = hb[i], max(hb[i + 1], hb[i] + 1)
                    w0, w1 = wb[j], max(wb[j + 1], wb[j] + 1)
                    region = a[:, :, h0:h1, w0:w1]
                    flat = region.reshape(region.shape[0], region.shape[1], -1)
                    am = jnp.argmax(flat, axis=-1)
                    rw = w1 - w0
                    cols.append((h0 + am // rw) * w + (w0 + am % rw))
                rows.append(jnp.stack(cols, axis=-1))
            return jnp.stack(rows, axis=-2).astype(jnp.int32)

        mask = apply("fractional_max_pool2d_mask", fm, x,
                     differentiable=False)
        return out, mask
    return out


def fractional_max_pool3d(x, output_size, kernel_size=None,
                          random_u=None, return_mask=False, name=None):
    """3-D fractional max pooling (reference:
    paddle.nn.functional.fractional_max_pool3d)."""
    x = ensure_tensor(x)
    if kernel_size is not None:
        raise NotImplementedError(
            "fractional_max_pool3d: only the disjoint (kernel_size=None) "
            "mode is implemented; fixed-size overlapping windows are not")
    n_, c_, d, h, w = (int(v) for v in x._data.shape)
    od, oh, ow = ((output_size,) * 3 if isinstance(output_size, int)
                  else tuple(output_size))
    if random_u is None:
        from ..core.random import default_generator
        key = default_generator.split_key()
        u = float(jax.random.uniform(key, (), jnp.float32, 0.05, 0.95))
    else:
        u = float(random_u)

    db = _fractional_bounds(d, od, u)
    hb = _fractional_bounds(h, oh, u)
    wb = _fractional_bounds(w, ow, u)

    def f(a):
        planes = []
        for q in range(od):
            rows = []
            for i in range(oh):
                cols = []
                for j in range(ow):
                    region = a[:, :,
                               db[q]:max(db[q + 1], db[q] + 1),
                               hb[i]:max(hb[i + 1], hb[i] + 1),
                               wb[j]:max(wb[j + 1], wb[j] + 1)]
                    cols.append(jnp.max(region, axis=(2, 3, 4)))
                rows.append(jnp.stack(cols, axis=-1))
            planes.append(jnp.stack(rows, axis=-2))
        return jnp.stack(planes, axis=-3)

    out = apply("fractional_max_pool3d", f, x)
    if return_mask:
        raise NotImplementedError(
            "fractional_max_pool3d(return_mask=True) is not implemented; "
            "use fractional_max_pool2d or max_pool3d masks")
    return out


for _n in ("max_unpool1d", "max_unpool3d", "fractional_max_pool2d",
           "fractional_max_pool3d"):
    register_op(_n, globals()[_n])


def bilinear(x1, x2, weight, bias=None, name=None):
    """Functional bilinear transform (reference: paddle.nn.functional.
    bilinear): out[b, o] = x1[b] @ W[o] @ x2[b]^T (+ bias)."""
    x1, x2, weight = (ensure_tensor(x1), ensure_tensor(x2),
                      ensure_tensor(weight))
    from .linalg import _precision

    if bias is None:
        return apply("bilinear",
                     lambda a, b, w: jnp.einsum("bi,oij,bj->bo", a, w, b,
                                                precision=_precision()),
                     x1, x2, weight)
    return apply("bilinear",
                 lambda a, b, w, bb: jnp.einsum(
                     "bi,oij,bj->bo", a, w, b, precision=_precision())
                 + bb.reshape(1, -1),
                 x1, x2, weight, ensure_tensor(bias))


def gather_tree(ids, parents, name=None):
    """Beam-search backtrace (reference: paddle.nn.functional.gather_tree;
    upstream gather_tree op): ids/parents are (max_time, batch, beam); walk
    backwards from the last step following parent pointers so every beam
    holds its FULL token path. Static-shaped lax.scan over reversed time —
    jit-safe."""
    import jax

    ids, parents = ensure_tensor(ids), ensure_tensor(parents)

    def f(idv, parv):
        # canonical recurrence (upstream gather_tree / TF seq2seq):
        #   out[T-1] = ids[T-1, beam]; parent = parents[T-1, beam]
        #   for t in T-2..0: out[t] = ids[t, parent];
        #                    parent = parents[t, parent]
        T = idv.shape[0]
        beams = jnp.arange(idv.shape[2], dtype=parv.dtype)
        b_idx = jnp.arange(idv.shape[1])[:, None]

        def step(carry, t):
            ptr = carry
            tok = idv[t][b_idx, ptr]
            return parv[t][b_idx, ptr], tok

        init = jnp.broadcast_to(beams[None, :],
                                (idv.shape[1], idv.shape[2]))
        _, toks = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return toks[::-1]

    return apply("gather_tree", f, ids, parents, differentiable=False)


register_op("bilinear", bilinear)
register_op("gather_tree", gather_tree)
