"""Matmul / linear algebra ops.

Parity surface: python/paddle/tensor/linalg.py + paddle/phi/kernels matmul
family. Matmuls are THE MXU ops: they stay large and batched; precision is
controlled by FLAGS_tpu_matmul_precision (default lets XLA pick bf16-on-MXU
with fp32 accumulation).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import flags as _flags
from ..core.tensor import Tensor, apply, register_tensor_method, to_tensor
from ._helpers import ensure_tensor, register_op


def _precision():
    p = _flags.flag("tpu_matmul_precision")
    return None if p == "default" else p


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)

    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b, precision=_precision())

    return apply("matmul", f, x, y)


register_op("matmul", matmul, methods=("matmul", "mm", "__matmul__"))


def mm(input, mat2, name=None):
    """Upstream ``paddle.mm(input, mat2)`` — plain matmul, upstream arg
    names (a migrating ``mm(input=a, mat2=b)`` call must bind)."""
    return matmul(input, mat2)


register_op("mm", mm)


def _rmatmul(self, other):
    return matmul(ensure_tensor(other), self)


register_tensor_method("__rmatmul__", _rmatmul)


def bmm(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply("bmm", lambda a, b: jnp.matmul(a, b, precision=_precision()), x, y)


register_op("bmm", bmm, methods=("bmm",))


def dot(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply("dot", lambda a, b: jnp.sum(a * b, axis=-1), x, y)


register_op("dot", dot, methods=("dot",))


def inner(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply("inner", lambda a, b: jnp.inner(a, b), x, y)


def outer(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply("outer", lambda a, b: jnp.outer(a, b), x, y)


register_op("inner", inner, methods=("inner",))
register_op("outer", outer, methods=("outer",))


def einsum(equation, *operands):
    tensors = [ensure_tensor(t) for t in operands]
    return apply("einsum",
                 lambda *arrs: jnp.einsum(equation, *arrs, precision=_precision()),
                 *tensors)


register_op("einsum", einsum)


def kron(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply("kron", jnp.kron, x, y)


register_op("kron", kron, methods=("kron",))


def mv(x, vec, name=None):
    x, vec = ensure_tensor(x), ensure_tensor(vec)
    return apply("mv", lambda a, v: a @ v, x, vec)


register_op("mv", mv, methods=("mv",))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    input, x, y = ensure_tensor(input), ensure_tensor(x), ensure_tensor(y)
    return apply("addmm",
                 lambda i, a, b: beta * i + alpha * jnp.matmul(a, b, precision=_precision()),
                 input, x, y)


register_op("addmm", addmm, methods=("addmm",))


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis

    def f(a):
        if p == "fro":
            if ax is None:
                return jnp.sqrt(jnp.sum(a * a))
            return jnp.sqrt(jnp.sum(a * a, axis=ax, keepdims=keepdim))
        if p in ("inf", float("inf")):
            r = jnp.max(jnp.abs(a), axis=ax, keepdims=keepdim)
            return r
        if p in ("-inf", float("-inf")):
            return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=ax, keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)

    return apply("norm", f, x)


register_op("norm", norm, methods=("norm",))


def dist(x, y, p=2, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)

    def f(a, b):
        d = jnp.abs(a - b)
        if p == float("inf"):
            return jnp.max(d)
        if p == 0:
            return jnp.sum((d != 0).astype(a.dtype))
        return jnp.sum(d ** p) ** (1.0 / p)

    return apply("dist", f, x, y)


register_op("dist", dist, methods=("dist",))


# linalg submodule-style ops
def inv(x, name=None):
    return apply("inv", jnp.linalg.inv, ensure_tensor(x))


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply("pinv", lambda a: jnp.linalg.pinv(a, rcond=rcond, hermitian=hermitian),
                 ensure_tensor(x))


def det(x, name=None):
    return apply("det", jnp.linalg.det, ensure_tensor(x))


def slogdet(x, name=None):
    x = ensure_tensor(x)
    out = apply("slogdet", lambda a: tuple(jnp.linalg.slogdet(a)), x)
    return out


def svd(x, full_matrices=False, name=None):
    x = ensure_tensor(x)
    return tuple(apply("svd", lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)), x))


def qr(x, mode="reduced", name=None):
    x = ensure_tensor(x)
    return tuple(apply("qr", lambda a: tuple(jnp.linalg.qr(a, mode=mode)), x))


def eigh(x, UPLO="L", name=None):
    x = ensure_tensor(x)
    return tuple(apply("eigh", lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), x))


def eig(x, name=None):
    x = ensure_tensor(x)
    return tuple(apply("eig", lambda a: tuple(jnp.linalg.eig(a)), x))


def eigvals(x, name=None):
    return apply("eigvals", jnp.linalg.eigvals, ensure_tensor(x))


def eigvalsh(x, UPLO="L", name=None):
    return apply("eigvalsh", lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), ensure_tensor(x))


def cholesky(x, upper=False, name=None):
    def f(a):
        l = jnp.linalg.cholesky(a)
        return jnp.swapaxes(l, -1, -2) if upper else l
    return apply("cholesky", f, ensure_tensor(x))


def cholesky_solve(x, y, upper=False, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)

    def f(b, l):
        if upper:
            l = jnp.swapaxes(l, -1, -2)
        z = jax.scipy.linalg.solve_triangular(l, b, lower=True)
        return jax.scipy.linalg.solve_triangular(jnp.swapaxes(l, -1, -2), z, lower=False)

    return apply("cholesky_solve", f, x, y)


def cholesky_inverse(x, upper=False, name=None):
    """Inverse of the SPD matrix whose Cholesky factor is ``x``
    (reference: paddle.linalg.cholesky_inverse / torch.cholesky_inverse,
    upstream paddle/phi/kernels/cholesky_inverse_kernel): given lower L
    with A = L L^T (or upper U with A = U^T U), returns A^{-1} via two
    triangular solves against the identity — no explicit inverse of A is
    formed."""
    x = ensure_tensor(x)

    def f(l):
        if upper:
            l = jnp.swapaxes(l, -1, -2)
        eye = jnp.broadcast_to(jnp.eye(l.shape[-1], dtype=l.dtype),
                               l.shape)
        z = jax.scipy.linalg.solve_triangular(l, eye, lower=True)
        return jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(l, -1, -2), z, lower=False)

    return apply("cholesky_inverse", f, x)


def solve(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply("solve", jnp.linalg.solve, x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)

    def f(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)

    return apply("triangular_solve", f, x, y)


def lstsq(x, y, rcond=None, driver=None, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    out = apply("lstsq", lambda a, b: tuple(jnp.linalg.lstsq(a, b, rcond=rcond)), x, y)
    return tuple(out)


def matrix_power(x, n, name=None):
    return apply("matrix_power", lambda a: jnp.linalg.matrix_power(a, n), ensure_tensor(x))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply("matrix_rank",
                 lambda a: jnp.linalg.matrix_rank(a, rtol=tol),
                 ensure_tensor(x), differentiable=False)


def cond(x, p=None, name=None):
    return apply("cond", lambda a: jnp.linalg.cond(a, p=p), ensure_tensor(x))


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    x = ensure_tensor(x)
    return apply("cov", lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0),
                 x)


def corrcoef(x, rowvar=True, name=None):
    return apply("corrcoef", lambda a: jnp.corrcoef(a, rowvar=rowvar), ensure_tensor(x))


def multi_dot(x, name=None):
    tensors = [ensure_tensor(t) for t in x]
    return apply("multi_dot", lambda *arrs: jnp.linalg.multi_dot(arrs), *tensors)


def cross(x, y, axis=9, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    ax = axis if axis != 9 else (-1 if x._data.shape[-1] == 3 else
                                 next(i for i, s in enumerate(x._data.shape) if s == 3))
    return apply("cross", lambda a, b: jnp.cross(a, b, axis=ax), x, y)


def householder_product(x, tau, name=None):
    """``paddle.linalg.householder_product`` parity: x (*, m, n) holds the
    reflector vectors below the diagonal, tau (*, k) the scaling factors
    (k <= n); returns the FIRST n COLUMNS of Q = H_1 H_2 ... H_k, shape
    (*, m, n) — upstream python/paddle/tensor/linalg.py householder_product
    (the LAPACK orgqr contract), including batched inputs and complex
    v v^H reflectors. The k reflections unroll as a static Python loop
    (k is a compile-time shape; XLA fuses the chain)."""
    x, tau = ensure_tensor(x), ensure_tensor(tau)

    def core(a, t):
        m, n = a.shape
        k = t.shape[0]
        rows = jnp.arange(m)
        q = jnp.eye(m, dtype=a.dtype)
        for i in range(k):
            # v_i = [0]*i + [1] + a[i+1:, i]
            v = jnp.where(rows > i, a[:, i], jnp.zeros((), a.dtype))
            v = v.at[i].set(1)
            q = q - t[i] * (q @ v[:, None]) @ jnp.conj(v)[None, :]
        return q[:, :n]

    def f(a, t):
        batch = a.shape[:-2]
        if not batch:
            return core(a, t)
        fa = a.reshape((-1,) + a.shape[-2:])
        ft = t.reshape((-1, t.shape[-1]))
        out = jax.vmap(core)(fa, ft)
        return out.reshape(batch + out.shape[-2:])

    return apply("householder_product", f, x, tau)


for _n in ("inv", "pinv", "det", "slogdet", "svd", "qr", "eigh", "eig", "eigvals",
           "eigvalsh", "cholesky", "cholesky_solve", "solve", "triangular_solve",
           "lstsq", "matrix_power", "matrix_rank", "cond", "cov", "corrcoef",
           "multi_dot", "cross", "householder_product"):
    register_op(_n, globals()[_n])


def vecdot(x, y, axis=-1, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply("vecdot", lambda a, b: jnp.sum(a * b, axis=axis), x, y)


def matrix_exp(x, name=None):
    x = ensure_tensor(x)

    def f(a):
        if a.ndim == 2:
            return jax.scipy.linalg.expm(a)
        batch = a.reshape((-1,) + a.shape[-2:])
        out = jax.vmap(jax.scipy.linalg.expm)(batch)
        return out.reshape(a.shape)

    return apply("matrix_exp", f, x)


def lu(x, pivot=True, get_infos=False, name=None):
    """LU factorization with 1-based LAPACK pivots (reference: paddle.linalg.lu)."""
    x = ensure_tensor(x)

    def f(a):
        lu_mat, piv = jax.scipy.linalg.lu_factor(a)
        return lu_mat, (piv + 1).astype(jnp.int32)

    lu_mat, piv = apply("lu", f, x)
    if get_infos:
        info = to_tensor(jnp.zeros(x._data.shape[:-2], jnp.int32))
        return lu_mat, piv, info
    return lu_mat, piv


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack an LU factorization into (P, L, U)."""
    x, y = ensure_tensor(x), ensure_tensor(y)
    m, n = int(x._data.shape[-2]), int(x._data.shape[-1])
    k = min(m, n)

    def f2d(a, piv):
        l = jnp.tril(a[:, :k], -1) + jnp.eye(m, k, dtype=a.dtype)
        u = jnp.triu(a[:k, :])
        # replay LAPACK row swaps to build the permutation matrix
        perm = jnp.arange(m)
        for i in range(piv.shape[-1]):
            j = piv[i].astype(jnp.int32) - 1
            pi, pj = perm[i], perm[j]
            perm = perm.at[i].set(pj).at[j].set(pi)
        p = jnp.eye(m, dtype=a.dtype)[perm].T
        return p, l, u

    def f(a, piv):
        if a.ndim == 2:
            return f2d(a, piv)
        batch = a.shape[:-2]
        af = a.reshape((-1,) + a.shape[-2:])
        pf = piv.reshape((-1, piv.shape[-1]))
        p, l, u = jax.vmap(f2d)(af, pf)
        return (p.reshape(batch + p.shape[-2:]),
                l.reshape(batch + l.shape[-2:]),
                u.reshape(batch + u.shape[-2:]))

    p, l, u = apply("lu_unpack", f, x, y, differentiable=False)
    return p, l, u


def ormqr(x, tau, other, left=True, transpose=False, name=None):
    """Multiply ``other`` by Q from a householder factorization."""
    x, tau, other = ensure_tensor(x), ensure_tensor(tau), ensure_tensor(other)

    def f2d(a, t, c):
        m, nr = a.shape[-2], t.shape[-1]
        q = jnp.eye(m, dtype=a.dtype)
        for i in range(nr):
            v = jnp.concatenate([jnp.zeros(i, a.dtype), jnp.ones(1, a.dtype),
                                 a[i + 1:, i]])
            q = q - t[i] * (q @ v[:, None]) @ v[None, :]
        if transpose:
            q = jnp.swapaxes(q, -1, -2)
        return q @ c if left else c @ q

    def f(a, t, c):
        if a.ndim == 2:
            return f2d(a, t, c)
        batch = a.shape[:-2]
        out = jax.vmap(f2d)(a.reshape((-1,) + a.shape[-2:]),
                            t.reshape((-1, t.shape[-1])),
                            c.reshape((-1,) + c.shape[-2:]))
        return out.reshape(batch + out.shape[-2:])

    return apply("ormqr", f, x, tau, other)


for _n in ("vecdot", "matrix_exp", "lu", "lu_unpack", "ormqr"):
    register_op(_n, globals()[_n])
