"""Indexing / gather / scatter / search ops + Tensor.__getitem__/__setitem__.

Parity surface: python/paddle/tensor/manipulation.py + search.py and the phi
gather/scatter kernel family. Static-shape ops lower to XLA gather/scatter;
ops with data-dependent output shapes (masked_select, nonzero, unique) run
eagerly only and raise under ``to_static`` tracing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply, register_tensor_method, _is_tracer
from ._helpers import ensure_tensor, register_op
from ..core.dtype import canonicalize as _canon
_i64 = _canon("int64")

_py_slice = slice


def _reject_dynamic(op_name, *tensors):
    if any(_is_tracer(t._data) for t in tensors):
        raise RuntimeError(
            f"{op_name} has a data-dependent output shape and cannot run under "
            "paddle.jit.to_static / XLA tracing; run it eagerly or use a "
            "masked/padded formulation")


def gather(x, index, axis=None, name=None):
    x, index = ensure_tensor(x), ensure_tensor(index)
    if isinstance(axis, Tensor):
        axis = int(axis._data)
    if axis is None:  # upstream default: gather along axis 0
        axis = 0
    return apply("gather", lambda a, i: jnp.take(a, i.astype(jnp.int32), axis=axis), x, index)


register_op("gather", gather, methods=("gather",))


def gather_nd(x, index, name=None):
    x, index = ensure_tensor(x), ensure_tensor(index)

    def f(a, idx):
        idx = idx.astype(jnp.int32)
        k = idx.shape[-1]
        out = a[tuple(jnp.moveaxis(idx, -1, 0))]
        return out

    return apply("gather_nd", f, x, index)


register_op("gather_nd", gather_nd, methods=("gather_nd",))


def scatter(x, index, updates, overwrite=True, name=None):
    x, index, updates = ensure_tensor(x), ensure_tensor(index), ensure_tensor(updates)

    def f(a, i, u):
        i = i.astype(jnp.int32).reshape(-1)
        if overwrite:
            return a.at[i].set(u)
        # paddle overwrite=False: zero the rows then accumulate
        zeroed = a.at[i].set(jnp.zeros_like(u))
        return zeroed.at[i].add(u)

    return apply("scatter", f, x, index, updates)


register_op("scatter", scatter, methods=("scatter",), inplace_method="scatter_")


def scatter_nd_add(x, index, updates, name=None):
    x, index, updates = ensure_tensor(x), ensure_tensor(index), ensure_tensor(updates)

    def f(a, i, u):
        i = i.astype(jnp.int32)
        return a.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)

    return apply("scatter_nd_add", f, x, index, updates)


register_op("scatter_nd_add", scatter_nd_add, methods=("scatter_nd_add",))


def scatter_nd(index, updates, shape, name=None):
    index, updates = ensure_tensor(index), ensure_tensor(updates)
    shape = tuple(int(s) for s in shape)

    def f(i, u):
        z = jnp.zeros(shape, u.dtype)
        return z.at[tuple(jnp.moveaxis(i.astype(jnp.int32), -1, 0))].add(u)

    return apply("scatter_nd", f, index, updates)


register_op("scatter_nd", scatter_nd)


def index_select(x, index, axis=0, name=None):
    x, index = ensure_tensor(x), ensure_tensor(index)
    return apply("index_select",
                 lambda a, i: jnp.take(a, i.astype(jnp.int32), axis=axis), x, index)


register_op("index_select", index_select, methods=("index_select",))


def index_sample(x, index):
    x, index = ensure_tensor(x), ensure_tensor(index)
    return apply("index_sample",
                 lambda a, i: jnp.take_along_axis(a, i.astype(jnp.int32), axis=1), x, index)


register_op("index_sample", index_sample, methods=("index_sample",))


def index_add(x, index, axis, value, name=None):
    x, index, value = ensure_tensor(x), ensure_tensor(index), ensure_tensor(value)

    def f(a, i, v):
        i = i.astype(jnp.int32)
        moved = jnp.moveaxis(a, axis, 0)
        vmoved = jnp.moveaxis(v, axis, 0)
        out = moved.at[i].add(vmoved)
        return jnp.moveaxis(out, 0, axis)

    return apply("index_add", f, x, index, value)


register_op("index_add", index_add, methods=("index_add",), inplace_method="index_add_")


def index_put(x, indices, value, accumulate=False, name=None):
    x = ensure_tensor(x)
    value = ensure_tensor(value)
    idx_tensors = [ensure_tensor(i) for i in indices]

    def f(a, v, *idx):
        idx = tuple(i if i.dtype == jnp.bool_ else i.astype(jnp.int32) for i in idx)
        if accumulate:
            return a.at[idx].add(v)
        return a.at[idx].set(v)

    return apply("index_put", f, x, value, *idx_tensors)


register_op("index_put", index_put, methods=("index_put",), inplace_method="index_put_")


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    arr, indices = ensure_tensor(arr), ensure_tensor(indices)
    return apply("take_along_axis",
                 lambda a, i: jnp.take_along_axis(a, i.astype(jnp.int32), axis=axis),
                 arr, indices)


register_op("take_along_axis", take_along_axis, methods=("take_along_axis",))


def put_along_axis(arr, indices, values, axis, reduce="assign", include_self=True,
                   broadcast=True, name=None):
    arr, indices = ensure_tensor(arr), ensure_tensor(indices)
    values = ensure_tensor(values)

    def f(a, i, v):
        i = i.astype(jnp.int32)
        v = jnp.broadcast_to(v, i.shape).astype(a.dtype)
        if reduce == "assign":
            return jnp.put_along_axis(a, i, v, axis=axis, inplace=False)
        out = jnp.moveaxis(a, axis, -1)
        idx = jnp.moveaxis(i, axis, -1)
        val = jnp.moveaxis(v, axis, -1)
        if reduce in ("add", "sum"):
            return jnp.moveaxis(_scatter_last(out, idx, val, "add"), -1, axis)
        if reduce in ("mul", "multiply"):
            return jnp.moveaxis(_scatter_last(out, idx, val, "mul"), -1, axis)
        raise ValueError(f"unsupported reduce {reduce}")

    return apply("put_along_axis", f, arr, indices, values)


def _scatter_last(out, idx, val, mode):
    """scatter along last axis with batch dims via vmap."""
    def one(o, i, v):
        return o.at[i].add(v) if mode == "add" else o.at[i].multiply(v)
    flat_o = out.reshape(-1, out.shape[-1])
    flat_i = idx.reshape(-1, idx.shape[-1])
    flat_v = val.reshape(-1, val.shape[-1])
    res = jax.vmap(one)(flat_o, flat_i, flat_v)
    return res.reshape(out.shape)


register_op("put_along_axis", put_along_axis, methods=("put_along_axis",))


def where(condition, x=None, y=None, name=None):
    condition = ensure_tensor(condition)
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply("where", lambda c, a, b: jnp.where(c, a, b), condition, x, y)


register_op("where", where, methods=("where",))


def masked_select(x, mask, name=None):
    x, mask = ensure_tensor(x), ensure_tensor(mask)
    _reject_dynamic("masked_select", x, mask)
    return Tensor(x._data[np.asarray(mask._data)], stop_gradient=x.stop_gradient)


register_op("masked_select", masked_select, methods=("masked_select",))


def masked_fill(x, mask, value, name=None):
    x, mask = ensure_tensor(x), ensure_tensor(mask)
    if isinstance(value, Tensor):
        return apply("masked_fill", lambda a, m, v: jnp.where(m, v.astype(a.dtype), a),
                     x, mask, value)
    return apply("masked_fill", lambda a, m: jnp.where(m, jnp.asarray(value, a.dtype), a),
                 x, mask)


register_op("masked_fill", masked_fill, methods=("masked_fill",), inplace_method="masked_fill_")


def nonzero(x, as_tuple=False):
    x = ensure_tensor(x)
    _reject_dynamic("nonzero", x)
    idx = np.nonzero(np.asarray(x._data))
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i)) for i in idx)
    return Tensor(jnp.asarray(np.stack(idx, axis=-1).astype(np.int32)))


register_op("nonzero", nonzero, methods=("nonzero",))


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)
    _reject_dynamic("unique", x)
    res = np.unique(np.asarray(x._data), return_index=return_index,
                    return_inverse=return_inverse, return_counts=return_counts,
                    axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


register_op("unique", unique, methods=("unique",))


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    x = ensure_tensor(x)
    _reject_dynamic("unique_consecutive", x)
    a = np.asarray(x._data)
    if axis is None:
        a = a.reshape(-1)
        keep = np.concatenate([[True], a[1:] != a[:-1]])
        out = a[keep]
    else:
        raise NotImplementedError("unique_consecutive with axis")
    outs = [Tensor(jnp.asarray(out))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        outs.append(Tensor(jnp.asarray(inv.astype(np.int32))))
    if return_counts:
        idx = np.nonzero(keep)[0]
        cnt = np.diff(np.concatenate([idx, [len(a)]]))
        outs.append(Tensor(jnp.asarray(cnt.astype(np.int32))))
    return outs[0] if len(outs) == 1 else tuple(outs)


register_op("unique_consecutive", unique_consecutive)


def sort(x, axis=-1, descending=False, stable=False, name=None):
    x = ensure_tensor(x)

    def f(a):
        r = jnp.sort(a, axis=axis, stable=stable)
        return jnp.flip(r, axis=axis) if descending else r

    return apply("sort", f, x)


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    x = ensure_tensor(x)

    def f(a):
        r = jnp.argsort(a, axis=axis, stable=stable)
        return (jnp.flip(r, axis=axis) if descending else r).astype(_i64)

    return apply("argsort", f, x, differentiable=False)


register_op("sort", sort, methods=("sort",))
register_op("argsort", argsort, methods=("argsort",))


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    x = ensure_tensor(x)
    if isinstance(k, Tensor):
        k = int(k._data)
    if axis is None:  # upstream default: last axis
        axis = -1

    def f(a):
        moved = jnp.moveaxis(a, axis, -1)
        src = moved if largest else -moved
        vals, idx = jax.lax.top_k(src, k)
        if not largest:
            vals = -vals
        return (jnp.moveaxis(vals, -1, axis), jnp.moveaxis(idx, -1, axis).astype(_i64))

    return apply("topk", f, x)


register_op("topk", topk, methods=("topk",))


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = ensure_tensor(x)

    def f(a):
        moved = jnp.moveaxis(a, axis, -1)
        s = jnp.sort(moved, axis=-1)
        si = jnp.argsort(moved, axis=-1)
        v = s[..., k - 1]
        i = si[..., k - 1].astype(_i64)
        if keepdim:
            v = jnp.expand_dims(v, axis)
            i = jnp.expand_dims(i, axis)
        return v, i

    return apply("kthvalue", f, x)


register_op("kthvalue", kthvalue, methods=("kthvalue",))


def mode(x, axis=-1, keepdim=False, name=None):
    x = ensure_tensor(x)

    def f(a):
        moved = jnp.moveaxis(a, axis, -1)
        sorted_a = jnp.sort(moved, axis=-1)
        n = sorted_a.shape[-1]
        same = sorted_a[..., 1:] == sorted_a[..., :-1]
        run = jnp.concatenate([jnp.zeros_like(same[..., :1]), same], axis=-1)
        # run length ending at each position
        def scan_fn(carry, x_t):
            c = jnp.where(x_t, carry + 1, 0)
            return c, c
        _, runlens = jax.lax.scan(scan_fn, jnp.zeros(moved.shape[:-1], jnp.int32),
                                  jnp.moveaxis(run, -1, 0))
        runlens = jnp.moveaxis(runlens, 0, -1)
        best = jnp.argmax(runlens, axis=-1)
        vals = jnp.take_along_axis(sorted_a, best[..., None], axis=-1)[..., 0]
        # index of first occurrence in original array
        eq = moved == vals[..., None]
        idx = jnp.argmax(eq, axis=-1).astype(_i64)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            idx = jnp.expand_dims(idx, axis)
        return vals, idx

    return apply("mode", f, x)


register_op("mode", mode, methods=("mode",))


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    sorted_sequence, values = ensure_tensor(sorted_sequence), ensure_tensor(values)

    def f(s, v):
        side = "right" if right else "left"
        if s.ndim == 1:
            r = jnp.searchsorted(s, v, side=side)
        else:
            flat_s = s.reshape(-1, s.shape[-1])
            flat_v = v.reshape(-1, v.shape[-1])
            r = jax.vmap(lambda ss, vv: jnp.searchsorted(ss, vv, side=side))(flat_s, flat_v)
            r = r.reshape(v.shape)
        return r.astype(jnp.int32 if out_int32 else _i64)

    return apply("searchsorted", f, sorted_sequence, values, differentiable=False)


register_op("searchsorted", searchsorted)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


register_op("bucketize", bucketize, methods=("bucketize",))


def one_hot(x, num_classes, name=None):
    x = ensure_tensor(x)
    return apply("one_hot",
                 lambda a: jax.nn.one_hot(a.astype(jnp.int32), num_classes,
                                          dtype=jnp.float32), x, differentiable=False)


register_op("one_hot", one_hot)


def bincount(x, weights=None, minlength=0, name=None):
    x = ensure_tensor(x)
    _reject_dynamic("bincount", x)
    n = max(int(np.asarray(x._data).max(initial=-1)) + 1, minlength)
    if weights is not None:
        weights = ensure_tensor(weights)
        return apply("bincount",
                     lambda a, w: jnp.bincount(a.astype(jnp.int32), weights=w, length=n),
                     x, weights)
    return apply("bincount",
                 lambda a: jnp.bincount(a.astype(jnp.int32), length=n).astype(_i64),
                 x, differentiable=False)


register_op("bincount", bincount, methods=("bincount",))


def histogram(input, bins=100, min=0, max=0, name=None):
    input = ensure_tensor(input)
    lo, hi = float(min), float(max)

    def f(a):
        l, h = (a.min(), a.max()) if lo == 0 and hi == 0 else (lo, hi)
        hist, _ = jnp.histogram(a, bins=bins, range=(l, h))
        return hist.astype(_i64)

    return apply("histogram", f, input, differentiable=False)


register_op("histogram", histogram, methods=("histogram",))


def histogramdd(x, bins=10, ranges=None, density: bool = False, weights=None,
                name=None):
    """N-dimensional histogram (reference: paddle.histogramdd over the last
    dim of an (N, D) sample matrix). Returns (hist, list-of-edges)."""
    x = ensure_tensor(x)
    w = ensure_tensor(weights) if weights is not None else None
    d = int(x._data.shape[-1])
    if ranges is not None and len(ranges) == 2 * d and not hasattr(
            ranges[0], "__len__"):
        # paddle passes a FLAT [lo0, hi0, lo1, hi1, ...] list; numpy/jax
        # want per-dimension pairs
        ranges = [(float(ranges[2 * i]), float(ranges[2 * i + 1]))
                  for i in range(d)]

    def f(a, *maybe_w):
        ww = maybe_w[0] if maybe_w else None
        hist, edges = jnp.histogramdd(a, bins=bins, range=ranges,
                                      density=density, weights=ww)
        return (hist,) + tuple(edges)

    args = (x, w) if w is not None else (x,)
    out = apply("histogramdd", f, *args, differentiable=False)
    return out[0], list(out[1:])


register_op("histogramdd", histogramdd, methods=("histogramdd",))


def vander(x, n=None, increasing: bool = False, name=None):
    """Vandermonde matrix (reference: paddle.vander — output keeps the
    input dtype, integer powers stay exact)."""
    x = ensure_tensor(x)
    cols = int(x._data.shape[0]) if n is None else int(n)

    def f(a):
        p = jnp.arange(cols, dtype=a.dtype)
        out = a[:, None] ** p[None, :]
        if not increasing:
            out = out[:, ::-1]
        return out

    return apply("vander", f, x)


register_op("vander", vander, methods=("vander",))


# --- Tensor indexing ---------------------------------------------------------

def _convert_index(item):
    if isinstance(item, Tensor):
        return item._data
    if isinstance(item, (list, np.ndarray)):
        return jnp.asarray(np.asarray(item))
    if isinstance(item, tuple):
        return tuple(_convert_index(i) for i in item)
    return item


def _getitem(self, item):
    idx = _convert_index(item)
    # dynamic boolean mask on concrete data -> eager numpy path
    return apply("getitem", lambda a: a[idx], self)


def _setitem(self, item, value):
    idx = _convert_index(item)
    if isinstance(value, Tensor):
        out = apply("setitem", lambda a, v: a.at[idx].set(v.astype(a.dtype)), self, value)
    else:
        out = apply("setitem",
                    lambda a: a.at[idx].set(jnp.asarray(value).astype(a.dtype)), self)
    self._rebind(out)


register_tensor_method("__getitem__", _getitem)
register_tensor_method("__setitem__", _setitem)


def masked_scatter(x, mask, value, name=None):
    """Fill masked positions of x from value's leading elements in order
    (reference: paddle.masked_scatter)."""
    x = ensure_tensor(x)
    mask = ensure_tensor(mask)
    value = ensure_tensor(value)
    if not _is_tracer(mask._data):
        needed = int(jnp.sum(jnp.broadcast_to(mask._data, x._data.shape)))
        if needed > value._data.size:
            raise ValueError(
                f"masked_scatter: mask selects {needed} elements but value "
                f"has only {value._data.size} (reference raises too)")

    def f(a, m, v):
        m = jnp.broadcast_to(m, a.shape)
        flat_m = m.reshape(-1)
        # position of each masked slot among masked slots
        ord_idx = jnp.cumsum(flat_m.astype(jnp.int32)) - 1
        src = v.reshape(-1)[jnp.clip(ord_idx, 0, v.size - 1)]
        return jnp.where(flat_m, src.astype(a.dtype),
                         a.reshape(-1)).reshape(a.shape)

    return apply("masked_scatter", f, x, mask, value)


def index_fill(x, index, axis, value, name=None):
    """Fill rows/slices selected by index along axis (reference:
    paddle.index_fill)."""
    x = ensure_tensor(x)
    index = ensure_tensor(index)
    vconst = float(value) if not isinstance(value, Tensor) else None
    args = [x, index] + ([ensure_tensor(value)] if vconst is None else [])

    def f(a, idx, *maybe_v):
        v = maybe_v[0] if maybe_v else jnp.asarray(vconst, a.dtype)
        mask1d = jnp.zeros((a.shape[axis],), bool).at[idx].set(True)
        shape = [1] * a.ndim
        shape[axis] = a.shape[axis]
        return jnp.where(mask1d.reshape(shape), v.astype(a.dtype), a)

    return apply("index_fill", f, *args)


register_op("masked_scatter", masked_scatter, methods=("masked_scatter",))
register_op("index_fill", index_fill, methods=("index_fill",))
