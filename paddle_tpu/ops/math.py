"""Elementwise math ops (unary, binary, logic, bitwise) + Tensor operators.

Parity surface: upstream paddle/phi/kernels/{cpu,gpu}/ elementwise & unary
kernels and python/paddle/tensor/math.py. Each op is one jnp call dispatched
through ``apply`` so autograd/AMP/tracing come for free; XLA fuses chains of
these into single kernels on TPU (the reference needs CINN for that).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply, register_tensor_method, to_tensor
from ._helpers import ensure_tensor, make_binary, make_unary, register_op

# --- unary ------------------------------------------------------------------
abs = make_unary("abs", jnp.abs, methods=("abs", "__abs__"))
acos = make_unary("acos", jnp.arccos)
acosh = make_unary("acosh", jnp.arccosh)
asin = make_unary("asin", jnp.arcsin)
asinh = make_unary("asinh", jnp.arcsinh)
atan = make_unary("atan", jnp.arctan)
atanh = make_unary("atanh", jnp.arctanh)
ceil = make_unary("ceil", jnp.ceil, inplace="ceil_")
cos = make_unary("cos", jnp.cos)
cosh = make_unary("cosh", jnp.cosh)
deg2rad = make_unary("deg2rad", jnp.deg2rad)
rad2deg = make_unary("rad2deg", jnp.rad2deg)
digamma = make_unary("digamma", jax.scipy.special.digamma)
erf = make_unary("erf", jax.scipy.special.erf)
erfinv = make_unary("erfinv", jax.scipy.special.erfinv, inplace="erfinv_")
exp = make_unary("exp", jnp.exp, inplace="exp_")
expm1 = make_unary("expm1", jnp.expm1)
floor = make_unary("floor", jnp.floor, inplace="floor_")
frac = make_unary("frac", lambda x: x - jnp.trunc(x))
i0 = make_unary("i0", jax.scipy.special.i0)
i1 = make_unary("i1", jax.scipy.special.i1)
lgamma = make_unary("lgamma", jax.scipy.special.gammaln)
log = make_unary("log", jnp.log)
log10 = make_unary("log10", jnp.log10)
log1p = make_unary("log1p", jnp.log1p)
log2 = make_unary("log2", jnp.log2)
neg = make_unary("neg", jnp.negative, methods=("neg", "__neg__"))
reciprocal = make_unary("reciprocal", jnp.reciprocal, inplace="reciprocal_")
round = make_unary("round", jnp.round, inplace="round_")
rsqrt = make_unary("rsqrt", jax.lax.rsqrt, inplace="rsqrt_")
sigmoid = make_unary("sigmoid", jax.nn.sigmoid)
sign = make_unary("sign", jnp.sign)
sgn = make_unary("sgn", jnp.sign)
sin = make_unary("sin", jnp.sin)
sinh = make_unary("sinh", jnp.sinh)
sqrt = make_unary("sqrt", jnp.sqrt, inplace="sqrt_")
square = make_unary("square", jnp.square)
tan = make_unary("tan", jnp.tan)
tanh = make_unary("tanh", jnp.tanh, inplace="tanh_")
def trunc(input, name=None):  # upstream names the arg ``input``
    return apply("trunc", jnp.trunc, ensure_tensor(input))


register_op("trunc", trunc, methods=("trunc",), inplace_method="trunc_")
angle = make_unary("angle", jnp.angle)
conj = make_unary("conj", jnp.conj)
real = make_unary("real", jnp.real)
imag = make_unary("imag", jnp.imag)

isnan = make_unary("isnan", jnp.isnan, differentiable=False)
isinf = make_unary("isinf", jnp.isinf, differentiable=False)
isfinite = make_unary("isfinite", jnp.isfinite, differentiable=False)
logical_not = make_unary("logical_not", jnp.logical_not, differentiable=False)
bitwise_not = make_unary("bitwise_not", jnp.bitwise_not, differentiable=False)

# --- binary -----------------------------------------------------------------
add = make_binary("add", jnp.add, inplace="add_")
subtract = make_binary("subtract", jnp.subtract, inplace="subtract_")
multiply = make_binary("multiply", jnp.multiply, inplace="multiply_")
divide = make_binary("divide", jnp.true_divide, inplace="divide_")
floor_divide = make_binary("floor_divide", jnp.floor_divide)
mod = make_binary("mod", jnp.mod, methods=("mod", "remainder"))
remainder = mod
pow = make_binary("pow", jnp.power, methods=("pow",))
maximum = make_binary("maximum", jnp.maximum)
minimum = make_binary("minimum", jnp.minimum)
fmax = make_binary("fmax", jnp.fmax)
fmin = make_binary("fmin", jnp.fmin)
atan2 = make_binary("atan2", jnp.arctan2)
hypot = make_binary("hypot", jnp.hypot)
logaddexp = make_binary("logaddexp", jnp.logaddexp)
nextafter = make_binary("nextafter", jnp.nextafter)
copysign = make_binary("copysign", jnp.copysign)
heaviside = make_binary("heaviside", jnp.heaviside)
gcd = make_binary("gcd", jnp.gcd, differentiable=False)
lcm = make_binary("lcm", jnp.lcm, differentiable=False)

logical_and = make_binary("logical_and", jnp.logical_and, differentiable=False)
logical_or = make_binary("logical_or", jnp.logical_or, differentiable=False)
logical_xor = make_binary("logical_xor", jnp.logical_xor, differentiable=False)
bitwise_and = make_binary("bitwise_and", jnp.bitwise_and, differentiable=False)
bitwise_or = make_binary("bitwise_or", jnp.bitwise_or, differentiable=False)
bitwise_xor = make_binary("bitwise_xor", jnp.bitwise_xor, differentiable=False)
bitwise_left_shift = make_binary("bitwise_left_shift", jnp.left_shift, differentiable=False)
bitwise_right_shift = make_binary("bitwise_right_shift", jnp.right_shift, differentiable=False)

equal = make_binary("equal", jnp.equal, differentiable=False)
not_equal = make_binary("not_equal", jnp.not_equal, differentiable=False)
greater_than = make_binary("greater_than", jnp.greater, differentiable=False)
greater_equal = make_binary("greater_equal", jnp.greater_equal, differentiable=False)
less_than = make_binary("less_than", jnp.less, differentiable=False)
less_equal = make_binary("less_equal", jnp.less_equal, differentiable=False)


def equal_all(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply("equal_all", lambda a, b: jnp.array_equal(a, b), x, y,
                 differentiable=False)


register_op("equal_all", equal_all, methods=("equal_all",))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply("isclose", lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                 equal_nan=equal_nan), x, y, differentiable=False)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply("allclose", lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                 equal_nan=equal_nan), x, y, differentiable=False)


register_op("isclose", isclose, methods=("isclose",))
register_op("allclose", allclose, methods=("allclose",))


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    """paddle.scale parity (upstream phi scale kernel)."""
    x = ensure_tensor(x)
    s, b = scale, bias
    if isinstance(s, Tensor):
        def f(a, sv):
            return a * sv + b if bias_after_scale else (a + b) * sv
        out = apply("scale", f, x, s)
    else:
        def f(a):
            return a * s + b if bias_after_scale else (a + b) * s
        out = apply("scale", f, x)
    return out


register_op("scale", scale, methods=("scale",), inplace_method="scale_")


def lerp(x, y, weight, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    if isinstance(weight, Tensor):
        return apply("lerp", lambda a, b, w: a + w * (b - a), x, y, weight)
    return apply("lerp", lambda a, b: a + weight * (b - a), x, y)


register_op("lerp", lerp, methods=("lerp",), inplace_method="lerp_")


def clip(x, min=None, max=None, name=None):
    x = ensure_tensor(x)
    lo = float(min) if min is not None and not isinstance(min, Tensor) else min
    hi = float(max) if max is not None and not isinstance(max, Tensor) else max
    if isinstance(lo, Tensor) or isinstance(hi, Tensor):
        lo_t = lo if isinstance(lo, Tensor) else to_tensor(lo if lo is not None else -jnp.inf)
        hi_t = hi if isinstance(hi, Tensor) else to_tensor(hi if hi is not None else jnp.inf)
        return apply("clip", lambda a, l, h: jnp.clip(a, l, h), x, lo_t, hi_t)
    return apply("clip", lambda a: jnp.clip(a, lo, hi), x)


register_op("clip", clip, methods=("clip",), inplace_method="clip_")


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    x = ensure_tensor(x)
    return apply("stanh", lambda a: scale_b * jnp.tanh(scale_a * a), x)


register_op("stanh", stanh, methods=("stanh",))


def multiplex(inputs, index, name=None):
    ts = [ensure_tensor(t) for t in inputs]
    index = ensure_tensor(index)

    def f(idx, *xs):
        stacked = jnp.stack(xs, axis=0)
        return jnp.take_along_axis(
            stacked, idx.reshape(1, -1, *([1] * (stacked.ndim - 2))), axis=0)[0]

    return apply("multiplex", f, index, *ts)


register_op("multiplex", multiplex)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    x = ensure_tensor(x)
    return apply("nan_to_num",
                 lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf), x)


register_op("nan_to_num", nan_to_num, methods=("nan_to_num",))


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y = ensure_tensor(y)
    if x is not None:
        return apply("trapezoid", lambda a, b: jnp.trapezoid(a, b, axis=axis),
                     y, ensure_tensor(x))
    return apply("trapezoid", lambda a: jnp.trapezoid(a, dx=dx or 1.0, axis=axis), y)


register_op("trapezoid", trapezoid)

# --- Tensor dunder operators -------------------------------------------------

def _install_operators():
    def rev(fn):
        def r(self, other):
            return fn(to_tensor(other) if not isinstance(other, Tensor) else other, self)
        return r

    ops_map = {
        "__add__": add, "__radd__": rev(add),
        "__sub__": subtract, "__rsub__": rev(subtract),
        "__mul__": multiply, "__rmul__": rev(multiply),
        "__truediv__": divide, "__rtruediv__": rev(divide),
        "__floordiv__": floor_divide, "__rfloordiv__": rev(floor_divide),
        "__mod__": mod, "__rmod__": rev(mod),
        "__pow__": pow, "__rpow__": rev(pow),
        "__matmul__": None,  # installed by linalg module
        "__eq__": equal, "__ne__": not_equal,
        "__lt__": less_than, "__le__": less_equal,
        "__gt__": greater_than, "__ge__": greater_equal,
        "__and__": bitwise_and, "__or__": bitwise_or, "__xor__": bitwise_xor,
        "__invert__": bitwise_not,
        "__lshift__": bitwise_left_shift, "__rshift__": bitwise_right_shift,
    }
    for name, fn in ops_map.items():
        if fn is not None:
            register_tensor_method(name, fn)
    register_tensor_method("__pos__", lambda self: self)


_install_operators()


def frexp(x, name=None):
    """Mantissa/exponent decomposition (reference: paddle.frexp)."""
    x = ensure_tensor(x)

    def f(a):
        m, e = jnp.frexp(a)
        return m, e.astype(jnp.int32)

    return apply("frexp", f, x, differentiable=False)


def diff(x, n: int = 1, axis: int = -1, prepend=None, append=None, name=None):
    """n-th forward difference (reference: paddle.diff)."""
    x = ensure_tensor(x)
    extras = [t for t in (prepend, append) if t is not None]

    def f(a, *pa):
        idx = 0
        pre = pa[idx] if prepend is not None else None
        idx += prepend is not None
        app = pa[idx] if append is not None else None
        return jnp.diff(a, n=n, axis=axis, prepend=pre, append=app)

    return apply("diff", f, x, *[ensure_tensor(t) for t in extras])


def trapezoid(y, x=None, dx=None, axis: int = -1, name=None):
    """Trapezoidal integration (reference: paddle.trapezoid)."""
    y = ensure_tensor(y)
    if x is not None:
        xt = ensure_tensor(x)
        return apply("trapezoid",
                     lambda a, b: jnp.trapezoid(a, b, axis=axis), y, xt)
    d = 1.0 if dx is None else float(dx)
    return apply("trapezoid", lambda a: jnp.trapezoid(a, dx=d, axis=axis), y)


def cumulative_trapezoid(y, x=None, dx=None, axis: int = -1, name=None):
    """Cumulative trapezoid (reference: paddle.cumulative_trapezoid)."""
    y = ensure_tensor(y)

    def core(a, b=None, d=1.0):
        sl = [slice(None)] * a.ndim
        sl_lo, sl_hi = list(sl), list(sl)
        sl_lo[axis] = slice(None, -1)
        sl_hi[axis] = slice(1, None)
        avg = (a[tuple(sl_lo)] + a[tuple(sl_hi)]) * 0.5
        if b is not None:
            step = b[tuple(sl_hi)] - b[tuple(sl_lo)]
        else:
            step = d
        return jnp.cumsum(avg * step, axis=axis)

    if x is not None:
        return apply("cumulative_trapezoid", lambda a, b: core(a, b),
                     y, ensure_tensor(x))
    d = 1.0 if dx is None else float(dx)
    return apply("cumulative_trapezoid", lambda a: core(a, d=d), y)


def cov(x, rowvar: bool = True, ddof: bool = True, fweights=None,
        aweights=None, name=None):
    """Covariance matrix (reference: paddle.linalg.cov)."""
    x = ensure_tensor(x)
    extras = [t for t in (fweights, aweights) if t is not None]

    def f(a, *wa):
        idx = 0
        fw = wa[idx] if fweights is not None else None
        idx += fweights is not None
        aw = wa[idx] if aweights is not None else None
        return jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0,
                       fweights=fw, aweights=aw)

    return apply("cov", f, x, *[ensure_tensor(t) for t in extras])


def corrcoef(x, rowvar: bool = True, name=None):
    x = ensure_tensor(x)
    return apply("corrcoef", lambda a: jnp.corrcoef(a, rowvar=rowvar), x)


def tensordot(x, y, axes=2, name=None):
    """Generalized tensor contraction (reference: paddle.tensordot)."""
    x, y = ensure_tensor(x), ensure_tensor(y)
    if isinstance(axes, (list, tuple)):
        if len(axes) == 2 and all(isinstance(a, (list, tuple)) for a in axes):
            ax = tuple(tuple(a) for a in axes)
        else:
            # paddle's flat form: contract THESE axes of both tensors
            flat = tuple(int(a) for a in axes)
            ax = (flat, flat)
    else:
        ax = axes
    return apply("tensordot", lambda a, b: jnp.tensordot(a, b, axes=ax), x, y)


register_op("frexp", frexp, methods=("frexp",))
register_op("diff", diff, methods=("diff",))
register_op("trapezoid", trapezoid, methods=("trapezoid",))
register_op("cumulative_trapezoid", cumulative_trapezoid,
            methods=("cumulative_trapezoid",))
register_op("cov", cov, methods=("cov",))
register_op("corrcoef", corrcoef, methods=("corrcoef",))
register_op("tensordot", tensordot, methods=("tensordot",))
