"""Op library: importing this package installs the full op surface onto the
``paddle_tpu`` namespace and the Tensor method table.

Analogue of the reference's kernel registration pass (upstream: the
PD_REGISTER_KERNEL expansions + generated python bindings): ``OP_REGISTRY``
maps op name -> callable.
"""

from ._helpers import OP_REGISTRY, register_op  # noqa: F401

from . import math  # noqa: F401
from . import math_ext  # noqa: F401
from . import reduce  # noqa: F401
from . import manipulation  # noqa: F401
from . import creation  # noqa: F401
from . import indexing  # noqa: F401
from . import linalg  # noqa: F401
from . import activation  # noqa: F401
from . import conv_pool  # noqa: F401
from . import nn_ops  # noqa: F401
from . import nn_ext  # noqa: F401
from . import loss_ops  # noqa: F401
from . import vision  # noqa: F401
from . import array  # noqa: F401
from . import math_ext2  # noqa: F401  (last: aliases earlier registrations)
from . import math_ext4  # noqa: F401  (wave 4: trace/view/polar/pdist/...)
