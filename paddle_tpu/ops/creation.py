"""Tensor creation ops (deterministic + random).

Parity surface: python/paddle/tensor/creation.py + random.py. Random ops draw
from the global splittable PRNG (core/random.py) so they are reproducible and
functionalize under ``to_static``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as _dtype
from ..core.random import default_generator
from ..core.tensor import Tensor, apply, to_tensor
from ._helpers import ensure_tensor, register_op


def _shape_tuple(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._data) if isinstance(s, Tensor) else int(s) for s in shape)


def _dt(dtype, default=None):
    d = _dtype.convert_dtype(dtype)
    if d is None:
        d = default or _dtype.get_default_dtype()
    return _dtype.canonicalize(d)


def zeros(shape, dtype=None, name=None):
    return to_tensor(jnp.zeros(_shape_tuple(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return to_tensor(jnp.ones(_shape_tuple(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        arr = jnp.full(_shape_tuple(shape), fill_value)
        if arr.dtype == jnp.float64:
            arr = arr.astype(_dtype.get_default_dtype())
        return to_tensor(arr)
    return to_tensor(jnp.full(_shape_tuple(shape), fill_value, _dt(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    x = ensure_tensor(x)
    return apply("zeros_like", lambda a: jnp.zeros_like(a, dtype=_dtype.canonicalize(dtype)),
                 x, differentiable=False)


def ones_like(x, dtype=None, name=None):
    x = ensure_tensor(x)
    return apply("ones_like", lambda a: jnp.ones_like(a, dtype=_dtype.canonicalize(dtype)),
                 x, differentiable=False)


def full_like(x, fill_value, dtype=None, name=None):
    x = ensure_tensor(x)
    return apply("full_like",
                 lambda a: jnp.full_like(a, fill_value, dtype=_dtype.canonicalize(dtype)),
                 x, differentiable=False)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v
    start, end, step = val(start), val(end), val(step)
    if end is None:
        start, end = 0, start
    arr = jnp.arange(start, end, step, dtype=_dtype.canonicalize(dtype))
    if dtype is None and arr.dtype == jnp.float64:
        arr = arr.astype(_dtype.get_default_dtype())
    return to_tensor(arr)


def linspace(start, stop, num, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v
    arr = jnp.linspace(val(start), val(stop), int(val(num)),
                       dtype=_dt(dtype, _dtype.float32))
    return to_tensor(arr)


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v
    arr = jnp.logspace(val(start), val(stop), int(val(num)), base=val(base),
                       dtype=_dt(dtype, _dtype.float32))
    return to_tensor(arr)


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return to_tensor(jnp.eye(int(num_rows),
                             int(num_columns) if num_columns is not None else None,
                             dtype=_dt(dtype)))


def tril_indices(row, col, offset=0, dtype="int64", name=None):
    r, c = jnp.tril_indices(row, k=offset, m=col)
    return to_tensor(jnp.stack([r, c]).astype(_dt(dtype, _dtype.int64)))


def triu_indices(row, col=None, offset=0, dtype="int64", name=None):
    r, c = jnp.triu_indices(row, k=offset, m=col or row)
    return to_tensor(jnp.stack([r, c]).astype(_dt(dtype, _dtype.int64)))


def clone(x, name=None):
    return ensure_tensor(x).clone()


def assign(x, output=None):
    x = ensure_tensor(x) if not isinstance(x, (np.ndarray, list, tuple, int, float)) else to_tensor(np.asarray(x))
    out = apply("assign", jnp.copy, x)
    if output is not None:
        output._rebind(out)
        return output
    return out


def complex(real, imag, name=None):
    real, imag = ensure_tensor(real), ensure_tensor(imag)
    return apply("complex", jax.lax.complex, real, imag)


# --- random -----------------------------------------------------------------

def _key():
    return default_generator.split_key()


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype=dtype, min=0.0, max=1.0)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    d = _dt(dtype)
    key = _key()
    arr = jax.random.uniform(key, _shape_tuple(shape), dtype=d,
                             minval=float(min), maxval=float(max))
    return Tensor(arr)


def randn(shape, dtype=None, name=None):
    return standard_normal(shape, dtype)


def standard_normal(shape, dtype=None, name=None):
    d = _dt(dtype)
    return Tensor(jax.random.normal(_key(), _shape_tuple(shape), dtype=d))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(np.shape(m), np.shape(s))
        arr = jax.random.normal(_key(), shp, dtype=_dtype.get_default_dtype())
        return Tensor(arr * s + m)
    arr = jax.random.normal(_key(), _shape_tuple(shape), dtype=_dtype.get_default_dtype())
    return Tensor(arr * std + mean)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    arr = jax.random.randint(_key(), _shape_tuple(shape), int(low), int(high),
                             dtype=_dt(dtype, _dtype.int64))
    return Tensor(arr)


def randint_like(x, low=0, high=None, dtype=None, name=None):
    x = ensure_tensor(x)
    return randint(low, high, tuple(x._data.shape), dtype or x.dtype)


def randperm(n, dtype="int64", name=None):
    arr = jax.random.permutation(_key(), int(n)).astype(_dt(dtype, _dtype.int64))
    return Tensor(arr)


def bernoulli(x, name=None):
    x = ensure_tensor(x)
    key = _key()
    return apply("bernoulli",
                 lambda p: jax.random.bernoulli(key, p).astype(p.dtype), x,
                 differentiable=False)


def multinomial(x, num_samples=1, replacement=False, name=None):
    x = ensure_tensor(x)
    key = _key()

    def f(p):
        logits = jnp.log(jnp.clip(p, 1e-30, None))
        if replacement:
            return jax.random.categorical(key, logits, axis=-1,
                                          shape=p.shape[:-1] + (num_samples,))
        # without replacement: Gumbel top-k trick
        g = jax.random.gumbel(key, p.shape)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx

    return apply("multinomial", f, x, differentiable=False)


def poisson(x, name=None):
    x = ensure_tensor(x)
    key = _key()
    return apply("poisson", lambda lam: jax.random.poisson(key, lam).astype(lam.dtype),
                 x, differentiable=False)


def rand_like(x, name=None):
    x = ensure_tensor(x)
    return rand(tuple(x._data.shape), x.dtype)


def randn_like(x, name=None):
    x = ensure_tensor(x)
    return standard_normal(tuple(x._data.shape), x.dtype)


def normal_(x, mean=0.0, std=1.0, name=None):
    arr = jax.random.normal(_key(), tuple(x._data.shape),
                            dtype=x._data.dtype) * std + mean
    x._set_data(arr)
    return x


def uniform_(tensor, min=-1.0, max=1.0, seed=0, name=None):
    arr = jax.random.uniform(_key(), tuple(tensor._data.shape),
                             dtype=tensor._data.dtype, minval=min, maxval=max)
    tensor._set_data(arr)
    return tensor


for _name in ("zeros", "ones", "full", "empty", "zeros_like", "ones_like",
              "full_like", "empty_like", "arange", "linspace", "logspace", "eye",
              "tril_indices", "triu_indices", "clone", "assign", "complex",
              "rand", "uniform", "randn", "standard_normal", "normal", "randint",
              "randint_like", "randperm", "bernoulli", "multinomial", "poisson",
              "rand_like", "randn_like"):
    register_op(_name, globals()[_name])

from ..core.tensor import register_tensor_method
register_tensor_method("normal_", normal_)
register_tensor_method("uniform_", uniform_)
register_tensor_method("zero_", lambda self: (self._set_data(jnp.zeros_like(self._data)), self)[1])
register_tensor_method("fill_", lambda self, v: (self._set_data(jnp.full_like(self._data, v)), self)[1])
