"""Reduction / scan ops.

Parity surface: upstream paddle/phi/kernels reduce kernels and
python/paddle/tensor/math.py + stat.py reduction APIs. XLA lowers these onto
the TPU's reduction units directly; no hand-written tree reductions needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply
from ._helpers import ensure_tensor, make_reduction, register_op

sum = make_reduction("sum", jnp.sum, dtype_pos="after_axis")
mean = make_reduction("mean", jnp.mean)
prod = make_reduction("prod", jnp.prod, dtype_pos="last")
amax = make_reduction("amax", jnp.max)
amin = make_reduction("amin", jnp.min)
nansum = make_reduction("nansum", jnp.nansum, dtype_pos="after_axis")
nanmean = make_reduction("nanmean", jnp.nanmean)
all = make_reduction("all", jnp.all, bool_out=True)
any = make_reduction("any", jnp.any, bool_out=True)


def max(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    return apply("max", lambda a: jnp.max(a, axis=axis, keepdims=keepdim), x)


def min(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    return apply("min", lambda a: jnp.min(a, axis=axis, keepdims=keepdim), x)


register_op("max", max, methods=("max",))
register_op("min", min, methods=("min",))


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = ensure_tensor(x)
    ddof = 1 if unbiased else 0
    return apply("std", lambda a: jnp.std(a, axis=axis, ddof=ddof, keepdims=keepdim), x)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = ensure_tensor(x)
    ddof = 1 if unbiased else 0
    return apply("var", lambda a: jnp.var(a, axis=axis, ddof=ddof, keepdims=keepdim), x)


register_op("std", std, methods=("std",))
register_op("var", var, methods=("var",))


def logsumexp(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply("logsumexp",
                 lambda a: jax.scipy.special.logsumexp(a, axis=ax, keepdims=keepdim), x)


register_op("logsumexp", logsumexp, methods=("logsumexp",))


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = ensure_tensor(x)

    def f(a):
        r = jnp.argmax(a, axis=axis, keepdims=keepdim if axis is not None else False)
        from ..core.dtype import canonicalize as _c
        return r.astype(_c(dtype))

    return apply("argmax", f, x, differentiable=False)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = ensure_tensor(x)

    def f(a):
        r = jnp.argmin(a, axis=axis, keepdims=keepdim if axis is not None else False)
        from ..core.dtype import canonicalize as _c
        return r.astype(_c(dtype))

    return apply("argmin", f, x, differentiable=False)


register_op("argmax", argmax, methods=("argmax",))
register_op("argmin", argmin, methods=("argmin",))


def count_nonzero(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply("count_nonzero",
                 lambda a: jnp.count_nonzero(a, axis=ax, keepdims=keepdim).astype(jnp.int64),
                 x, differentiable=False)


register_op("count_nonzero", count_nonzero, methods=("count_nonzero",))


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    x = ensure_tensor(x)
    if mode == "min":  # lower of the two middle values (reference option)
        return apply("median", lambda a: jnp.quantile(
            a, 0.5, axis=axis, keepdims=keepdim, method="lower"), x)
    return apply("median", lambda a: jnp.median(a, axis=axis, keepdims=keepdim), x)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    x = ensure_tensor(x)
    return apply("quantile", lambda a: jnp.quantile(
        a, jnp.asarray(q), axis=axis, keepdims=keepdim, method=interpolation), x)


register_op("median", median, methods=("median",))
register_op("quantile", quantile, methods=("quantile",))


def cumsum(x, axis=None, dtype=None, name=None):
    x = ensure_tensor(x)

    def f(a):
        if axis is None:
            a = a.reshape(-1)
            r = jnp.cumsum(a)
        else:
            r = jnp.cumsum(a, axis=axis)
        from ..core.dtype import canonicalize as _c
        return r.astype(_c(dtype)) if dtype is not None else r

    return apply("cumsum", f, x)


def cumprod(x, dim=None, dtype=None, name=None):
    x = ensure_tensor(x)

    def f(a):
        r = jnp.cumprod(a, axis=dim)
        from ..core.dtype import canonicalize as _c
        return r.astype(_c(dtype)) if dtype is not None else r

    return apply("cumprod", f, x)


def cummax(x, axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)

    def f(a):
        arr = a.reshape(-1) if axis is None else a
        ax = 0 if axis is None else axis
        vals = jax.lax.cummax(arr, axis=ax)
        n = arr.shape[ax]
        idx = jnp.arange(n).reshape([-1 if i == ax % arr.ndim else 1 for i in range(arr.ndim)])
        idx = jnp.broadcast_to(idx, arr.shape)
        is_new = arr == vals
        run_idx = jax.lax.cummax(jnp.where(is_new, idx, -1), axis=ax)
        return vals, run_idx.astype(jnp.dtype(dtype))

    out, idx = apply("cummax", f, x)
    return out, idx


def cummin(x, axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)

    def f(a):
        arr = a.reshape(-1) if axis is None else a
        ax = 0 if axis is None else axis
        vals = jax.lax.cummin(arr, axis=ax)
        n = arr.shape[ax]
        idx = jnp.arange(n).reshape([-1 if i == ax % arr.ndim else 1 for i in range(arr.ndim)])
        idx = jnp.broadcast_to(idx, arr.shape)
        is_new = arr == vals
        run_idx = jax.lax.cummax(jnp.where(is_new, idx, -1), axis=ax)
        return vals, run_idx.astype(jnp.dtype(dtype))

    out, idx = apply("cummin", f, x)
    return out, idx


register_op("cumsum", cumsum, methods=("cumsum",), inplace_method="cumsum_")
register_op("cumprod", cumprod, methods=("cumprod",))
register_op("cummax", cummax, methods=("cummax",))
register_op("cummin", cummin, methods=("cummin",))


def logcumsumexp(x, axis=None, dtype=None, name=None):
    x = ensure_tensor(x)

    def f(a):
        arr = a.reshape(-1) if axis is None else a
        ax = 0 if axis is None else axis
        r = jax.lax.cumlogsumexp(arr, axis=ax)
        return r.astype(jnp.dtype(dtype)) if dtype is not None else r

    return apply("logcumsumexp", f, x)


register_op("logcumsumexp", logcumsumexp, methods=("logcumsumexp",))


def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    x = ensure_tensor(x)
    if mode == "min":
        return apply("nanmedian", lambda a: jnp.nanquantile(
            a, 0.5, axis=axis, keepdims=keepdim, method="lower"), x)
    return apply("nanmedian", lambda a: jnp.nanmedian(
        a, axis=axis, keepdims=keepdim), x)


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear",
                name=None):
    x = ensure_tensor(x)
    return apply("nanquantile", lambda a: jnp.nanquantile(
        a, jnp.asarray(q), axis=axis, keepdims=keepdim,
        method=interpolation), x)


register_op("nanmedian", nanmedian, methods=("nanmedian",))
register_op("nanquantile", nanquantile, methods=("nanquantile",))
