"""Shape / layout manipulation ops.

Parity surface: python/paddle/tensor/manipulation.py and the corresponding
phi kernels. All static-shape (XLA requirement); ops whose output shape is
data-dependent (masked_select, nonzero, unique) execute eagerly and are
rejected under tracing with a clear error.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply, register_tensor_method, _is_tracer
from ._helpers import ensure_tensor, register_op

# capture builtins before any same-named ops shadow them in this module
_py_sum, _py_max, _py_min, _py_abs, _py_slice = sum, max, min, abs, slice


def _norm_shape(shape, x_shape):
    """Paddle reshape semantics: -1 infers, 0 copies the input dim."""
    shape = [int(s._data) if isinstance(s, Tensor) else int(s) for s in shape]
    out = []
    for i, s in enumerate(shape):
        if s == 0:
            out.append(x_shape[i])
        else:
            out.append(s)
    return tuple(out)


def reshape(x, shape, name=None):
    x = ensure_tensor(x)
    shape = _norm_shape(shape, x._data.shape)
    return apply("reshape", lambda a: jnp.reshape(a, shape), x)


register_op("reshape", reshape, methods=("reshape",), inplace_method="reshape_")


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = ensure_tensor(x)
    nd = x._data.ndim
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0
    new_shape = x._data.shape[:s] + (-1,) + x._data.shape[e + 1:]
    return apply("flatten", lambda a: jnp.reshape(a, new_shape), x)


register_op("flatten", flatten, methods=("flatten",), inplace_method="flatten_")


def transpose(x, perm, name=None):
    x = ensure_tensor(x)
    perm = tuple(int(p) for p in perm)
    return apply("transpose", lambda a: jnp.transpose(a, perm), x)


register_op("transpose", transpose, methods=("transpose",))


def t(x, name=None):
    x = ensure_tensor(x)
    if x._data.ndim > 2:
        raise ValueError("paddle.t only supports tensors with ndim <= 2")
    return apply("t", lambda a: a.T, x)


register_op("t", t, methods=("t",))
register_tensor_method("T", property(lambda self: apply("T", lambda a: a.T, self)))


def squeeze(x, axis=None, name=None):
    x = ensure_tensor(x)
    if axis is None:
        ax = None
    else:
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        ax = tuple(a % x._data.ndim for a in axes if x._data.shape[a % x._data.ndim] == 1)
    return apply("squeeze", lambda a: jnp.squeeze(a, axis=ax), x)


register_op("squeeze", squeeze, methods=("squeeze",), inplace_method="squeeze_")


def unsqueeze(x, axis, name=None):
    x = ensure_tensor(x)
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = tuple(int(a._data) if isinstance(a, Tensor) else int(a) for a in axes)

    def f(a):
        for ax in sorted(ax0 if ax0 >= 0 else ax0 + a.ndim + 1 for ax0 in axes):
            a = jnp.expand_dims(a, ax)
        return a

    return apply("unsqueeze", f, x)


register_op("unsqueeze", unsqueeze, methods=("unsqueeze",), inplace_method="unsqueeze_")


def concat(x, axis=0, name=None):
    tensors = [ensure_tensor(t) for t in x]
    if isinstance(axis, Tensor):
        axis = int(axis._data)
    return apply("concat", lambda *arrs: jnp.concatenate(arrs, axis=axis), *tensors)


register_op("concat", concat)


def stack(x, axis=0, name=None):
    tensors = [ensure_tensor(t) for t in x]
    return apply("stack", lambda *arrs: jnp.stack(arrs, axis=axis), *tensors)


register_op("stack", stack)


def split(x, num_or_sections, axis=0, name=None):
    x = ensure_tensor(x)
    if isinstance(axis, Tensor):
        axis = int(axis._data)
    dim = x._data.shape[axis]
    if isinstance(num_or_sections, int):
        sections = [dim // num_or_sections] * num_or_sections
    else:
        sections = [int(s) for s in num_or_sections]
        n_unknown = _py_sum(1 for s in sections if s == -1)
        if n_unknown:
            known = _py_sum(s for s in sections if s != -1)
            sections = [dim - known if s == -1 else s for s in sections]
    offsets = np.cumsum([0] + sections[:-1]).tolist()

    def f(a):
        return tuple(jax.lax.slice_in_dim(a, o, o + s, axis=axis)
                     for o, s in zip(offsets, sections))

    return list(apply("split", f, x))


register_op("split", split, methods=("split",))


def chunk(x, chunks, axis=0, name=None):
    x = ensure_tensor(x)
    return split(x, chunks, axis=axis)


register_op("chunk", chunk, methods=("chunk",))


def unbind(input, axis=0, name=None):
    x = ensure_tensor(input)
    n = x._data.shape[axis]

    def f(a):
        return tuple(jnp.squeeze(s, axis=axis)
                     for s in jnp.split(a, n, axis=axis))

    return list(apply("unbind", f, x))


register_op("unbind", unbind, methods=("unbind",))


def tile(x, repeat_times, name=None):
    x = ensure_tensor(x)
    reps = tuple(int(r._data) if isinstance(r, Tensor) else int(r) for r in repeat_times) \
        if isinstance(repeat_times, (list, tuple)) else (int(repeat_times),)
    return apply("tile", lambda a: jnp.tile(a, reps), x)


register_op("tile", tile, methods=("tile",))


def expand(x, shape, name=None):
    x = ensure_tensor(x)
    shape = _norm_shape(shape, x._data.shape)
    # paddle expand: -1 means keep input dim
    nd_in = x._data.ndim
    full = []
    for i, s in enumerate(shape):
        if s == -1:
            full.append(x._data.shape[i - (len(shape) - nd_in)])
        else:
            full.append(s)
    return apply("expand", lambda a: jnp.broadcast_to(a, tuple(full)), x)


register_op("expand", expand, methods=("expand",))


def expand_as(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply("expand_as", lambda a, b: jnp.broadcast_to(a, b.shape), x, y)


register_op("expand_as", expand_as, methods=("expand_as",))


def broadcast_to(x, shape, name=None):
    return expand(x, shape, name=name)


register_op("broadcast_to", broadcast_to, methods=("broadcast_to",))


def broadcast_tensors(inputs, name=None):
    tensors = [ensure_tensor(t) for t in inputs]
    return list(apply("broadcast_tensors", lambda *arrs: tuple(jnp.broadcast_arrays(*arrs)),
                      *tensors))


register_op("broadcast_tensors", broadcast_tensors)


def roll(x, shifts, axis=None, name=None):
    x = ensure_tensor(x)
    sh = tuple(shifts) if isinstance(shifts, (list, tuple)) else shifts
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply("roll", lambda a: jnp.roll(a, sh, axis=ax), x)


register_op("roll", roll, methods=("roll",))


def flip(x, axis, name=None):
    x = ensure_tensor(x)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return apply("flip", lambda a: jnp.flip(a, axis=ax), x)


register_op("flip", flip, methods=("flip",))


def fliplr(x, name=None):
    """Flip left/right — flip(axis=1), ndim >= 2 (reference:
    paddle.fliplr)."""
    x = ensure_tensor(x)
    if len(x._data.shape) < 2:
        raise ValueError("fliplr requires a tensor of at least 2-D")
    return flip(x, axis=1)


def flipud(x, name=None):
    """Flip up/down — flip(axis=0), ndim >= 1 (reference: paddle.flipud)."""
    x = ensure_tensor(x)
    if len(x._data.shape) < 1:
        raise ValueError("flipud requires a tensor of at least 1-D")
    return flip(x, axis=0)


register_op("fliplr", fliplr, methods=("fliplr",))
register_op("flipud", flipud, methods=("flipud",))


def rot90(x, k=1, axes=(0, 1), name=None):
    x = ensure_tensor(x)
    return apply("rot90", lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x)


register_op("rot90", rot90, methods=("rot90",))


def repeat_interleave(x, repeats, axis=None, name=None):
    x = ensure_tensor(x)
    if isinstance(repeats, Tensor):
        total = int(jnp.sum(repeats._data)) if not _is_tracer(repeats._data) else None
        return apply("repeat_interleave",
                     lambda a, r: jnp.repeat(a, r, axis=axis, total_repeat_length=total),
                     x, repeats)
    return apply("repeat_interleave", lambda a: jnp.repeat(a, repeats, axis=axis), x)


register_op("repeat_interleave", repeat_interleave, methods=("repeat_interleave",))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    if isinstance(pad, Tensor):
        pad = [int(v) for v in np.asarray(pad._data)]
    pad = [int(p) for p in pad]
    nd = x._data.ndim
    if len(pad) == 2 * nd:
        # full-rank form: [d0_before, d0_after, d1_before, ...]
        pairs = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # spatial form, innermost-dim-first: NCHW pad=[left,right,top,bottom]
        n_spatial = len(pad) // 2
        spatial = [(pad[2 * i], pad[2 * i + 1]) for i in range(n_spatial)]
        spatial = list(reversed(spatial))  # -> outermost spatial dim first
        if data_format.endswith("C") and nd >= 3:  # NHWC/NLC/NDHWC
            pairs = [(0, 0)] * (nd - n_spatial - 1) + spatial + [(0, 0)]
        else:  # NCHW/NCL/NCDHW
            pairs = [(0, 0)] * (nd - n_spatial) + spatial
    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]

    def f(a):
        if jmode == "constant":
            return jnp.pad(a, pairs, mode="constant", constant_values=value)
        return jnp.pad(a, pairs, mode=jmode)

    return apply("pad", f, x)


register_op("pad", pad)


def tril(x, diagonal=0, name=None):
    x = ensure_tensor(x)
    return apply("tril", lambda a: jnp.tril(a, k=diagonal), x)


def triu(x, diagonal=0, name=None):
    x = ensure_tensor(x)
    return apply("triu", lambda a: jnp.triu(a, k=diagonal), x)


register_op("tril", tril, methods=("tril",))
register_op("triu", triu, methods=("triu",))


def diag(x, offset=0, padding_value=0.0, name=None):
    x = ensure_tensor(x)

    def f(a):
        if a.ndim == 1:
            d = jnp.diag(a, k=offset)
            if padding_value != 0:
                mask = jnp.diag(jnp.ones_like(a, dtype=bool), k=offset)
                d = jnp.where(mask, d, padding_value)
            return d
        return jnp.diag(a, k=offset)

    return apply("diag", f, x)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    x = ensure_tensor(x)
    return apply("diagonal", lambda a: jnp.diagonal(a, offset=offset, axis1=axis1,
                                                    axis2=axis2), x)


register_op("diag", diag, methods=("diag",))
register_op("diagonal", diagonal, methods=("diagonal",))


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    x = ensure_tensor(x)

    def f(a):
        n = a.shape[-1] + _py_abs(offset)
        out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        idx = jnp.arange(a.shape[-1])
        r = idx + _py_max(-offset, 0)
        c = idx + _py_max(offset, 0)
        out = out.at[..., r, c].set(a)
        if (dim1, dim2) != (-2, -1):
            out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
        return out

    return apply("diag_embed", f, x)


register_op("diag_embed", diag_embed)


def meshgrid(*args, name=None):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    tensors = [ensure_tensor(t) for t in args]
    return list(apply("meshgrid", lambda *arrs: tuple(jnp.meshgrid(*arrs, indexing="ij")),
                      *tensors))


register_op("meshgrid", meshgrid)


def cast(x, dtype):
    from ..core.dtype import convert_dtype
    x = ensure_tensor(x)
    d = convert_dtype(dtype)
    return apply("cast", lambda a: a.astype(d), x, amp=False)


register_op("cast", cast, methods=("cast", "astype"), inplace_method="cast_")


def slice(input, axes, starts, ends):
    input = ensure_tensor(input)
    axes = [int(a) for a in axes]
    starts = [int(s._data) if isinstance(s, Tensor) else int(s) for s in starts]
    ends = [int(e._data) if isinstance(e, Tensor) else int(e) for e in ends]

    def f(a):
        idx = [_py_slice(None)] * a.ndim
        for ax, st, en in zip(axes, starts, ends):
            dim = a.shape[ax]
            st2 = _py_max(st + dim, 0) if st < 0 else _py_min(st, dim)
            en2 = _py_max(en + dim, 0) if en < 0 else _py_min(en, dim)
            idx[ax] = _py_slice(st2, en2)
        return a[tuple(idx)]

    return apply("slice", f, input)


register_op("slice", slice)


def strided_slice(x, axes, starts, ends, strides, name=None):
    x = ensure_tensor(x)
    axes = [int(a) for a in axes]
    starts = [int(s) for s in starts]
    ends = [int(e) for e in ends]
    strides = [int(s) for s in strides]

    def f(a):
        idx = [_py_slice(None)] * a.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx[ax] = _py_slice(st, en, sd)
        return a[tuple(idx)]

    return apply("strided_slice", f, x)


register_op("strided_slice", strided_slice)


def moveaxis(x, source, destination, name=None):
    x = ensure_tensor(x)
    return apply("moveaxis", lambda a: jnp.moveaxis(a, source, destination), x)


def swapaxes(x, axis1, axis2, name=None):
    x = ensure_tensor(x)
    return apply("swapaxes", lambda a: jnp.swapaxes(a, axis1, axis2), x)


register_op("moveaxis", moveaxis, methods=("moveaxis",))
register_op("swapaxes", swapaxes, methods=("swapaxes",))


def as_real(x, name=None):
    x = ensure_tensor(x)
    return apply("as_real", lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), x)


def as_complex(x, name=None):
    x = ensure_tensor(x)
    return apply("as_complex", lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x)


register_op("as_real", as_real, methods=("as_real",))
register_op("as_complex", as_complex, methods=("as_complex",))


def crop(x, shape=None, offsets=None, name=None):
    """Static crop (reference: paddle.crop): take a ``shape``-sized box
    starting at ``offsets`` (default 0s). -1 in shape keeps the rest of
    that dim."""
    x = ensure_tensor(x)
    nd = x._data.ndim
    full = x._data.shape

    def _as_list(v, fill):
        if v is None:
            return [fill] * nd
        if isinstance(v, Tensor):
            v = [int(i) for i in np.asarray(v._data)]
        return [int(i._data) if isinstance(i, Tensor) else int(i) for i in v]

    offs = _as_list(offsets, 0)
    shp = _as_list(shape, -1)
    shp = [full[i] - offs[i] if s == -1 else s for i, s in enumerate(shp)]
    for i, (o, s) in enumerate(zip(offs, shp)):
        if o < 0 or s < 0 or o + s > full[i]:
            # python slicing would CLAMP and silently return a smaller
            # tensor; the reference validates and raises
            raise ValueError(
                f"crop out of bounds on dim {i}: offset {o} + shape {s} > "
                f"input dim {full[i]}")
    slices = tuple(_py_slice(o, o + s) for o, s in zip(offs, shp))
    return apply("crop", lambda a: a[slices], x)


register_op("crop", crop, methods=("crop",))
