"""Fused int8-state AdamW update as ONE Pallas kernel per parameter.

Why this exists (round 5): the chunked XLA formulation of the int8 update
(`optimizer._adam_q8_update`) runs ~1000 dynamic-slice fusions back-to-back
per giant scan-stacked parameter — TPUs execute fusions sequentially, so
the serialized tail cost (~0.19 s/step at 2.07B params, ~8x over the HBM
floor of its ~10 B/param traffic) cannot be recovered by unrolling or
cross-param windows at the HLO level. The Pallas kernel streams the whole
parameter once: the grid walks (G, 2048)-block tiles with double-buffered
DMA, all fp32 intermediates live in VMEM (zero HBM transients — the very
thing the chunking existed to bound), and the five state buffers update
in place via input_output_aliases.

Reference parity surface: the bitsandbytes-style 8-bit optimizer layout
(1 byte/element + 4 bytes/block scale) recorded in SURVEY §2.1 "fused
kernels" (upstream: paddle/phi/kernels/gpu/fused_adam_kernel.cu and the
multi_tensor_adam family); the sqrt-space second moment is this repo's
round-4 finding (linear int8 of v explodes training).

Layout contract (matches `optimizer._q8_quantize`):
  m_q, v_q : int8  (nb, 2048)   v_q stores quantized sqrt(v)
  m_s, v_s : fp32  (nb, 1)      per-block absmax/127 scales
  base     : param dtype (nb, 2048) flattened view of the param/master
  grad     : any float (nb, 2048)
The caller guarantees n % 2048 == 0 (the optimizer routes ragged params
to the chunked XLA path — they are small, so their cost is noise).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_BLOCK = 2048      # quantization block (elements) — fixed by the q8 layout
_TILE_BLOCKS = 256  # blocks per grid step: ~0.5M elems, ~16MB fp32 in VMEM


def _kernel(sc_ref, seed_ref, mq_ref, ms_ref, vq_ref, vs_ref, base_ref,
            g_ref, mq_o, ms_o, vq_o, vs_o, base_o, *, use_sr, has_wd,
            out_dtype):
    lr, wd, c1, c2, eps, b1, b2 = (sc_ref[i] for i in range(7))
    g32 = g_ref[:].astype(jnp.float32)
    m32 = mq_ref[:].astype(jnp.float32) * ms_ref[:]
    sv = vq_ref[:].astype(jnp.float32) * vs_ref[:]
    v32 = sv * sv
    nm = b1 * m32 + (1.0 - b1) * g32
    nv = b2 * v32 + (1.0 - b2) * g32 * g32

    # requantize m (linear) and v (sqrt space) — same rule as _q8_quantize
    msc = jnp.max(jnp.abs(nm), axis=1, keepdims=True) / 127.0
    msc = jnp.where(msc == 0.0, 1.0, msc)
    mq_o[:] = jnp.clip(jnp.round(nm / msc), -127, 127).astype(jnp.int8)
    ms_o[:] = msc
    sq = jnp.sqrt(nv)
    vsc = jnp.max(jnp.abs(sq), axis=1, keepdims=True) / 127.0
    vsc = jnp.where(vsc == 0.0, 1.0, vsc)
    vq_o[:] = jnp.clip(jnp.round(sq / vsc), -127, 127).astype(jnp.int8)
    vs_o[:] = vsc

    upd = base_ref[:].astype(jnp.float32)
    if has_wd:
        upd = upd * (1.0 - lr * wd)
    upd = upd - lr * (nm / c1) / (jnp.sqrt(nv / c2) + eps)
    if use_sr:
        # stochastic f32->bf16 rounding, per-tile seeded (unbiased: adds
        # uniform low mantissa bits then truncates — optimizer.
        # _stochastic_round_bf16's rule with the on-core PRNG)
        pltpu.prng_seed(seed_ref[0] + pl.program_id(0))
        bits = jax.lax.bitcast_convert_type(upd, jnp.uint32)
        rnd = pltpu.prng_random_bits(upd.shape).astype(jnp.uint32) \
            & jnp.uint32(0xFFFF)
        rounded = (bits + rnd) & jnp.uint32(0xFFFF0000)
        out = jax.lax.bitcast_convert_type(rounded, jnp.float32)
        out = jnp.where(jnp.isfinite(upd), out, upd)
        base_o[:] = out.astype(jnp.bfloat16)
    else:
        base_o[:] = upd.astype(out_dtype)


@functools.partial(jax.jit, static_argnames=("use_sr", "has_wd",
                                             "interpret"))
def q8_adam_update(m_q, m_s, v_q, v_s, base, grad, scalars, seed, *,
                   use_sr: bool, has_wd: bool, interpret: bool = False):
    """One-kernel in-place int8 AdamW step.

    scalars: (7,) fp32 — lr_eff, weight_decay, c1 (=1-b1^t), c2 (=1-b2^t),
    epsilon, beta1, beta2. seed: (1,) int32 (ignored unless use_sr).
    Returns (m_q', m_s', v_q', v_s', base') aliased onto the inputs."""
    nb = m_q.shape[0]
    g = min(_TILE_BLOCKS, nb)
    grid = (pl.cdiv(nb, g),)
    row = lambda i: (i, 0)
    const = lambda i: (0,)
    out_dtype = base.dtype
    kern = functools.partial(_kernel, use_sr=use_sr, has_wd=has_wd,
                             out_dtype=out_dtype)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((7,), const, memory_space=pltpu.SMEM),
            pl.BlockSpec((1,), const, memory_space=pltpu.SMEM),
            pl.BlockSpec((g, _BLOCK), row),
            pl.BlockSpec((g, 1), row),
            pl.BlockSpec((g, _BLOCK), row),
            pl.BlockSpec((g, 1), row),
            pl.BlockSpec((g, _BLOCK), row),
            pl.BlockSpec((g, _BLOCK), row),
        ],
        out_specs=[
            pl.BlockSpec((g, _BLOCK), row),
            pl.BlockSpec((g, 1), row),
            pl.BlockSpec((g, _BLOCK), row),
            pl.BlockSpec((g, 1), row),
            pl.BlockSpec((g, _BLOCK), row),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(m_q.shape, jnp.int8),
            jax.ShapeDtypeStruct(m_s.shape, jnp.float32),
            jax.ShapeDtypeStruct(v_q.shape, jnp.int8),
            jax.ShapeDtypeStruct(v_s.shape, jnp.float32),
            jax.ShapeDtypeStruct(base.shape, out_dtype),
        ],
        input_output_aliases={2: 0, 3: 1, 4: 2, 5: 3, 6: 4},
        interpret=interpret,
    )(scalars, seed, m_q, m_s, v_q, v_s, base, grad)
