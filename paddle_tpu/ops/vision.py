"""Detection/vision ops: IoU, box codecs, static-shape NMS.

Parity surface: the reference's detection op set used by PaddleDetection
(``multiclass_nms3``, ``distance2bbox``, bbox IoU utilities — upstream
paddle/phi/kernels/ + ppdet modeling; no line cites: reference mount was
empty, see SURVEY.md provenance).

TPU-native design: everything is STATIC-SHAPE. Greedy NMS is a fixed-length
``lax.fori_loop`` suppression sweep over the top-k candidates (O(k^2) IoU
matrix work on the VPU — no data-dependent shapes), vmapped over classes;
outputs are fixed ``keep_top_k`` rows padded with label -1, plus a
detection count — the standard XLA-friendly detection contract.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, apply

__all__ = [
    "bbox_iou", "box_area", "distance2bbox", "bbox2distance",
    "multiclass_nms", "nms",
]


# ---------------------------------------------------------------------------
# pure jax helpers (also used by models/ppyoloe.py losses)
# ---------------------------------------------------------------------------
def _box_area(boxes):
    return jnp.clip(boxes[..., 2] - boxes[..., 0], 0) * \
        jnp.clip(boxes[..., 3] - boxes[..., 1], 0)


def _pairwise_iou(a, b, mode: str = "iou", eps: float = 1e-9):
    """a: [..., M, 4], b: [..., N, 4] (xyxy) → [..., M, N]."""
    lt = jnp.maximum(a[..., :, None, :2], b[..., None, :, :2])
    rb = jnp.minimum(a[..., :, None, 2:], b[..., None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = _box_area(a)[..., :, None] + _box_area(b)[..., None, :] - inter
    iou = inter / (union + eps)
    if mode == "iou":
        return iou
    # giou: subtract normalized hull slack
    hull_lt = jnp.minimum(a[..., :, None, :2], b[..., None, :, :2])
    hull_rb = jnp.maximum(a[..., :, None, 2:], b[..., None, :, 2:])
    hull_wh = jnp.clip(hull_rb - hull_lt, 0)
    hull = hull_wh[..., 0] * hull_wh[..., 1]
    return iou - (hull - union) / (hull + eps)


def _nms_suppress(boxes, iou_threshold):
    """Greedy NMS over score-sorted candidates with a fixed-trip-count
    suppression loop. boxes [K,4] sorted by score desc; returns keep [K].
    No score-positivity requirement — validity filtering is the caller's
    convention (the multiclass path masks on thresholded scores)."""
    k = boxes.shape[0]
    ious = _pairwise_iou(boxes, boxes)  # [K, K]
    idx = jnp.arange(k)

    def body(i, supp):
        alive = jnp.logical_not(supp[i])
        kill = alive & (ious[i] > iou_threshold) & (idx > i)
        return supp | kill

    supp = lax.fori_loop(0, k, body, jnp.zeros(k, bool))
    return jnp.logical_not(supp)


# ---------------------------------------------------------------------------
# public ops
# ---------------------------------------------------------------------------
def bbox_iou(boxes1, boxes2, mode: str = "iou") -> Tensor:
    """Pairwise IoU/GIoU between two box sets (xyxy)."""
    return apply("bbox_iou", partial(_pairwise_iou, mode=mode), boxes1, boxes2,
                 differentiable=True)


def box_area(boxes) -> Tensor:
    return apply("box_area", _box_area, boxes)


def distance2bbox(points, distance, max_shape=None) -> Tensor:
    """Decode (l, t, r, b) distances at anchor points into xyxy boxes."""

    def fn(p, d):
        x1y1 = p - d[..., :2]
        x2y2 = p + d[..., 2:]
        out = jnp.concatenate([x1y1, x2y2], axis=-1)
        if max_shape is not None:
            h, w = max_shape
            out = jnp.clip(out, 0, jnp.asarray([w, h, w, h], out.dtype))
        return out

    return apply("distance2bbox", fn, points, distance)


def bbox2distance(points, bbox, reg_max: Optional[float] = None) -> Tensor:
    """Encode xyxy boxes as (l, t, r, b) distances from anchor points."""

    def fn(p, b):
        lt = p - b[..., :2]
        rb = b[..., 2:] - p
        out = jnp.concatenate([lt, rb], axis=-1)
        if reg_max is not None:
            out = jnp.clip(out, 0, reg_max - 0.01)
        return out

    return apply("bbox2distance", fn, points, bbox)


def nms(boxes, iou_threshold: float = 0.3, scores=None, category_idxs=None,
        categories=None, top_k: Optional[int] = None) -> Tensor:
    """``paddle.vision.ops.nms`` parity (upstream python/paddle/vision/ops.py
    nms: positional order boxes, iou_threshold, scores, category_idxs,
    categories, top_k).

    * ``scores=None``: suppression in the given box order (upstream
      "sorted by score or in the given order").
    * ``category_idxs``/``categories``: categorical NMS — boxes of different
      categories never suppress each other (implemented by offsetting each
      category into a disjoint coordinate range, one fused pass; upstream
      loops per category).

    Static-shape divergence (see MIGRATING.md): returns kept indices in
    descending-score order, compacted to the front and padded with -1 to a
    fixed length (``top_k`` if given, else the box count) instead of a
    dynamic-length array.
    """
    n = boxes.shape[0]
    k = min(int(top_k), n) if top_k is not None else n

    def fn(b, *rest):
        rest = list(rest)
        s = rest.pop(0) if scores is not None else None
        cidx = rest.pop(0) if category_idxs is not None else None
        if cidx is not None:
            # disjoint per-category windows: cross-category IoU becomes 0
            span = 2.0 * (jnp.max(jnp.abs(b)) + 1.0)
            b = b + cidx.astype(b.dtype)[:, None] * span
        order = jnp.argsort(-s) if s is not None else jnp.arange(b.shape[0])
        keep = _nms_suppress(b[order], iou_threshold)
        kept = jnp.where(keep, order, -1)
        # stable-compact the kept indices to the front, then cut to k
        pos = jnp.where(keep, jnp.arange(keep.shape[0]), keep.shape[0])
        return kept[jnp.argsort(pos)][:k]

    args = [boxes]
    if scores is not None:
        args.append(scores)
    if category_idxs is not None:
        args.append(category_idxs)
    return apply("nms", fn, *args, differentiable=False)


def multiclass_nms(bboxes, scores, score_threshold: float = 0.05,
                   nms_top_k: int = 1000, keep_top_k: int = 100,
                   nms_threshold: float = 0.5, background_label: int = -1
                   ) -> Tuple[Tensor, Tensor]:
    """Per-class NMS with static output (parity: multiclass_nms3).

    bboxes: [B, N, 4] xyxy; scores: [B, C, N].
    Returns (out [B, keep_top_k, 6] rows = [label, score, x1, y1, x2, y2]
    padded with label -1, nums_detections [B]).
    """

    def fn(bx, sc):
        def one_image(boxes, scores_cn):
            c = scores_cn.shape[0]
            k = min(nms_top_k, boxes.shape[0])
            if 0 <= background_label < c:
                # multiclass_nms3 semantics: the background class never emits
                scores_cn = scores_cn.at[background_label].set(0.0)

            def per_class(s):
                order = jnp.argsort(-s)[:k]
                bs = boxes[order]
                ss = jnp.where(s[order] > score_threshold, s[order], 0.0)
                keep = _nms_suppress(bs, nms_threshold)
                return jnp.where(keep, ss, 0.0), bs

            ss, bs = jax.vmap(per_class)(scores_cn)  # [C,k], [C,k,4]
            labels = jnp.broadcast_to(jnp.arange(c)[:, None], ss.shape)
            flat_s = ss.reshape(-1)
            flat_b = bs.reshape(-1, 4)
            flat_l = labels.reshape(-1)
            if flat_s.shape[0] < keep_top_k:  # keep the static contract
                pad = keep_top_k - flat_s.shape[0]
                flat_s = jnp.pad(flat_s, (0, pad))
                flat_b = jnp.pad(flat_b, ((0, pad), (0, 0)))
                flat_l = jnp.pad(flat_l, (0, pad))
            top = jnp.argsort(-flat_s)[:keep_top_k]
            sel_s, sel_b = flat_s[top], flat_b[top]
            sel_l = jnp.where(sel_s > 0, flat_l[top], -1).astype(jnp.float32)
            out = jnp.concatenate(
                [sel_l[:, None], sel_s[:, None], sel_b], axis=-1)
            return out, jnp.sum(sel_s > 0).astype(jnp.int32)

        return jax.vmap(one_image)(bx, sc)

    return apply("multiclass_nms", fn, bboxes, scores, differentiable=False)
