"""Convolution / pooling / resize ops.

Parity surface: python/paddle/nn/functional/conv.py + pooling.py and phi
conv/pool kernels (the reference's cuDNN seam, upstream
paddle/phi/kernels/gpudnn/). TPU-native: ``lax.conv_general_dilated`` maps
convs straight onto the MXU; pooling is ``lax.reduce_window``. Default layout
NCHW matches paddle; XLA relayouts internally for the TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply
from ._helpers import ensure_tensor, register_op


def _pair(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _conv_padding(padding, spatial, strides, kernel, dilation):
    """Resolve paddle padding spec -> lax padding list."""
    if isinstance(padding, str):
        return padding.upper()  # 'SAME' | 'VALID'
    if isinstance(padding, int):
        return [(padding, padding)] * spatial
    padding = list(padding)
    if len(padding) == spatial:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * spatial:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(spatial)]
    raise ValueError(f"bad padding {padding}")


def _conv(x, weight, bias, stride, padding, dilation, groups, spatial, data_format,
          op_name):
    x = ensure_tensor(x)
    weight = ensure_tensor(weight)
    strides = _pair(stride, spatial)
    dil = _pair(dilation, spatial)
    pad = _conv_padding(padding, spatial, strides, None, dil)
    if data_format in ("NCHW", "NCL", "NCDHW"):
        lhs_spec = "NC" + "DHW"[3 - spatial:]
    else:
        lhs_spec = "N" + "DHW"[3 - spatial:] + "C"
    rhs_spec = "OI" + "DHW"[3 - spatial:]
    out_spec = lhs_spec
    dn = jax.lax.conv_dimension_numbers(
        tuple(x._data.shape), tuple(weight._data.shape), (lhs_spec, rhs_spec, out_spec))

    def f(a, w, *maybe_bias):
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=strides, padding=pad, rhs_dilation=dil,
            dimension_numbers=dn, feature_group_count=groups)
        if maybe_bias:
            b = maybe_bias[0]
            shape = [1] * out.ndim
            shape[lhs_spec.index("C")] = b.size
            out = out + b.reshape(shape)
        return out

    if bias is not None:
        return apply(op_name, f, x, weight, ensure_tensor(bias))
    return apply(op_name, f, x, weight)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 data_format, "conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format, "conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format, "conv3d")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, data_format="NCHW", output_size=None,
                     name=None):
    x, weight = ensure_tensor(x), ensure_tensor(weight)
    spatial = 2
    strides = _pair(stride, spatial)
    dil = _pair(dilation, spatial)
    pads = _conv_padding(padding, spatial, strides, None, dil)
    opad = _pair(output_padding, spatial)
    # paddle weight layout for transpose conv: (in_channels, out_channels/groups, kH, kW)
    dn = jax.lax.conv_dimension_numbers(
        tuple(x._data.shape),
        (weight._data.shape[1] * groups, weight._data.shape[0] // groups,
         weight._data.shape[2], weight._data.shape[3]),
        ("NCHW", "OIHW", "NCHW"))

    def f(a, w, *maybe_bias):
        # gradient-of-conv formulation: transpose conv = lhs-dilated conv with
        # flipped kernel, swapping I/O axes of the weight
        wt = jnp.swapaxes(w, 0, 1)  # (out/g, in, kH, kW) -> treat as OIHW
        if groups > 1:
            ic = w.shape[0]
            oc_g = w.shape[1]
            wg = w.reshape(groups, ic // groups, oc_g, *w.shape[2:])
            wt = jnp.concatenate([jnp.swapaxes(g, 0, 1) for g in wg], axis=0)
        wt = jnp.flip(wt, axis=(-1, -2))
        if isinstance(pads, str):
            pad_cfg = pads
        else:
            pad_cfg = [
                (dil[i] * (w.shape[2 + i] - 1) - pads[i][0],
                 dil[i] * (w.shape[2 + i] - 1) - pads[i][1] + opad[i])
                for i in range(spatial)
            ]
        out = jax.lax.conv_general_dilated(
            a, wt, window_strides=(1, 1), padding=pad_cfg, lhs_dilation=strides,
            rhs_dilation=dil, dimension_numbers=dn, feature_group_count=groups)
        if maybe_bias:
            b = maybe_bias[0]
            out = out + b.reshape(1, -1, 1, 1)
        return out

    if bias is not None:
        return apply("conv2d_transpose", f, x, weight, ensure_tensor(bias))
    return apply("conv2d_transpose", f, x, weight)


def _pool(x, op_name, kernel_size, stride, padding, spatial, reducer, init,
          ceil_mode=False, data_format="NCHW", exclusive=True,
          divisor_override=None):
    x = ensure_tensor(x)
    k = _pair(kernel_size, spatial)
    s = _pair(stride if stride is not None else kernel_size, spatial)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _pair(padding, spatial)
        pad = [(pp, pp) for pp in p]
    channel_first = data_format in ("NCHW", "NCL", "NCDHW")
    if channel_first:
        window = (1, 1) + k
        strides = (1, 1) + s
        pad_full = [(0, 0), (0, 0)] + (pad if not isinstance(pad, str) else [])
    else:
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pad_full = [(0, 0)] + (pad if not isinstance(pad, str) else []) + [(0, 0)]
    ceil_extra = False
    user_pad_full = [tuple(pp) for pp in pad_full] \
        if not isinstance(pad, str) else None
    if ceil_mode and not isinstance(pad, str):
        # ceil output shapes: extend the HIGH-side padding so reduce_window
        # emits the last partial window (reference rule: that window must
        # still START inside input+pad_lo, else it is dropped). Padding
        # elements never pollute results: max uses -inf, avg either counts
        # real elements (exclusive) or divides by the fixed kernel size.
        sp0 = 2 if channel_first else 1
        for j in range(spatial):
            dim = sp0 + j
            length = int(x._data.shape[dim])
            eff = length + 2 * p[j] - k[j]
            if eff % s[j] != 0:
                out_ceil = -(-eff // s[j]) + 1
                if (out_ceil - 1) * s[j] >= length + p[j]:
                    continue  # would start entirely in padding: dropped
                hi_extra = (out_ceil - 1) * s[j] + k[j] - (length + 2 * p[j])
                lo, hi = pad_full[dim]
                pad_full[dim] = (lo, hi + hi_extra)
                ceil_extra = True
    pad_cfg = pad if isinstance(pad, str) else pad_full

    def f(a):
        if reducer == "max":
            return jax.lax.reduce_window(a, -jnp.inf, jax.lax.max, window, strides,
                                         pad_cfg)
        summed = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, strides, pad_cfg)
        if divisor_override is not None:
            # fixed user divisor replaces every counting rule (upstream
            # avg_pool2d/3d divisor_override)
            return summed / float(divisor_override)

        def real_counts():
            return jax.lax.reduce_window(jnp.ones_like(a), 0.0, jax.lax.add,
                                         window, strides, pad_cfg)

        if isinstance(pad_cfg, str):
            return summed / real_counts()
        if not exclusive:
            # paddle exclusive=False: user padding COUNTS in the divisor
            # (torch count_include_pad=True) but the ceil extension never
            # does — count over ones pre-padded with the user padding
            if not ceil_extra:
                return summed / float(np.prod(k))
            ones_up = jnp.pad(jnp.ones_like(a), user_pad_full,
                              constant_values=1.0)
            extras = [(f_[0] - u[0], f_[1] - u[1])
                      for f_, u in zip(pad_full, user_pad_full)]
            counts_up = jax.lax.reduce_window(ones_up, 0.0, jax.lax.add,
                                              window, strides, extras)
            return summed / counts_up
        # exclusive=True (the paddle default): padding and ceil-extension
        # elements are EXCLUDED from the divisor — divide by the true
        # element count per window. No-padding floor-mode keeps the cheap
        # constant divisor.
        if not ceil_extra and all(pp == (0, 0) for pp in pad_full):
            return summed / float(np.prod(k))
        return summed / real_counts()

    return apply(op_name, f, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    if return_mask:
        from .manipulation import squeeze, unsqueeze
        from .nn_ext import max_pool2d_with_index
        if ceil_mode or data_format != "NCL" or isinstance(padding, str):
            raise NotImplementedError(
                "max_pool1d(return_mask=True) supports NCL, ceil_mode=False, "
                "numeric padding")
        k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
        s = stride if stride is None or isinstance(stride, int) else stride[0]
        p = padding if isinstance(padding, int) else padding[0]
        out, mask = max_pool2d_with_index(unsqueeze(x, 2), (1, k),
                                          (1, s if s is not None else k),
                                          (0, p))
        return squeeze(out, 2), squeeze(mask, 2)
    return _pool(x, "max_pool1d", kernel_size, stride, padding, 1, "max", -jnp.inf,
                 ceil_mode, data_format)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        from .nn_ext import max_pool2d_with_index
        if ceil_mode or data_format != "NCHW" or isinstance(padding, str):
            raise NotImplementedError(
                "max_pool2d(return_mask=True) supports NCHW, ceil_mode=False, "
                "numeric padding")
        return max_pool2d_with_index(x, kernel_size, stride, padding)
    return _pool(x, "max_pool2d", kernel_size, stride, padding, 2, "max", -jnp.inf,
                 ceil_mode, data_format)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, "max_pool3d", kernel_size, stride, padding, 3, "max", -jnp.inf,
                 ceil_mode, data_format)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, "avg_pool1d", kernel_size, stride, padding, 1, "avg", 0.0,
                 ceil_mode, data_format, exclusive=exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, "avg_pool2d", kernel_size, stride, padding, 2, "avg", 0.0,
                 ceil_mode, data_format, exclusive=exclusive,
                 divisor_override=divisor_override)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, "avg_pool3d", kernel_size, stride, padding, 3, "avg", 0.0,
                 ceil_mode, data_format, exclusive=exclusive,
                 divisor_override=divisor_override)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    out_hw = _pair(output_size, 2)
    if data_format != "NCHW":
        # channel-last: transpose around the channel-first exact helper
        def to_cf(t):
            from ..ops.manipulation import transpose as _tp
            return _tp(t, [0, 3, 1, 2])

        out = _adaptive_pool_exact("adaptive_avg_pool2d", to_cf(x), out_hw,
                                   "avg")
        from ..ops.manipulation import transpose as _tp
        return _tp(out, [0, 2, 3, 1])
    return _adaptive_pool_exact("adaptive_avg_pool2d", x, out_hw, "avg")


def adaptive_avg_pool1d(x, output_size, name=None):
    o = int(output_size) if not isinstance(output_size, (list, tuple)) \
        else int(output_size[0])
    return _adaptive_pool_exact("adaptive_avg_pool1d", x, (o,), "avg")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError(
            "adaptive_max_pool2d(return_mask=True) is not implemented")
    return _adaptive_pool_exact("adaptive_max_pool2d", x,
                                _pair(output_size, 2), "max")


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
                align_mode=0, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    nd = x._data.ndim
    spatial = nd - 2
    if size is not None:
        if isinstance(size, Tensor):
            size = [int(v) for v in np.asarray(size._data)]
        out_sp = tuple(int(s._data) if isinstance(s, Tensor) else int(s)
                       for s in (size if isinstance(size, (list, tuple)) else [size]))
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * spatial
        in_sp = x._data.shape[2:] if data_format.startswith("NC") else x._data.shape[1:-1]
        out_sp = tuple(int(d * f) for d, f in zip(in_sp, sf))
    channel_first = data_format.startswith("NC")
    if channel_first:
        out_shape = x._data.shape[:2] + out_sp
    else:
        out_shape = (x._data.shape[0],) + out_sp + (x._data.shape[-1],)
    method = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
              "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]

    def f(a):
        return jax.image.resize(a, out_shape, method=method)

    return apply("interpolate", f, x)


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format, name)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    if data_format != "NCHW":
        raise ValueError(
            f"{data_format!r} layout is not implemented; use NCHW")
    r = int(upscale_factor)

    def f(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c // (r * r), r, r, h, w)
        a = a.transpose(0, 1, 4, 2, 5, 3)
        return a.reshape(n, c // (r * r), h * r, w * r)

    return apply("pixel_shuffle", f, x)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    x = ensure_tensor(x)
    k = _pair(kernel_sizes, 2)
    s = _pair(strides, 2)
    p = _pair(paddings, 2)
    d = _pair(dilations, 2)

    def f(a):
        n, c, h, w = a.shape
        patches = jax.lax.conv_general_dilated_patches(
            a, filter_shape=k, window_strides=s,
            padding=[(p[0], p[0]), (p[1], p[1])], rhs_dilation=d,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        # patches: (N, C*kh*kw, oh, ow) -> (N, C*kh*kw, L)
        return patches.reshape(n, patches.shape[1], -1)

    return apply("unfold", f, x)




def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    if data_format != "NCHW":
        raise ValueError(
            f"{data_format!r} layout is not implemented; use NCHW")
    r = int(downscale_factor)

    def f(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c, h // r, r, w // r, r)
        a = a.transpose(0, 1, 3, 5, 2, 4)
        return a.reshape(n, c * r * r, h // r, w // r)

    return apply("pixel_unshuffle", f, x)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    if data_format != "NCHW":
        raise ValueError(
            f"{data_format!r} layout is not implemented; use NCHW")
    g = int(groups)

    def f(a):
        n, c, h, w = a.shape
        a = a.reshape(n, g, c // g, h, w)
        return a.transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)

    return apply("channel_shuffle", f, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """Inverse of unfold: sum patch columns back into an image (reference:
    phi::FoldKernel). x (N, C*kh*kw, L) -> (N, C, H, W)."""
    x = ensure_tensor(x)
    oh, ow = _pair(output_sizes, 2)
    kh, kw = _pair(kernel_sizes, 2)
    s = _pair(strides, 2)
    p = _pair(paddings, 2)
    d = _pair(dilations, 2)

    def f(a):
        n, ckk, l = a.shape
        c = ckk // (kh * kw)
        n_h = (oh + 2 * p[0] - (d[0] * (kh - 1) + 1)) // s[0] + 1
        n_w = (ow + 2 * p[1] - (d[1] * (kw - 1) + 1)) // s[1] + 1
        cols = a.reshape(n, c, kh, kw, n_h, n_w)
        img = jnp.zeros((n, c, oh + 2 * p[0], ow + 2 * p[1]), a.dtype)
        # scatter-add each kernel tap's grid (static python loops over kh/kw)
        for i in range(kh):
            for j in range(kw):
                ys = i * d[0] + jnp.arange(n_h) * s[0]
                xs = j * d[1] + jnp.arange(n_w) * s[1]
                img = img.at[:, :, ys[:, None], xs[None, :]].add(
                    cols[:, :, i, j])
        return img[:, :, p[0]:p[0] + oh, p[1]:p[1] + ow]

    return apply("fold", f, x)

for _n in ("conv1d", "conv2d", "conv3d", "conv2d_transpose", "max_pool1d",
           "max_pool2d", "max_pool3d", "avg_pool1d", "avg_pool2d", "avg_pool3d",
           "adaptive_avg_pool1d", "adaptive_avg_pool2d", "adaptive_max_pool2d",
           "interpolate", "upsample", "pixel_shuffle",
           "pixel_unshuffle", "channel_shuffle", "fold"):
    register_op(_n, globals()[_n])
# NOTE: this module's ``unfold`` (im2col) is nn.functional.unfold only;
# top-level paddle.unfold is the sliding-window Tensor op (math_ext.py) —
# they are DIFFERENT upstream APIs sharing a name.


def _adaptive_pool_exact(op_name, x, out_sizes, mode):
    """Exact adaptive pooling over the trailing spatial dims of an NC...
    tensor: bin i spans [floor(i*L/out), ceil((i+1)*L/out)) — the reference
    semantics for ANY input size (divisible inputs reduce to equal
    windows). Output sizes are small constants, so the per-bin Python loop
    unrolls into a static program."""
    import math as _math

    x = ensure_tensor(x)
    spatial = len(out_sizes)
    in_sizes = tuple(int(d) for d in x._data.shape[2:2 + spatial])

    def bins(L, out):
        return [(int(_math.floor(i * L / out)),
                 max(int(_math.ceil((i + 1) * L / out)),
                     int(_math.floor(i * L / out)) + 1))
                for i in range(out)]

    all_bins = [bins(L, o) for L, o in zip(in_sizes, out_sizes)]
    red = jnp.max if mode == "max" else jnp.mean
    axes = tuple(range(2, 2 + spatial))

    if all(L % o == 0 for L, o in zip(in_sizes, out_sizes)):
        # equal windows: one reduce_window beats the per-bin unrolling
        ks = tuple(L // o for L, o in zip(in_sizes, out_sizes))
        window = (1, 1) + ks

        def f(a):
            if mode == "max":
                return jax.lax.reduce_window(a, -jnp.inf, jax.lax.max,
                                             window, window, "VALID")
            summed = jax.lax.reduce_window(a, 0.0, jax.lax.add, window,
                                           window, "VALID")
            import math as _m
            return summed / _m.prod(ks)

        return apply(op_name, f, x)

    def f(a):
        def build(dim, index):
            if dim == spatial:
                sl = (slice(None), slice(None)) + tuple(
                    slice(lo, hi) for lo, hi in index)
                return red(a[sl], axis=axes)
            # each child is (N, C, out_{dim+1}, ...): stacking at axis=2
            # prepends this dim's bins in the right position
            return jnp.stack([build(dim + 1, index + [b])
                              for b in all_bins[dim]], axis=2)
        return build(0, [])

    return apply(op_name, f, x)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    """(reference: paddle.nn.functional.adaptive_avg_pool3d)"""
    if data_format != "NCDHW":
        raise NotImplementedError(
            "adaptive_avg_pool3d supports NCDHW only")
    return _adaptive_pool_exact("adaptive_avg_pool3d", x,
                                _pair(output_size, 3), "avg")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    """(reference: paddle.nn.functional.adaptive_max_pool1d)"""
    if return_mask:
        raise NotImplementedError(
            "adaptive_max_pool1d(return_mask=True) is not implemented")
    o = int(output_size) if not isinstance(output_size, (list, tuple)) \
        else int(output_size[0])
    return _adaptive_pool_exact("adaptive_max_pool1d", x, (o,), "max")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    """(reference: paddle.nn.functional.adaptive_max_pool3d)"""
    if return_mask:
        raise NotImplementedError(
            "adaptive_max_pool3d(return_mask=True) is not implemented")
    return _adaptive_pool_exact("adaptive_max_pool3d", x,
                                _pair(output_size, 3), "max")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     data_format="NCDHW", output_size=None, name=None):
    """3-D transposed convolution (reference:
    paddle.nn.functional.conv3d_transpose): gradient-of-conv as an
    lhs-dilated conv with the flipped kernel (same formulation as the 2-D
    op; paddle output size (i-1)*s - 2p + dil*(k-1) + 1 + opad)."""
    if data_format != "NCDHW":
        raise NotImplementedError("conv3d_transpose supports NCDHW only")
    x, weight = ensure_tensor(x), ensure_tensor(weight)
    spatial = 3
    strides = _pair(stride, spatial)
    dil = _pair(dilation, spatial)
    pads = _conv_padding(padding, spatial, strides, None, dil)
    opad = _pair(output_padding, spatial)
    if output_size is not None and not isinstance(pads, str):
        # reference semantics: output_size resolves the stride ambiguity —
        # derive the implied output_padding per dim
        outs = [int(v) for v in (output_size if isinstance(
            output_size, (list, tuple)) else [output_size] * spatial)][-3:]
        opad = tuple(
            outs[i] - ((int(x._data.shape[2 + i]) - 1) * strides[i]
                       - pads[i][0] - pads[i][1]
                       + dil[i] * (int(weight._data.shape[2 + i]) - 1) + 1)
            for i in range(spatial))
        if any(o < 0 or o >= strides[i] for i, o in enumerate(opad)):
            raise ValueError(
                f"conv3d_transpose: output_size {outs} unreachable with "
                f"stride {strides} / padding {padding}")
    extras = [ensure_tensor(bias)] if bias is not None else []

    def f(a, w, *rest):
        wt = jnp.swapaxes(w, 0, 1)  # (in, out/g, kD,kH,kW) -> OIDHW
        if groups > 1:
            ic = w.shape[0]
            oc_g = w.shape[1]
            wg = w.reshape(groups, ic // groups, oc_g, *w.shape[2:])
            wt = jnp.concatenate([jnp.swapaxes(g, 0, 1) for g in wg], axis=0)
        wt = jnp.flip(wt, axis=(-1, -2, -3))
        if isinstance(pads, str):
            pad_cfg = pads
        else:
            pad_cfg = [
                (dil[i] * (w.shape[2 + i] - 1) - pads[i][0],
                 dil[i] * (w.shape[2 + i] - 1) - pads[i][1] + opad[i])
                for i in range(spatial)
            ]
        out = jax.lax.conv_general_dilated(
            a, wt, window_strides=(1, 1, 1), padding=pad_cfg,
            lhs_dilation=strides, rhs_dilation=dil,
            feature_group_count=groups)
        if rest:
            out = out + rest[0].reshape(1, -1, 1, 1, 1)
        return out

    return apply("conv3d_transpose", f, x, weight, *extras)


for _n in ("adaptive_avg_pool3d", "adaptive_max_pool1d",
           "adaptive_max_pool3d", "conv3d_transpose"):
    register_op(_n, globals()[_n])
