"""Paged-attention decode: a Pallas kernel that consumes the page pool +
page tables directly — the dense stacked cache never exists in the decode
program.

Why (ROADMAP 3a): the serving decode program used to reconstruct the full
dense ``(L, 2, B, H, max_len, D)`` cache inside the trace every step
(``serving/kv_cache.py::gather_pages``), so per-token attention bandwidth
scaled with ``max_len``, not with the live context. This module makes the
decode step's KV traffic O(live pages) reads + O(1) page writes:

* **Streaming kernel** (:func:`paged_attention`): one program per
  (batch row, q head); the grid's innermost dimension walks the slot's
  page-table row, and the ``PrefetchScalarGridSpec`` index maps resolve
  each K/V block to ``pool[tables[b, s], layer, k/v, h // rep]`` — Pallas
  double-buffers the page DMAs, and a repeated block index (the trailing
  scratch-page entries of a short slot) skips the re-fetch, so HBM
  traffic follows the LIVE page count. Online softmax (the
  ``ops/flash_attention.py`` pattern) runs in fp32 VMEM scratch carried
  across the page dimension; pages whose first position is ``>= t`` skip
  compute entirely (``@pl.when``).
* **In-kernel dequant**: the int8 leg multiplies each streamed page by
  its per-(page, layer, K/V, head) absmax scale — the exact grid
  ``serving/kv_cache.py::quantize_pages`` wrote — so the quantized pool
  is never expanded outside VMEM. The bf16 leg upcasts in-register.
* **Current token exact**: the position-``t`` K/V is passed to the kernel
  unquantized and joins the softmax in fp32 — matching the dense path,
  where the step writes the fresh token into the gathered cache *before*
  attention and quantization happens only at write-back.
* **In-place token write** (:func:`scatter_token_inplace`): K/V for
  position ``t`` lands in the containing pool page by scatter — O(1)
  pages per slot, no dense round-trip. The int8 leg re-quantizes the one
  containing page under the kv_cache requantization contract (positions
  ``> t`` masked to zero; same math as ``scatter_token_page``, sourced
  from the pool instead of the dense cache).

Tiering (the flash-SDPA / step-capture contract): the kernel is the TPU
tier; off-TPU it runs under the Pallas interpreter when forced (tests)
while ``auto`` keeps CPU on the existing dense-gather debug tier, which
stays the parity reference (``PADDLE_TPU_PAGED_ATTENTION=auto|on|off``).
:func:`paged_attention_dense` is that reference restricted to one layer —
it gathers only the slot's pages for the layer being decoded, so even the
debug tier of a paged program never rebuilds the L-stacked cache.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass, replace
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only resolves on TPU-enabled jaxlib (always true here)
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

__all__ = ["PagedDecodeCache", "mode", "decode_path", "kernel_eligible",
           "paged_attention", "paged_attention_dense",
           "scatter_token_inplace", "paged_decode_attention"]

_NEG_INF = -1e30  # matches ops/flash_attention.py's mask fill

_VALID_MODES = ("auto", "on", "off")


def mode() -> str:
    """Resolve ``PADDLE_TPU_PAGED_ATTENTION`` (default ``auto``).

    ``auto`` — kernel on TPU, dense-gather debug tier on CPU (the same
    device split as flash SDPA); ``on`` — kernel everywhere (Pallas
    interpreter off-TPU: slow, for parity tests); ``off`` — dense tier
    everywhere."""
    m = os.environ.get("PADDLE_TPU_PAGED_ATTENTION", "auto").strip().lower()
    if m in _VALID_MODES:
        return m
    if not m:                        # set-but-empty reads as unset
        return "auto"
    if m in ("0", "false", "no", "disable", "disabled"):
        return "off"
    if m in ("1", "true", "yes", "enable", "enabled", "kernel"):
        return "on"
    # a typo must not silently flip the decode tier (e.g. "dense" reading
    # as auto -> kernel on TPU): fail like the config-field validation
    raise ValueError(
        f"PADDLE_TPU_PAGED_ATTENTION must be auto|on|off, got {m!r}")


def decode_path(override: str = "") -> str:
    """``"kernel"`` or ``"dense"`` for the current device + mode.

    ``override`` (a ``ServingConfig.paged_attention`` value) wins over the
    env knob when non-empty, mirroring the watchdog/queue-wait contract."""
    m = (override or "").strip().lower() or mode()
    if m not in _VALID_MODES:
        raise ValueError(
            f"paged_attention mode must be auto|on|off, got {m!r} "
            "(env: PADDLE_TPU_PAGED_ATTENTION)")
    if m == "off":
        return "dense"
    if m == "on":
        return "kernel"
    return "kernel" if jax.default_backend() not in ("cpu",) else "dense"


def kernel_interpret() -> bool:
    """Off-TPU the kernel runs under the Pallas interpreter (tests)."""
    return jax.default_backend() in ("cpu",)


def kernel_eligible(page_size: int, head_dim: int, storage_dtype) -> bool:
    """Mosaic tiling constraints for the compiled (non-interpret) kernel:
    the K/V block's sublane dimension is ``page_size`` (8/16/32-multiple
    for f32/bf16/int8) and its lane dimension is ``head_dim`` (8-aligned,
    the flash kernel's bound). Ineligible shapes stay on the per-layer
    dense tier — correctness is never gated on tiling."""
    dt = jnp.dtype(storage_dtype)
    if dt == jnp.int8:
        sublane = 32
    elif dt.itemsize == 2:
        sublane = 16
    else:
        sublane = 8
    return page_size % sublane == 0 and head_dim % 8 == 0


@dataclass
class PagedDecodeCache:
    """The traced handle that threads the page pool through a decode step
    in place of the dense stacked cache.

    The serving engine builds one per compiled decode call and passes it
    as the ``step_fn``'s cache argument; models that understand it
    (``FusedMultiTransformer``, ``LlamaForCausalLM.serving_callables``)
    run their cached attention over the kernel and return an updated
    handle. Fields are Tensors (traced inside the decode program):

    * ``pool``    — ``(num_pages, L, 2, H_kv, page_size, D)`` storage dtype
    * ``scales``  — ``(num_pages, L, 2, H_kv)`` fp32 (int8 leg only)
    * ``tables``  — ``(B, pages_per_slot)`` int32 page-table rows
    * ``t``       — ``(B,)`` int32 per-slot write position (the decode
      step attends positions ``<= t`` and writes K/V at ``t``)
    * ``layer``   — scalar int32 Tensor, set per layer by the model's
      layer loop/scan (:meth:`at_layer`); ``None`` on the engine-level
      handle
    * ``impl``    — ``"kernel"`` | ``"dense"`` (the per-layer debug tier)
    * ``interpret`` — run the kernel under the Pallas interpreter (CPU)
    """

    pool: object
    tables: object
    t: object
    page_size: int
    scales: Optional[object] = None
    layer: Optional[object] = None
    impl: str = "kernel"
    interpret: bool = False

    def at_layer(self, layer) -> "PagedDecodeCache":
        return replace(self, layer=layer)

    @property
    def num_kv_heads(self) -> int:
        return int(self.pool.shape[3])

    @property
    def head_dim(self) -> int:
        return int(self.pool.shape[5])


# ---------------------------------------------------------------------------
# the streaming kernel
# ---------------------------------------------------------------------------

def _decode_kernel(tables_ref, t_ref, layer_ref, q_ref, kn_ref, vn_ref,
                   k_ref, v_ref, *rest, page_size: int, sm_scale: float,
                   num_pages: int, quantized: bool):
    """One (batch row, q head) program; grid dim 2 streams the slot's
    page-table row. fp32 online softmax carried in VMEM scratch across
    pages (TPU grids run sequentially, so scratch persists); the final
    page step folds in the CURRENT token's unquantized K/V at position
    ``t`` and writes the output block.

    Refs: q/kn/vn ``(1, 1, D)``; k/v ``(1, 1, 1, 1, ps, D)`` — the page
    the index map resolved via the prefetched table; int8 adds two
    ``(1, 1, 1, 1)`` scale refs. Scratch: m/l ``(1, 1)``, acc ``(1, D)``.
    """
    rest = list(rest)
    ks_ref = rest.pop(0) if quantized else None
    vs_ref = rest.pop(0) if quantized else None
    o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    s = pl.program_id(2)
    ps = page_size

    @pl.when(s == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    t = t_ref[b]
    page_start = s * ps
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale        # (D,)

    @pl.when(page_start < t)                 # live page: stream it
    def _stream():
        k_blk = k_ref[0, 0, 0, 0].astype(jnp.float32)     # (ps, D)
        v_blk = v_ref[0, 0, 0, 0].astype(jnp.float32)
        if quantized:
            k_blk = k_blk * ks_ref[0, 0, 0, 0]
            v_blk = v_blk * vs_ref[0, 0, 0, 0]
        logits = jnp.dot(k_blk, q, preferred_element_type=jnp.float32)
        pos = page_start + jax.lax.broadcasted_iota(jnp.int32, (ps,), 0)
        logits = jnp.where(pos < t, logits, _NEG_INF)
        m_prev = m_ref[0, 0]
        m_new = jnp.maximum(m_prev, jnp.max(logits))
        p = jnp.exp(logits - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[0, 0] = alpha * l_ref[0, 0] + jnp.sum(p)
        acc_ref[0, :] = alpha * acc_ref[0, :] + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32)
        m_ref[0, 0] = m_new

    @pl.when(s == num_pages - 1)             # fold in position t, emit
    def _finish():
        kn = kn_ref[0, 0].astype(jnp.float32)
        vn = vn_ref[0, 0].astype(jnp.float32)
        logit_t = jnp.dot(q, kn, preferred_element_type=jnp.float32)
        m_prev = m_ref[0, 0]
        m_new = jnp.maximum(m_prev, logit_t)
        p_t = jnp.exp(logit_t - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_fin = alpha * l_ref[0, 0] + p_t
        acc = alpha * acc_ref[0, :] + p_t * vn
        o_ref[0, 0] = (acc / jnp.maximum(l_fin, 1e-30)).astype(o_ref.dtype)


def _kernel_call(q, k_new, v_new, pool, scales, tables, t, layer,
                 page_size: int, interpret: bool):
    """q ``(B, H, D)``, k/v_new ``(B, H_kv, D)``, pool
    ``(P, L, 2, H_kv, ps, D)`` → out ``(B, H, D)`` in q.dtype. GQA via
    ``rep = H // H_kv`` folded into the index maps (no repeat buffer)."""
    b, h, d = q.shape
    h_kv = pool.shape[3]
    rep = h // h_kv
    s = tables.shape[1]
    ps = page_size
    quantized = scales is not None
    sm_scale = 1.0 / float(d) ** 0.5
    kern = functools.partial(_decode_kernel, page_size=ps,
                             sm_scale=sm_scale, num_pages=s,
                             quantized=quantized)

    def q_map(bi, hi, si, tabs, tt, lr):
        return (bi, hi, 0)

    def kvn_map(bi, hi, si, tabs, tt, lr):
        return (bi, hi // rep, 0)

    def page_map(kv):
        def f(bi, hi, si, tabs, tt, lr):
            return (tabs[bi, si], lr[0], kv, hi // rep, 0, 0)
        return f

    def scale_map(kv):
        def f(bi, hi, si, tabs, tt, lr):
            return (tabs[bi, si], lr[0], kv, hi // rep)
        return f

    in_specs = [
        pl.BlockSpec((1, 1, d), q_map),
        pl.BlockSpec((1, 1, d), kvn_map),
        pl.BlockSpec((1, 1, d), kvn_map),
        pl.BlockSpec((1, 1, 1, 1, ps, d), page_map(0)),
        pl.BlockSpec((1, 1, 1, 1, ps, d), page_map(1)),
    ]
    inputs = [q, k_new, v_new, pool, pool]
    if quantized:
        in_specs += [pl.BlockSpec((1, 1, 1, 1), scale_map(0)),
                     pl.BlockSpec((1, 1, 1, 1), scale_map(1))]
        inputs += [scales, scales]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, h, s),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((1, 1), jnp.float32),   # running max
            pltpu.VMEM((1, 1), jnp.float32),   # running denominator
            pltpu.VMEM((1, d), jnp.float32),   # weighted-V accumulator
        ],
    )
    layer_arr = jnp.asarray(layer, jnp.int32).reshape(1)
    return pl.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), t.astype(jnp.int32), layer_arr, *inputs)


# ---------------------------------------------------------------------------
# the per-layer dense tier (debug / parity reference / ineligible shapes)
# ---------------------------------------------------------------------------

def paged_attention_dense(q, k_new, v_new, pool, scales, tables, t, layer,
                          page_size: int):
    """Reference math for one layer: gather the slot's pages FOR THE
    DECODED LAYER ONLY (a flat ``(page, layer)`` take — the L-stacked
    dense cache still never exists), insert the current token, span-mask
    to ``<= t``, softmax. The kernel is pinned against this."""
    p_, l_, _, h_kv, ps, d = pool.shape
    b, s = tables.shape
    m = s * ps
    rep = q.shape[1] // h_kv
    idx = tables.astype(jnp.int32) * l_ + jnp.asarray(layer, jnp.int32)
    taken = jnp.take(pool.reshape(p_ * l_, 2, h_kv, ps, d), idx, axis=0)
    taken = taken.astype(jnp.float32)
    if scales is not None:
        sc = jnp.take(scales.reshape(p_ * l_, 2, h_kv), idx, axis=0)
        taken = taken * sc[..., None, None]
    # (B, S, 2, H_kv, ps, D) -> k/v (B, H_kv, M, D)
    k = taken[:, :, 0].transpose(0, 2, 1, 3, 4).reshape(b, h_kv, m, d)
    v = taken[:, :, 1].transpose(0, 2, 1, 3, 4).reshape(b, h_kv, m, d)
    t32 = t.astype(jnp.int32)
    onehot = jax.nn.one_hot(t32, m, dtype=jnp.bool_)[:, None, :, None]
    k = jnp.where(onehot, k_new.astype(jnp.float32)[:, :, None, :], k)
    v = jnp.where(onehot, v_new.astype(jnp.float32)[:, :, None, :], v)
    if rep > 1:
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    qf = q.astype(jnp.float32)
    logits = jnp.einsum("bhd,bhld->bhl", qf, k) / float(d) ** 0.5
    span = jnp.arange(m, dtype=jnp.int32)[None, :] <= t32[:, None]
    logits = jnp.where(span[:, None, :], logits, _NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhl,bhld->bhd", p, v).astype(q.dtype)


def paged_attention(q, k_new, v_new, pool, scales, tables, t, layer, *,
                    page_size: int, impl: str = "kernel",
                    interpret: bool = False):
    """Decode attention for one layer over the page pool. Dispatches the
    streaming kernel or the per-layer dense tier; the compiled TPU kernel
    additionally requires :func:`kernel_eligible` tiling (interpret mode
    has no tiling constraints)."""
    if impl == "kernel" and (interpret or kernel_eligible(
            page_size, int(pool.shape[-1]), pool.dtype)):
        return _kernel_call(q, k_new, v_new, pool, scales, tables, t,
                            layer, page_size, interpret)
    return paged_attention_dense(q, k_new, v_new, pool, scales, tables, t,
                                 layer, page_size)


# ---------------------------------------------------------------------------
# the in-place token write
# ---------------------------------------------------------------------------

def scatter_token_inplace(pool, scales, tables, t, layer, k_new, v_new,
                          page_size: int):
    """Write position ``t``'s K/V into the containing pool page for one
    layer — no dense round-trip. Returns ``(pool', scales')``.

    bf16/native: a single-position scatter (O(1) rows per slot). int8:
    the kv_cache requantization contract — the containing page is
    gathered, dequantized under its old scale, the token inserted,
    positions ``> t`` zeroed, and the page re-quantized — the exact math
    of ``scatter_token_page``, sourced from the pool."""
    ps = page_size
    t32 = t.astype(jnp.int32)
    l32 = jnp.asarray(layer, jnp.int32)
    pids = jnp.take_along_axis(tables.astype(jnp.int32),
                               (t32 // ps)[:, None], axis=1)[:, 0]  # (B,)
    off = t32 % ps
    kv_new = jnp.stack([k_new, v_new], axis=1)          # (B, 2, H_kv, D)
    if scales is None:
        return pool.at[pids, l32, :, :, off, :].set(
            kv_new.astype(pool.dtype)), None
    from ..serving.kv_cache import quantize_pages
    p_, l_ = pool.shape[0], pool.shape[1]
    flat_idx = pids * l_ + l32
    page = jnp.take(pool.reshape((p_ * l_,) + pool.shape[2:]), flat_idx,
                    axis=0).astype(jnp.float32)          # (B, 2, H, ps, D)
    old_sc = jnp.take(scales.reshape(p_ * l_, *scales.shape[2:]), flat_idx,
                      axis=0)                            # (B, 2, H)
    page = page * old_sc[..., None, None]
    sel = jax.nn.one_hot(off, ps, dtype=jnp.bool_)[:, None, None, :, None]
    page = jnp.where(sel, kv_new.astype(jnp.float32)[..., None, :], page)
    pos = (t32 // ps * ps)[:, None] + jnp.arange(ps, dtype=jnp.int32)[None]
    valid = pos <= t32[:, None]                          # (B, ps)
    page = jnp.where(valid[:, None, None, :, None], page, 0.0)
    q8, sc = quantize_pages(page)                        # (B,2,H,ps,D)/(B,2,H)
    return (pool.at[pids, l32].set(q8.astype(pool.dtype)),
            scales.at[pids, l32].set(sc))


# ---------------------------------------------------------------------------
# Tensor-level surface (the op models call)
# ---------------------------------------------------------------------------

def paged_decode_attention(q, k_new, v_new, cache: PagedDecodeCache):
    """One layer's cached decode attention over the paged pool.

    ``q`` ``(B, H, D)``, ``k_new``/``v_new`` ``(B, H_kv, D)`` Tensors (the
    CURRENT token's projections, attended unquantized at position ``t``);
    ``cache`` must carry a ``layer``. Returns ``(out (B, H, D) Tensor,
    cache')`` with the token written into the pool — the decode-step
    sequence the dense path got from gather → step → scatter, now
    page-local."""
    from ..core.tensor import apply
    from ._helpers import ensure_tensor
    if cache.layer is None:
        raise ValueError("paged_decode_attention: cache.layer is unset — "
                         "derive a per-layer view with cache.at_layer(i)")
    q, k_new, v_new = (ensure_tensor(x) for x in (q, k_new, v_new))
    layer_t = ensure_tensor(cache.layer).astype("int32")
    quantized = cache.scales is not None
    ps, impl, interpret = cache.page_size, cache.impl, cache.interpret

    def f(qa, kna, vna, pool, tables, t, layer, *maybe_scales):
        sc = maybe_scales[0] if quantized else None
        out = paged_attention(qa, kna, vna, pool, sc, tables, t, layer,
                              page_size=ps, impl=impl, interpret=interpret)
        pool2, sc2 = scatter_token_inplace(pool, sc, tables, t, layer,
                                           kna, vna, page_size=ps)
        return (out, pool2) + ((sc2,) if quantized else ())

    args = [q, k_new, v_new, cache.pool, cache.tables, cache.t,
            layer_t] + ([cache.scales] if quantized else [])
    outs = apply("paged_attention_decode", f, *args, differentiable=False,
                 amp=False)
    new_cache = replace(cache, pool=outs[1],
                        scales=outs[2] if quantized else None)
    return outs[0], new_cache
