"""Activation ops (functional layer backs nn.functional).

Parity surface: python/paddle/nn/functional/activation.py + phi activation
kernels. One jnp/jax.nn call each; XLA fuses them into adjacent matmuls on
TPU, which is the whole fusion story the reference needs fused kernels for.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply
from ._helpers import ensure_tensor, make_unary, register_op

relu = make_unary("relu", jax.nn.relu, inplace="relu_")
relu6 = make_unary("relu6", jax.nn.relu6)
silu = make_unary("silu", jax.nn.silu)
swish = make_unary("swish", jax.nn.silu)
softsign = make_unary("softsign", jax.nn.soft_sign)
tanhshrink = make_unary("tanhshrink", lambda x: x - jnp.tanh(x))
mish = make_unary("mish", lambda x: x * jnp.tanh(jax.nn.softplus(x)))
hardswish = make_unary("hardswish", jax.nn.hard_swish)
def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    """Upstream contract: max(0, min(1, slope * x + offset)) — the default
    slope/offset (1/6, 0.5) matches the fixed formula this op used before."""
    return apply("hardsigmoid",
                 lambda a: jnp.clip(slope * a + offset, 0.0, 1.0),
                 ensure_tensor(x))


register_op("hardsigmoid", hardsigmoid)
log_sigmoid = make_unary("log_sigmoid", jax.nn.log_sigmoid)


def gelu(x, approximate=False, name=None):
    x = ensure_tensor(x)
    return apply("gelu", lambda a: jax.nn.gelu(a, approximate=approximate), x)


register_op("gelu", gelu)


def softmax(x, axis=-1, dtype=None, name=None):
    x = ensure_tensor(x)

    def f(a):
        if dtype is not None:
            a = a.astype(jnp.dtype(dtype))
        return jax.nn.softmax(a, axis=axis)

    return apply("softmax", f, x)


register_op("softmax", softmax, methods=("softmax",))


def log_softmax(x, axis=-1, dtype=None, name=None):
    x = ensure_tensor(x)

    def f(a):
        if dtype is not None:
            a = a.astype(jnp.dtype(dtype))
        return jax.nn.log_softmax(a, axis=axis)

    return apply("log_softmax", f, x)


register_op("log_softmax", log_softmax)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    x = ensure_tensor(x)
    return apply("softplus",
                 lambda a: jnp.where(a * beta > threshold, a,
                                     (1.0 / beta) * jnp.log1p(jnp.exp(beta * a))), x)


register_op("softplus", softplus)


def leaky_relu(x, negative_slope=0.01, name=None):
    x = ensure_tensor(x)
    return apply("leaky_relu", lambda a: jax.nn.leaky_relu(a, negative_slope), x)


register_op("leaky_relu", leaky_relu)


def elu(x, alpha=1.0, name=None):
    x = ensure_tensor(x)
    return apply("elu", lambda a: jax.nn.elu(a, alpha), x)


register_op("elu", elu)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    x = ensure_tensor(x)
    return apply("selu", lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), x)


register_op("selu", selu)


def celu(x, alpha=1.0, name=None):
    x = ensure_tensor(x)
    return apply("celu", lambda a: jax.nn.celu(a, alpha), x)


register_op("celu", celu)


def prelu(x, weight, data_format="NCHW", name=None):
    x, weight = ensure_tensor(x), ensure_tensor(weight)

    def f(a, w):
        if w.size > 1:
            shape = [1] * a.ndim
            ch_axis = 1 if data_format[1] == "C" else a.ndim - 1
            shape[ch_axis] = w.size
            w = w.reshape(shape)
        return jnp.where(a > 0, a, w * a)

    return apply("prelu", f, x, weight)


register_op("prelu", prelu)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    x = ensure_tensor(x)
    return apply("hardtanh", lambda a: jnp.clip(a, min, max), x)


register_op("hardtanh", hardtanh)


def hardshrink(x, threshold=0.5, name=None):
    x = ensure_tensor(x)
    return apply("hardshrink",
                 lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), x)


register_op("hardshrink", hardshrink)


def softshrink(x, threshold=0.5, name=None):
    x = ensure_tensor(x)
    return apply("softshrink",
                 lambda a: jnp.where(a > threshold, a - threshold,
                                     jnp.where(a < -threshold, a + threshold, 0.0)), x)


register_op("softshrink", softshrink)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    x = ensure_tensor(x)
    return apply("thresholded_relu", lambda a: jnp.where(a > threshold, a, value), x)


register_op("thresholded_relu", thresholded_relu)


def glu(x, axis=-1, name=None):
    x = ensure_tensor(x)
    return apply("glu", lambda a: jax.nn.glu(a, axis=axis), x)


register_op("glu", glu)


def maxout(x, groups, axis=1, name=None):
    x = ensure_tensor(x)

    def f(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(new_shape), axis=ax + 1)

    return apply("maxout", f, x)


register_op("maxout", maxout)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ..core.random import default_generator
    x = ensure_tensor(x)
    key = default_generator.split_key()

    def f(a):
        g = jax.random.gumbel(key, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.put_along_axis(jnp.zeros_like(y), idx,
                                        jnp.ones_like(idx, y.dtype), axis=axis,
                                        inplace=False)
            # straight-through estimator
            y = y_hard - jax.lax.stop_gradient(y) + y
        return y

    return apply("gumbel_softmax", f, x)


register_op("gumbel_softmax", gumbel_softmax)
