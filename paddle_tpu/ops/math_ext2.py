"""Third wave of tensor-surface parity ops: stacking/splitting families,
special functions, scatter views, and assorted aliases.

Parity surface: python/paddle/tensor/{math,manipulation,creation}.py tail.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply, register_tensor_method, to_tensor
from ._helpers import ensure_tensor, register_op


# --- stacking / splitting ----------------------------------------------------

def _multi(name, jfn, tensors):
    ts = [ensure_tensor(t) for t in tensors]
    return apply(name, lambda *arrs: jfn(arrs), *ts)


def hstack(x, name=None):
    return _multi("hstack", jnp.hstack, x)


def vstack(x, name=None):
    return _multi("vstack", jnp.vstack, x)


def dstack(x, name=None):
    return _multi("dstack", jnp.dstack, x)


def column_stack(x, name=None):
    return _multi("column_stack", jnp.column_stack, x)


def row_stack(x, name=None):
    return _multi("row_stack", jnp.vstack, x)


def block_diag(inputs, name=None):
    ts = [ensure_tensor(t) for t in inputs]
    return apply("block_diag",
                 lambda *arrs: jax.scipy.linalg.block_diag(
                     *[a if a.ndim >= 2 else a.reshape(1, -1) for a in arrs]),
                 *ts)


def _split_sections(name, jfn, x, num_or_sections, axis_fixed=None):
    x = ensure_tensor(x)
    out = apply(name, lambda a: tuple(jfn(a, num_or_sections)), x)
    return list(out) if isinstance(out, tuple) else [out]


def hsplit(x, num_or_indices, name=None):
    return _split_sections("hsplit", jnp.hsplit, x, num_or_indices)


def vsplit(x, num_or_indices, name=None):
    return _split_sections("vsplit", jnp.vsplit, x, num_or_indices)


def dsplit(x, num_or_indices, name=None):
    return _split_sections("dsplit", jnp.dsplit, x, num_or_indices)


def tensor_split(x, num_or_indices, axis=0, name=None):
    x = ensure_tensor(x)
    out = apply("tensor_split",
                lambda a: tuple(jnp.array_split(
                    a, num_or_indices if isinstance(num_or_indices, int)
                    else list(num_or_indices), axis=axis)), x)
    return list(out) if isinstance(out, tuple) else [out]


def atleast_1d(*inputs, name=None):
    outs = [apply("atleast_1d", jnp.atleast_1d, ensure_tensor(t))
            for t in inputs]
    return outs if len(outs) > 1 else outs[0]


def atleast_2d(*inputs, name=None):
    outs = [apply("atleast_2d", jnp.atleast_2d, ensure_tensor(t))
            for t in inputs]
    return outs if len(outs) > 1 else outs[0]


def atleast_3d(*inputs, name=None):
    outs = [apply("atleast_3d", jnp.atleast_3d, ensure_tensor(t))
            for t in inputs]
    return outs if len(outs) > 1 else outs[0]


def unflatten(x, axis, shape, name=None):
    x = ensure_tensor(x)
    shp = [int(s._data) if isinstance(s, Tensor) else int(s) for s in shape]

    def f(a):
        ax = axis if axis >= 0 else axis + a.ndim
        return a.reshape(a.shape[:ax] + tuple(shp) + a.shape[ax + 1:])

    return apply("unflatten", f, x)


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


# --- scatter views -----------------------------------------------------------

def scatter_nd(index, updates, shape, name=None):
    """Scatter ``updates`` into zeros of ``shape`` at nd ``index`` (adds on
    duplicates, matching the reference kernel)."""
    index, updates = ensure_tensor(index), ensure_tensor(updates)
    shp = tuple(int(s._data) if isinstance(s, Tensor) else int(s)
                for s in shape)

    def f(idx, upd):
        zeros = jnp.zeros(shp, upd.dtype)
        return zeros.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)

    return apply("scatter_nd", f, index, updates)


def select_scatter(x, values, axis, index, name=None):
    """Write ``values`` into slice ``index`` of ``axis``."""
    x, values = ensure_tensor(x), ensure_tensor(values)

    def f(a, v):
        moved = jnp.moveaxis(a, axis, 0)
        out = moved.at[index].set(v)
        return jnp.moveaxis(out, 0, axis)

    return apply("select_scatter", f, x, values)


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    """Write ``value`` into the strided slice of ``x``."""
    x, value = ensure_tensor(x), ensure_tensor(value)
    sl = [slice(None)] * x._data.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        sl[int(ax)] = slice(int(st), int(en), int(sd))
    sl = tuple(sl)

    def f(a, v):
        return a.at[sl].set(v)

    return apply("slice_scatter", f, x, value)


def take(x, index, mode="raise", name=None):
    """Flat-index gather with raise/wrap/clip bounds modes."""
    x, index = ensure_tensor(x), ensure_tensor(index)
    n = int(np.prod(x._data.shape)) if x._data.shape else 1
    from ..core.tensor import _is_tracer
    if mode == "raise" and not _is_tracer(index._data):
        idx = np.asarray(index._data)
        if idx.size and (idx.max() >= n or idx.min() < -n):
            raise IndexError(
                f"take: index out of range for {n} elements "
                f"(min {idx.min()}, max {idx.max()})")

    def f(a, i):
        flat = a.reshape(-1)
        if mode == "wrap":
            i = i % n
        elif mode == "clip":
            i = jnp.clip(i, -n, n - 1)
        return flat[i]

    return apply("take", f, x, index)


# --- special functions -------------------------------------------------------

def i0e(x, name=None):
    return apply("i0e", jax.scipy.special.i0e, ensure_tensor(x))


def i1e(x, name=None):
    return apply("i1e", jax.scipy.special.i1e, ensure_tensor(x))


def polygamma(x, n, name=None):
    x = ensure_tensor(x)
    return apply("polygamma",
                 lambda a: jax.scipy.special.polygamma(int(n), a), x)


def multigammaln(x, p, name=None):
    return apply("multigammaln",
                 lambda a: jax.scipy.special.multigammaln(a, int(p)),
                 ensure_tensor(x))


def gammaln(x, name=None):
    return apply("gammaln", jax.scipy.special.gammaln, ensure_tensor(x))


def gammainc(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply("gammainc", jax.scipy.special.gammainc, x, y)


def gammaincc(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply("gammaincc", jax.scipy.special.gammaincc, x, y)


def logit(x, eps=None, name=None):
    x = ensure_tensor(x)

    def f(a):
        p = a if eps is None else jnp.clip(a, eps, 1.0 - eps)
        return jnp.log(p) - jnp.log1p(-p)

    return apply("logit", f, x)


def logaddexp2(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply("logaddexp2", jnp.logaddexp2, x, y)


def histogram_bin_edges(input, bins=100, min=0, max=0, name=None):
    input = ensure_tensor(input)

    def f(a):
        lo, hi = (float(min), float(max)) if (min != 0 or max != 0) else \
            (None, None)
        if lo is None:
            return jnp.histogram_bin_edges(a, bins=int(bins))
        return jnp.histogram_bin_edges(a, bins=int(bins), range=(lo, hi))

    return apply("histogram_bin_edges", f, input, differentiable=False)


# --- simple aliases ----------------------------------------------------------

def positive(x, name=None):
    return apply("positive", lambda a: +a, ensure_tensor(x))


def negative(x, name=None):
    return apply("negative", jnp.negative, ensure_tensor(x))


def diagflat(x, offset=0, name=None):
    return apply("diagflat", lambda a: jnp.diagflat(a, k=offset),
                 ensure_tensor(x))


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    input = ensure_tensor(input)

    def f(a):
        n = a.shape[-1] + abs(int(offset))
        out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        rows = jnp.arange(a.shape[-1]) + max(-offset, 0)
        cols = jnp.arange(a.shape[-1]) + max(offset, 0)
        out = out.at[..., rows, cols].set(a)
        if (dim1, dim2) != (-2, -1):
            nd = out.ndim
            d1, d2 = dim1 % nd, dim2 % nd
            out = jnp.moveaxis(out, (nd - 2, nd - 1), (d1, d2))
        return out

    return apply("diag_embed", f, input)


def matrix_transpose(x, name=None):
    return apply("matrix_transpose", lambda a: jnp.swapaxes(a, -1, -2),
                 ensure_tensor(x))


def svdvals(x, name=None):
    return apply("svdvals",
                 lambda a: jnp.linalg.svd(a, compute_uv=False),
                 ensure_tensor(x))


register_op("hstack", hstack)
register_op("vstack", vstack)
register_op("dstack", dstack)
register_op("column_stack", column_stack)
register_op("row_stack", row_stack)
register_op("block_diag", block_diag)
register_op("hsplit", hsplit)
register_op("vsplit", vsplit)
register_op("dsplit", dsplit)
register_op("tensor_split", tensor_split, methods=("tensor_split",))
register_op("atleast_1d", atleast_1d)
register_op("atleast_2d", atleast_2d)
register_op("atleast_3d", atleast_3d)
register_op("unflatten", unflatten, methods=("unflatten",))
register_op("broadcast_shape", broadcast_shape)
register_op("scatter_nd", scatter_nd)
register_op("select_scatter", select_scatter, methods=("select_scatter",))
register_op("slice_scatter", slice_scatter, methods=("slice_scatter",))
register_op("take", take, methods=("take",))
register_op("i0e", i0e, methods=("i0e",))
register_op("i1e", i1e, methods=("i1e",))
register_op("polygamma", polygamma, methods=("polygamma",))
register_op("multigammaln", multigammaln, methods=("multigammaln",))
register_op("gammaln", gammaln, methods=("gammaln",))
register_op("gammainc", gammainc, methods=("gammainc",))
register_op("gammaincc", gammaincc, methods=("gammaincc",))
register_op("logit", logit, methods=("logit",))
register_op("logaddexp2", logaddexp2, methods=("logaddexp2",))
register_op("histogram_bin_edges", histogram_bin_edges)
register_op("positive", positive, methods=("positive",))
register_op("negative", negative, methods=("negative",))
register_op("diagflat", diagflat, methods=("diagflat",))
register_op("diag_embed", diag_embed, methods=("diag_embed",))
register_op("matrix_transpose", matrix_transpose,
            methods=("matrix_transpose",))
register_op("svdvals", svdvals)


# aliases onto already-registered ops
from ._helpers import OP_REGISTRY as _REG  # noqa: E402

register_op("bitwise_invert", _REG["bitwise_not"])
register_tensor_method("inverse", _REG["inv"])
register_op("inverse", _REG["inv"])
register_tensor_method("cross", _REG["cross"])
register_tensor_method("searchsorted",
                       lambda self, values, out_int32=False, right=False:
                       _REG["searchsorted"](self, values, out_int32, right))


def _inplace(method_name, op_name):
    fn = _REG[op_name]

    def m(self, *args, **kwargs):
        return self._rebind(fn(self, *args, **kwargs))

    m.__name__ = method_name
    register_tensor_method(method_name, m)


_inplace("put_along_axis_", "put_along_axis")
_inplace("transpose_", "transpose")
_inplace("flatten_", "flatten") if "flatten" in _REG else None


# Tensor protocol / inplace tail
def _tensor_dlpack(self, stream=None, **kwargs):
    from ..utils.dlpack import to_dlpack
    return to_dlpack(self)


register_tensor_method("__dlpack__", _tensor_dlpack)
register_tensor_method("__dlpack_device__",
                       lambda self: self._data.__dlpack_device__())
_inplace("sigmoid_", "sigmoid")
