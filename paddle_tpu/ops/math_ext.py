"""Extended tensor math/manipulation ops.

Parity surface: the long tail of python/paddle/tensor/{math,manipulation,
search,random}.py — pairwise distance, bit/float classification, diagonal
scatter family, strided views, nucleus sampling. All static-shape,
XLA-friendly implementations (index grids precomputed at trace time).
"""

from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp
import numpy as np

from ..core.random import default_generator
from ..core.tensor import Tensor, apply, register_tensor_method, to_tensor
from ._helpers import ensure_tensor, register_op


# --- pairwise distance -------------------------------------------------------

def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    """Batched p-norm pairwise distance (reference: paddle.cdist).

    For p=2 the distance is computed through one batched matmul (MXU path)
    instead of the O(P*R*M) broadcasted difference, unless compute_mode
    forbids it.
    """
    x, y = ensure_tensor(x), ensure_tensor(y)
    # the mm trick loses ~1e-3 to cancellation; default to it only when the
    # pair count is large enough that the O(P*R*M) broadcast would dominate
    big = int(x._data.shape[-2]) * int(y._data.shape[-2]) > 64 * 64
    use_mm = p == 2.0 and (
        compute_mode == "use_mm_for_euclid_dist"
        or (compute_mode == "use_mm_for_euclid_dist_if_necessary" and big))

    def f(a, b):
        if use_mm:
            # |a-b|^2 = |a|^2 + |b|^2 - 2 a.b  (clamped for fp error)
            a2 = jnp.sum(a * a, axis=-1, keepdims=True)
            b2 = jnp.sum(b * b, axis=-1, keepdims=True)
            sq = a2 + jnp.swapaxes(b2, -1, -2) - 2.0 * (a @ jnp.swapaxes(b, -1, -2))
            return jnp.sqrt(jnp.clip(sq, 0.0, None))
        d = jnp.abs(a[..., :, None, :] - b[..., None, :, :])
        if p == 0:
            return jnp.sum((d != 0).astype(a.dtype), axis=-1)
        if p == float("inf"):
            return jnp.max(d, axis=-1)
        return jnp.sum(d ** p, axis=-1) ** (1.0 / p)

    return apply("cdist", f, x, y)


# --- elementwise float/bit classification ------------------------------------

def ldexp(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)

    def f(a, b):
        out_dt = a.dtype if jnp.issubdtype(a.dtype, jnp.floating) else jnp.float32
        # jnp.ldexp scales by the exponent directly (no exp2 overflow)
        return jnp.ldexp(a.astype(out_dt), b.astype(jnp.int32))

    return apply("ldexp", f, x, y)


def signbit(x, name=None):
    return apply("signbit", jnp.signbit, ensure_tensor(x), differentiable=False)


def isposinf(x, name=None):
    return apply("isposinf", jnp.isposinf, ensure_tensor(x), differentiable=False)


def isneginf(x, name=None):
    return apply("isneginf", jnp.isneginf, ensure_tensor(x), differentiable=False)


def isreal(x, name=None):
    return apply("isreal", jnp.isreal, ensure_tensor(x), differentiable=False)


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    x, test_x = ensure_tensor(x), ensure_tensor(test_x)
    return apply("isin",
                 lambda a, t: jnp.isin(a, t, assume_unique=assume_unique,
                                       invert=invert),
                 x, test_x, differentiable=False)


# --- renorm ------------------------------------------------------------------

def renorm(x, p, axis, max_norm, name=None):
    """Renormalize slices along ``axis`` whose p-norm exceeds ``max_norm``."""
    x = ensure_tensor(x)

    def f(a):
        ax = axis if axis >= 0 else axis + a.ndim
        reduce_axes = tuple(i for i in range(a.ndim) if i != ax)
        if p == float("inf"):
            norms = jnp.max(jnp.abs(a), axis=reduce_axes, keepdims=True)
        else:
            norms = jnp.sum(jnp.abs(a) ** p, axis=reduce_axes,
                            keepdims=True) ** (1.0 / p)
        scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return a * scale.astype(a.dtype)

    return apply("renorm", f, x)


# --- combinations ------------------------------------------------------------

def combinations(x, r=2, with_replacement=False, name=None):
    """All length-r combinations of a 1-D tensor (static index grid)."""
    x = ensure_tensor(x)
    n = int(x._data.shape[0])
    gen = (itertools.combinations_with_replacement if with_replacement
           else itertools.combinations)
    idx = np.array(list(gen(range(n), r)), dtype=np.int32).reshape(-1, r)
    return apply("combinations", lambda a: a[jnp.asarray(idx)], x)


# --- diagonal writes ---------------------------------------------------------

def _diag_index_grid(shape, offset, dim1, dim2):
    """Static (rows, cols, diag_len) index arrays for a matrix diagonal."""
    h, w = shape[dim1], shape[dim2]
    if offset >= 0:
        n = max(min(h, w - offset), 0)
        rows, cols = np.arange(n), np.arange(n) + offset
    else:
        n = max(min(h + offset, w), 0)
        rows, cols = np.arange(n) - offset, np.arange(n)
    return rows, cols, n


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    """In-place diagonal fill (reference: Tensor.fill_diagonal_)."""
    a = x._data
    if a.ndim == 2 and wrap and offset == 0:
        h, w = a.shape
        flat = np.arange(0, h * w, w + 1)  # numpy fill_diagonal wrap layout
        rows, cols = flat // w, flat % w
    else:
        rows, cols, n = _diag_index_grid(a.shape[:2] if a.ndim == 2 else a.shape,
                                         offset, 0, 1)
        if a.ndim > 2:
            # paddle requires all dims equal for ndim>2; fill the main diagonal
            n = min(a.shape)
            idx = tuple(jnp.arange(n) for _ in range(a.ndim))
            x._set_data(a.at[idx].set(value))
            return x
    x._set_data(a.at[jnp.asarray(rows), jnp.asarray(cols)].set(value))
    return x


def _diagonal_scatter_impl(a, b, offset, axis1, axis2):
    ax1 = axis1 if axis1 >= 0 else axis1 + a.ndim
    ax2 = axis2 if axis2 >= 0 else axis2 + a.ndim
    perm = [i for i in range(a.ndim) if i not in (ax1, ax2)] + [ax1, ax2]
    inv = np.argsort(perm)
    m = jnp.transpose(a, perm)          # (..., H, W)
    rows, cols, n = _diag_index_grid(m.shape[-2:] if m.ndim >= 2 else m.shape,
                                     offset, -2, -1)
    m = m.at[..., jnp.asarray(rows), jnp.asarray(cols)].set(b)
    return jnp.transpose(m, inv)


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    """Embed ``y`` into the (offset, axis1, axis2) diagonal of ``x``."""
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply("diagonal_scatter",
                 lambda a, b: _diagonal_scatter_impl(a, b, offset, axis1, axis2),
                 x, y)


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    return diagonal_scatter(x, y, offset=offset, axis1=dim1, axis2=dim2)


def fill_diagonal_tensor_(x, y, offset=0, dim1=0, dim2=1, name=None):
    out = fill_diagonal_tensor(x, y, offset, dim1, dim2)
    return x._rebind(out)


# --- strided views -----------------------------------------------------------

def tensor_unfold(x, axis, size, step, name=None):
    """Sliding windows along ``axis`` (reference: Tensor.unfold): the output
    gains a trailing window dimension of length ``size``."""
    x = ensure_tensor(x)
    ax = axis if axis >= 0 else axis + x._data.ndim
    length = int(x._data.shape[ax])
    starts = np.arange(0, length - size + 1, step, dtype=np.int32)
    idx = starts[:, None] + np.arange(size, dtype=np.int32)[None, :]

    def f(a):
        w = jnp.take(a, jnp.asarray(idx), axis=ax)  # (..., nwin, size, ...)
        # move the window-content dim to the end
        return jnp.moveaxis(w, ax + 1, -1)

    return apply("unfold_tensor", f, x)


def as_strided(x, shape, stride, offset=0, name=None):
    """View with explicit strides over the flat buffer (gather-based)."""
    x = ensure_tensor(x)
    shape = tuple(int(s) for s in shape)
    stride = tuple(int(s) for s in stride)
    grids = np.indices(shape).reshape(len(shape), -1)
    flat = offset + sum(g * s for g, s in zip(grids, stride))
    flat = jnp.asarray(flat.astype(np.int32))

    def f(a):
        return jnp.take(a.reshape(-1), flat).reshape(shape)

    return apply("as_strided", f, x)


def strides(x, name=None):
    """Element strides of ``x`` (reference: Tensor.strides / get_strides).

    XLA buffers are always dense row-major, so the strides are the
    canonical C-contiguous ones derived from the shape — in ELEMENTS, like
    the reference (numpy reports bytes; divide its strides by itemsize to
    compare)."""
    x = ensure_tensor(x)
    out, acc = [], 1
    for s in reversed(x._data.shape):
        out.append(acc)
        acc *= int(s)
    out.reverse()
    return out


def is_contiguous(x, name=None):
    """Always True (reference: Tensor.is_contiguous): jax arrays carry no
    user-visible stride permutations — ``as_strided`` and friends gather
    into fresh dense buffers instead of aliasing."""
    ensure_tensor(x)
    return True


def view_as(x, other, name=None):
    x, other = ensure_tensor(x), ensure_tensor(other)
    shp = tuple(other._data.shape)
    return apply("view_as", lambda a: a.reshape(shp), x)


# --- sampling ----------------------------------------------------------------

def standard_gamma(x, name=None):
    """Draw Gamma(alpha=x, scale=1) samples (reference: paddle.standard_gamma)."""
    x = ensure_tensor(x)
    key = default_generator.split_key()
    return apply("standard_gamma",
                 lambda a: jax.random.gamma(key, a).astype(a.dtype), x,
                 differentiable=False)


def top_p_sampling(x, ps, threshold=None, topp_seed=None, seed=-1, k=0,
                   mode="truncated", return_top=False, name=None):
    """Nucleus (top-p) sampling over the last axis of logits ``x``.

    Returns (values, ids) like the reference fused op. Probability mass
    outside the smallest prefix with cumulative prob >= ps is zeroed.
    """
    x, ps = ensure_tensor(x), ensure_tensor(ps)
    key = default_generator.split_key()

    def f(logits, p):
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        sorted_probs = jnp.sort(probs, axis=-1)[..., ::-1]
        sorted_idx = jnp.argsort(probs, axis=-1)[..., ::-1]
        cum = jnp.cumsum(sorted_probs, axis=-1)
        pcol = p.reshape(p.shape + (1,) * (cum.ndim - p.ndim))
        # keep the first token always; drop once cumulative mass (excl self)
        # has already reached p
        keep = (cum - sorted_probs) < pcol
        masked = jnp.where(keep, sorted_probs, 0.0)
        masked = masked / jnp.sum(masked, axis=-1, keepdims=True)
        choice = jax.random.categorical(key, jnp.log(masked + 1e-30), axis=-1)
        ids = jnp.take_along_axis(sorted_idx, choice[..., None], axis=-1)
        vals = jnp.take_along_axis(probs, ids, axis=-1)
        return vals.astype(logits.dtype), ids.astype(jnp.int64)

    out = apply("top_p_sampling", f, x, ps, differentiable=False)
    return tuple(out)


register_op("cdist", cdist, methods=("cdist",))
register_op("ldexp", ldexp, methods=("ldexp",))
register_op("signbit", signbit, methods=("signbit",))
register_op("isposinf", isposinf, methods=("isposinf",))
register_op("isneginf", isneginf, methods=("isneginf",))
register_op("isreal", isreal, methods=("isreal",))
register_op("isin", isin, methods=("isin",))
register_op("renorm", renorm, methods=("renorm",), inplace_method="renorm_")
register_op("combinations", combinations, methods=("combinations",))
register_op("diagonal_scatter", diagonal_scatter, methods=("diagonal_scatter",))
register_op("fill_diagonal_tensor", fill_diagonal_tensor,
            methods=("fill_diagonal_tensor",))
register_op("as_strided", as_strided, methods=("as_strided",))
register_op("strides", strides)
# Tensor.strides is an ATTRIBUTE upstream (t.strides, no call) while
# paddle.strides(t) is the functional spelling — install a property, not
# a method, so reference code reads it unparenthesized
register_tensor_method("strides", property(strides))
register_op("view_as", view_as, methods=("view_as",))
register_op("standard_gamma", standard_gamma)
register_op("top_p_sampling", top_p_sampling)

register_tensor_method("fill_diagonal_", fill_diagonal_)
register_tensor_method("fill_diagonal_tensor_", fill_diagonal_tensor_)
register_tensor_method("unfold", tensor_unfold)
# top-level paddle.unfold IS the sliding-window op (upstream
# python/paddle/tensor/manipulation.py unfold), NOT nn.functional.unfold's
# im2col — two different upstream APIs share the bare name
register_op("unfold", tensor_unfold)
register_tensor_method("contiguous", lambda self: self)
register_op("is_contiguous", is_contiguous, methods=("is_contiguous",))


# --- in-place random fills / scatter family ---------------------------------

def _inplace_random(x, sampler):
    key = default_generator.split_key()
    x._set_data(sampler(key).astype(x._data.dtype))
    return x


def cauchy_(x, loc=0, scale=1, name=None):
    """Fill with Cauchy samples (reference: Tensor.cauchy_)."""
    shape = tuple(x._data.shape)
    return _inplace_random(
        x, lambda k: loc + scale * jax.random.cauchy(k, shape))


def geometric_(x, probs, name=None):
    shape = tuple(x._data.shape)
    return _inplace_random(
        x, lambda k: jax.random.geometric(k, probs, shape).astype(jnp.float32))


def exponential_(x, lam=1.0, name=None):
    shape = tuple(x._data.shape)
    return _inplace_random(
        x, lambda k: jax.random.exponential(k, shape) / lam)


def log_normal_(x, mean=1.0, std=2.0, name=None):
    shape = tuple(x._data.shape)
    return _inplace_random(
        x, lambda k: jnp.exp(mean + std * jax.random.normal(k, shape)))


def bernoulli_(x, p=0.5, name=None):
    """Fill with Bernoulli(p) samples (reference: paddle.bernoulli_ /
    Tensor.bernoulli_; p may be a float or a broadcastable tensor)."""
    shape = tuple(x._data.shape)
    pv = p._data if isinstance(p, Tensor) else p
    return _inplace_random(
        x, lambda k: jax.random.bernoulli(k, pv, shape).astype(jnp.float32))


def index_fill(x, index, axis, value, name=None):
    """Fill the rows selected by ``index`` along ``axis`` with ``value``."""
    x, index = ensure_tensor(x), ensure_tensor(index)

    def f(a, idx):
        moved = jnp.moveaxis(a, axis, 0)
        filled = moved.at[idx].set(value)
        return jnp.moveaxis(filled, 0, axis)

    return apply("index_fill", f, x, index)


def index_fill_(x, index, axis, value, name=None):
    return x._rebind(index_fill(x, index, axis, value))


def masked_scatter(x, mask, value, name=None):
    """Copy elements of ``value`` (in order) into positions where ``mask``.

    Static-shape form: the k-th True position receives value.flat[k]."""
    x, mask, value = ensure_tensor(x), ensure_tensor(mask), ensure_tensor(value)
    from ..core.tensor import _is_tracer
    if not (_is_tracer(mask._data) or _is_tracer(value._data)):
        needed = int(np.asarray(
            jnp.broadcast_to(mask._data, x._data.shape)).sum())
        avail = int(np.prod(value._data.shape)) if value._data.shape else 1
        if avail < needed:
            raise ValueError(
                f"masked_scatter: mask selects {needed} elements but value "
                f"provides only {avail}")

    def f(a, m, v):
        mb = jnp.broadcast_to(m, a.shape).reshape(-1)
        flat = a.reshape(-1)
        # position of each element among the True entries
        order = jnp.cumsum(mb.astype(jnp.int32)) - 1
        src = v.reshape(-1)
        take = jnp.clip(order, 0, src.shape[0] - 1)
        return jnp.where(mb, src[take], flat).reshape(a.shape)

    return apply("masked_scatter", f, x, mask, value)


def masked_scatter_(x, mask, value, name=None):
    return x._rebind(masked_scatter(x, mask, value))


def _tensor_apply(x, func):
    """Elementwise python callable over the tensor (host round-trip;
    reference: Tensor.apply — documented as cpu-bound there too)."""
    arr = np.asarray(x._data)
    out = np.vectorize(func)(arr).astype(arr.dtype)
    return Tensor(jnp.asarray(out), stop_gradient=x.stop_gradient)


def _tensor_apply_(x, func):
    x._set_data(_tensor_apply(x, func)._data)
    return x


def _to_sparse_coo(x, sparse_dim=None):
    """reference: Tensor.to_sparse_coo(sparse_dim) — the FIRST sparse_dim
    dims become COO indices; trailing dims stay dense (the hybrid layout
    sparse Conv/BatchNorm consume: indices [N,H,W] or [N,D,H,W] with dense
    channel values)."""
    from ..sparse import sparse_coo_tensor
    arr = jnp.asarray(x._data)
    if sparse_dim is None or sparse_dim >= arr.ndim:
        nz = jnp.nonzero(arr)
        return sparse_coo_tensor(jnp.stack(nz), arr[nz], tuple(arr.shape))
    sd = int(sparse_dim)
    # a site is active when ANY trailing-dense element is nonzero
    mask = jnp.any(arr != 0, axis=tuple(range(sd, arr.ndim)))
    nz = jnp.nonzero(mask)
    return sparse_coo_tensor(jnp.stack(nz), arr[nz], tuple(arr.shape))


register_op("index_fill", index_fill, methods=("index_fill",))
register_op("masked_scatter", masked_scatter, methods=("masked_scatter",))
register_tensor_method("index_fill_", index_fill_)
register_tensor_method("masked_scatter_", masked_scatter_)
register_tensor_method("cauchy_", cauchy_)
register_tensor_method("geometric_", geometric_)
register_tensor_method("exponential_", exponential_)
register_tensor_method("log_normal_", log_normal_)
register_tensor_method("bernoulli_", bernoulli_)
register_op("bernoulli_", bernoulli_)
# top-level paddle.normal_ reuses the ONE in-place fill implementation
# (ops/creation.py normal_, already the Tensor.normal_ method)
from .creation import normal_ as _creation_normal_  # noqa: E402
register_op("normal_", _creation_normal_)
register_tensor_method("apply", _tensor_apply)
register_tensor_method("apply_", _tensor_apply_)
register_tensor_method("to_sparse_coo", _to_sparse_coo)
register_tensor_method("coalesce", lambda self: self)


def _dense_values(self):
    raise ValueError("Tensor.values() is only defined for sparse tensors; "
                     "use paddle.sparse.sparse_coo_tensor / to_sparse_coo()")


def _dense_indices(self):
    raise ValueError("Tensor.indices() is only defined for sparse tensors; "
                     "use paddle.sparse.sparse_coo_tensor / to_sparse_coo()")


register_tensor_method("values", _dense_values)
register_tensor_method("indices", _dense_indices)
