"""``paddle_tpu.resilience`` — unified failure handling for the framework.

Production TPU jobs fail in boring, recurring ways — a PS reply lost on
the wire, a rendezvous store socket reset, a worker preempted mid-
checkpoint — and before this layer every subsystem hand-rolled its own
recovery idiom (private backoff loops, fixed sleeps, ad-hoc reconnects).
This package centralizes the three pieces the ROADMAP's
"as many scenarios as you can imagine" goal needs:

* :mod:`~paddle_tpu.resilience.policy` — named :class:`RetryPolicy`
  objects (jittered exponential backoff, attempt caps, monotonic
  deadlines that propagate through nested calls via
  :class:`deadline_scope`), registry + ``PADDLE_TPU_RETRY_*`` env
  overrides, and :func:`jitter_sleep` for poll loops;
* :mod:`~paddle_tpu.resilience.breaker` — per-endpoint
  :class:`CircuitBreaker` (closed → open → half-open with cooldown) so a
  dead peer costs one fast :class:`BreakerOpen` instead of a connect
  timeout per attempt;
* :mod:`~paddle_tpu.resilience.faults` — deterministic
  :class:`FaultSchedule` injection (drop/delay/error/kill, scoped by
  site tag, seeded or scripted) threaded through the store client, rpc
  transport, PS service, checkpoint writer, serving engine, and the
  training supervisor — a no-op global probe when not installed;
* :mod:`~paddle_tpu.resilience.watchdog` — the monotonic-clock
  :class:`StepWatchdog` (extracted from serving in PR 10) that classifies
  a hung/zombie compiled call from a thread that cannot be wedged;
* :mod:`~paddle_tpu.resilience.trainer` — the fault-tolerant training
  supervisor: full resumable :class:`TrainState` (RNG, optimizer
  step+moments, LR-schedule position, dataloader cursor) through the
  verified-checkpoint writer, per-step retry/watchdog/NaN escalation,
  and restart-from-last-good with a bit-identical loss trajectory.

Everything is observable through :mod:`paddle_tpu.observability`:
``resilience.retries_total``, ``resilience.giveups_total``,
``resilience.breaker_state``, ``resilience.breaker_transitions_total``,
``resilience.injected_faults_total``, ``checkpoint.fallbacks_total``.
"""

from __future__ import annotations

from .policy import (DeadlineExceeded, RetryPolicy, current_deadline,
                     deadline_scope, get_policy, jitter_sleep,
                     register_policy, reset_policies)
from .breaker import (BreakerOpen, CircuitBreaker, breaker_for,
                      reset_breakers)
from .faults import (FaultInjected, FaultSchedule, KillPoint, fault_point,
                     install, installed, uninstall)
from .watchdog import StepWatchdog, WatchdogTimeout
from .trainer import (FaultTolerance, NonFiniteLossError, TrainAborted,
                      TrainState, TrainingSupervisor)

__all__ = [
    "RetryPolicy", "DeadlineExceeded", "deadline_scope", "current_deadline",
    "get_policy",
    "register_policy", "reset_policies", "jitter_sleep",
    "BreakerOpen", "CircuitBreaker", "breaker_for", "reset_breakers",
    "FaultInjected", "FaultSchedule", "KillPoint", "fault_point",
    "install", "installed", "uninstall",
    "StepWatchdog", "WatchdogTimeout",
    "FaultTolerance", "NonFiniteLossError", "TrainAborted",
    "TrainState", "TrainingSupervisor",
]
