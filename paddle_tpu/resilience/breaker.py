"""Per-endpoint circuit breakers: closed → open → half-open → closed.

A breaker sits in front of one ENDPOINT (a PS server shard, a store
address) and converts a run of consecutive transport failures into a fast
local failure (:class:`BreakerOpen`) instead of yet another connect
timeout. After ``cooldown`` seconds in the open state it admits exactly
one half-open PROBE; the probe's outcome decides between closing (healthy
again) and re-opening for another cooldown. Retry loops treat
``BreakerOpen`` like any transport failure — they keep backing off on
their own deadline — so a breaker never changes WHETHER a call ultimately
succeeds, only how much time is burned dialing a dead peer.

States export as ``resilience.breaker_state{endpoint=...}`` gauge values
(0 closed, 1 half-open, 2 open); every transition bumps
``resilience.breaker_transitions_total{endpoint=...,to=...}`` and every
fast-failed call ``resilience.breaker_short_circuits_total{endpoint=...}``.

Success/failure accounting is explicit (``before_call`` /
``record_success`` / ``record_failure``) rather than a context manager on
purpose: at the PS call site a server-side exception shipped back with its
original type means the endpoint is HEALTHY (it executed the call) and
must not trip the breaker — only the caller can classify that.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

from .. import observability as _obs

__all__ = ["BreakerOpen", "CircuitBreaker", "breaker_for", "reset_breakers",
           "CLOSED", "HALF_OPEN", "OPEN"]

CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
_STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class BreakerOpen(ConnectionError):
    """Fast local failure: the endpoint's breaker is open (cooling down)
    or its single half-open probe slot is already taken."""


class CircuitBreaker:
    def __init__(self, endpoint: str, *, failure_threshold: int = 5,
                 cooldown: float = 1.0, clock=time.monotonic):
        self.endpoint = endpoint
        self.failure_threshold = int(failure_threshold)
        self.cooldown = float(cooldown)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0          # consecutive, closed-state only
        self._opened_at = 0.0
        self._probe_inflight = False

    # -- state machine (transitions computed under the lock, metrics
    #    emitted after release so the breaker lock never nests inside the
    #    registry's per-family metric locks) ------------------------------
    def _transition_locked(self, to: str) -> str:
        self._state = to
        if to == CLOSED:
            self._failures = 0
        if to == OPEN:
            self._opened_at = self._clock()
        self._probe_inflight = False
        return to

    def _emit(self, transition: Optional[str]) -> None:
        if transition is not None:
            _obs.inc("resilience.breaker_transitions_total",
                     endpoint=self.endpoint, to=transition)
        _obs.set_gauge("resilience.breaker_state",
                       _STATE_GAUGE[self._state], endpoint=self.endpoint)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def before_call(self) -> None:
        """Gate one call attempt. Raises :class:`BreakerOpen` while open
        (cooldown not elapsed) or while another half-open probe is out."""
        short_circuit = False
        transition = None
        with self._lock:
            if self._state == OPEN:
                if self._clock() - self._opened_at >= self.cooldown:
                    transition = self._transition_locked(HALF_OPEN)
                    self._probe_inflight = True
                else:
                    short_circuit = True
            elif self._state == HALF_OPEN:
                if self._probe_inflight:
                    short_circuit = True
                else:
                    self._probe_inflight = True
        if transition is not None:
            self._emit(transition)
        if short_circuit:
            _obs.inc("resilience.breaker_short_circuits_total",
                     endpoint=self.endpoint)
            raise BreakerOpen(
                f"circuit breaker for {self.endpoint} is {self._state}")

    def record_success(self) -> None:
        transition = None
        with self._lock:
            self._failures = 0
            self._probe_inflight = False
            if self._state != CLOSED:
                transition = self._transition_locked(CLOSED)
        self._emit(transition)

    def record_failure(self) -> None:
        transition = None
        with self._lock:
            if self._state == HALF_OPEN:
                transition = self._transition_locked(OPEN)  # probe failed
            elif self._state == CLOSED:
                self._failures += 1
                if self._failures >= self.failure_threshold:
                    transition = self._transition_locked(OPEN)
        self._emit(transition)

    def reset(self) -> None:
        """Force-close (e.g. a failover re-resolved the endpoint to a NEW
        address: the old run of failures says nothing about it)."""
        transition = None
        with self._lock:
            if self._state != CLOSED:
                transition = self._transition_locked(CLOSED)
            self._failures = 0
        self._emit(transition)


# ---------------------------------------------------------------------------
# per-endpoint registry
# ---------------------------------------------------------------------------

_BREAKERS: Dict[str, CircuitBreaker] = {}
_LOCK = threading.Lock()


def breaker_for(endpoint: str, **defaults) -> CircuitBreaker:
    """Get-or-create the breaker guarding ``endpoint``. Global env
    overrides: ``PADDLE_TPU_RETRY_BREAKER_THRESHOLD`` and
    ``PADDLE_TPU_RETRY_BREAKER_COOLDOWN`` (read at creation)."""
    with _LOCK:
        br = _BREAKERS.get(endpoint)
        if br is None:
            raw = os.environ.get("PADDLE_TPU_RETRY_BREAKER_THRESHOLD")
            if raw is not None:
                defaults["failure_threshold"] = int(raw)
            raw = os.environ.get("PADDLE_TPU_RETRY_BREAKER_COOLDOWN")
            if raw is not None:
                defaults["cooldown"] = float(raw)
            br = CircuitBreaker(endpoint, **defaults)
            _BREAKERS[endpoint] = br
        return br


def reset_breakers() -> None:
    """Drop every cached breaker (tests)."""
    with _LOCK:
        _BREAKERS.clear()
