"""Fault-tolerant training supervisor: resumable state, bit-identical restart.

PR 5 made checkpoints crash-safe and PR 8 proved the serving engine
survives deadlines, hangs, and mid-batch faults — this module (PR 10)
delivers the same "under fire" guarantees for the TRAINING path. A fault
anywhere in a step loop used to kill the whole run, and even a manual
restart could not resume bit-identically because RNG, optimizer step,
LR-schedule position, and dataloader cursor were not part of the
checkpoint. Two pieces fix that:

* :class:`TrainState` — the FULL resumable state of a training run: model
  parameters, optimizer step + moments + master weights, LR-scheduler
  position, the framework RNG key, and the dataloader iteration cursor
  (``DataLoader.state_dict``), serialized through the PR 5
  verified-checkpoint writer (atomic writes, CRC manifest committed last,
  ``latest``/``latest.prev`` pointer rotation). ``restore_latest`` walks
  the pointer chain, so a kill mid-save always leaves a loadable
  last-good.
* :class:`TrainingSupervisor` — wraps any ``step_fn(batch) -> loss``
  closure (and is what ``hapi.Model.fit(fault_tolerance=...)`` rides):

  - **step supervision**: each step runs under the
    :class:`~paddle_tpu.resilience.watchdog.StepWatchdog`
    (``PADDLE_TPU_TRAIN_WATCHDOG_S``) and a named
    :class:`~paddle_tpu.resilience.policy.RetryPolicy` (``train.step``;
    ``train.data``/``train.save`` guard batch fetch and state saves), with
    ``train.step``/``train.data``/``train.save`` ``fault_point`` seams for
    deterministic :class:`~paddle_tpu.resilience.faults.FaultSchedule`
    drive;
  - **NaN/inf-loss escalation**: a non-finite loss skips the batch (the
    update is withheld when the caller supplies ``update_fn``) and bumps
    ``train.skipped_batches_total``; past ``max_skipped`` CONSECUTIVE
    skips the run rolls back to the last verified state;
  - **restart-from-last-good**: an unrecoverable step (device fault past
    the retry budget, watchdog trip, NaN escalation) restores the last
    verified :class:`TrainState` in-process and resumes — capped by
    ``PADDLE_TPU_TRAIN_MAX_RESTARTS`` — with a loss trajectory bitwise
    identical to an uninterrupted run (the acceptance proof in
    ``tests/test_train_chaos.py``). An injected
    :class:`~paddle_tpu.resilience.faults.KillPoint` (a BaseException:
    simulated process death) is deliberately NOT caught; a fresh
    supervisor with ``resume=True`` continues the run bit-identically.

Everything is observable: ``train.steps_total`` / ``train.retries_total``
/ ``train.restarts_total`` / ``train.skipped_batches_total`` /
``train.saves_total`` counters, the ``train.step_seconds`` wall-clock
histogram, and ``train.watchdog_trips_total{kind}`` through the
generalized watchdog.
"""

from __future__ import annotations

import json
import logging
import math
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import observability as _obs
from ..observability import http as _obs_http
from ..observability import trace as _trace
from . import faults as _faults
from .policy import env_float, env_int, get_policy
from .watchdog import StepWatchdog, WatchdogTimeout

__all__ = ["TrainState", "TrainingSupervisor", "TrainReport",
           "FaultTolerance", "TrainAborted", "NonFiniteLossError"]

_log = logging.getLogger(__name__)

SCHEMA_VERSION = 1
# single JSON blob carrying every non-tensor value (step, epoch, scheduler
# + dataloader positions) inside the checkpoint's metadata.json — one
# atomic value, not a _flatten explosion of loose leaves
_PYVALS_KEY = "train_pyvals"


class TrainAborted(RuntimeError):
    """Training could not continue: the restart budget is exhausted, or an
    unrecoverable step happened with no verified TrainState to roll back
    to. ``__cause__`` carries the final underlying error;
    ``flight_dump`` the path of the flight-recorder post-mortem written
    at abort (None when the dump itself failed)."""

    flight_dump: Optional[str] = None


class NonFiniteLossError(RuntimeError):
    """The loss went NaN/inf past the supervisor's tolerance
    (``nan_policy="raise"``, or ``max_skipped`` consecutive skips with no
    checkpoint to roll back to)."""


class _StepUnrecoverable(Exception):
    """Internal: this step failed for good; restore last-good or abort."""

    def __init__(self, cause: BaseException):
        super().__init__(str(cause))
        self.cause = cause


def _loss_value(loss: Any) -> float:
    """Coerce whatever the step closure returned to one host float."""
    if isinstance(loss, (list, tuple)):
        if not loss:
            raise ValueError("step_fn returned an empty loss sequence")
        loss = loss[0]
    if loss is None:
        raise ValueError("step_fn must return the step's loss")
    if hasattr(loss, "_data"):
        loss = loss._data
    return float(np.asarray(loss).ravel()[0])


# ---------------------------------------------------------------------------
# TrainState
# ---------------------------------------------------------------------------

class TrainState:
    """The full resumable state of a training run.

    ``network``/``optimizer`` follow the framework ``state_dict`` /
    ``set_state_dict`` protocol; ``loader`` is anything with the
    ``DataLoader.state_dict``/``load_state_dict`` contract (optional);
    the RNG axis is the framework's ``default_generator`` unless an
    explicit generator is passed. Tensors travel through
    ``distributed.checkpoint`` (verified, atomic, pointer-rotated);
    Python values (step, epoch, LR-scheduler dict, dataloader cursor)
    travel as one JSON blob inside ``metadata.json``.
    """

    def __init__(self, network=None, optimizer=None, loader=None,
                 generator=None):
        self.network = network
        self.optimizer = optimizer
        self.loader = loader
        self._generator = generator

    # -- component accessors -------------------------------------------------
    def _gen(self):
        if self._generator is not None:
            return self._generator
        from ..core.random import default_generator
        return default_generator

    def _scheduler(self):
        lr = getattr(self.optimizer, "_learning_rate", None) \
            if self.optimizer is not None else None
        if lr is not None and hasattr(lr, "state_dict") \
                and hasattr(lr, "step"):
            return lr
        return None

    def _tensor_tree(self) -> Dict[str, Any]:
        tree: Dict[str, Any] = {}
        if self.network is not None:
            tree["model"] = self.network.state_dict()
        if self.optimizer is not None:
            od = dict(self.optimizer.state_dict())
            # plain-value dict: restored via the pyvals blob (set_state_dict
            # + carried-LR sync), not the tensor loader
            od.pop("LR_Scheduler", None)
            tree["opt"] = od
        tree["rng"] = {"default": self._gen().state}
        return tree

    def pyvals(self, step: int, epoch: int = 0,
               extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        py: Dict[str, Any] = {"schema": SCHEMA_VERSION, "step": int(step),
                              "epoch": int(epoch)}
        sched = self._scheduler()
        if sched is not None:
            py["lr_sched"] = sched.state_dict()
        if self.loader is not None and hasattr(self.loader, "state_dict"):
            py["loader"] = self.loader.state_dict()
        if extra:
            py.update(extra)
        return py

    # -- persistence ---------------------------------------------------------
    def save(self, path: str, step: int, epoch: int = 0,
             extra: Optional[Dict[str, Any]] = None) -> str:
        """Write one verified checkpoint at ``path`` (atomic payload + CRC
        manifest committed last + ``latest``/``latest.prev`` rotation in
        the parent directory — the PR 5 writer). A kill at any point
        leaves the previous checkpoint loadable."""
        _faults.fault_point("train.save")
        from ..distributed import checkpoint as _ckpt
        with _trace.span("train.checkpoint", step=int(step)):
            tree = self._tensor_tree()
            tree[_PYVALS_KEY] = json.dumps(self.pyvals(step, epoch, extra))
            _ckpt.save_state_dict(tree, path)
        return path

    def restore(self, path: str) -> Dict[str, Any]:
        """Load ``path`` INTO the live objects (CRC-verified, no pointer
        fallback — :meth:`restore_latest` owns candidate selection) and
        apply scheduler/loader positions. Returns the pyvals dict."""
        from ..distributed import checkpoint as _ckpt
        if self.optimizer is not None \
                and hasattr(self.optimizer, "_materialize_state"):
            # accumulators/masters are created lazily on first step();
            # a fresh-process resume must materialize the destinations
            # BEFORE the tensor loader looks for them
            self.optimizer._materialize_state()
        tree = self._tensor_tree()
        _ckpt.load_state_dict(tree, path, fallback=False)
        try:
            with open(os.path.join(path, "metadata.json")) as f:
                meta = json.load(f)
        except (OSError, ValueError) as e:  # verified load just read it
            raise _ckpt.CheckpointCorruptError(
                f"metadata.json vanished under the load: {e}") from e
        ent = meta.get(_PYVALS_KEY, {})
        py = json.loads(ent["value"]) if "value" in ent else {}
        sched = self._scheduler()
        if sched is not None and "lr_sched" in py:
            sched.set_state_dict(py["lr_sched"])
            if hasattr(self.optimizer, "_sync_lr_tensor"):
                self.optimizer._sync_lr_tensor()
        if self.loader is not None and "loader" in py \
                and hasattr(self.loader, "load_state_dict"):
            self.loader.load_state_dict(py["loader"])
        return py

    def restore_latest(self, ckpt_dir: str
                       ) -> Optional[Tuple[str, Dict[str, Any]]]:
        """Restore the newest loadable checkpoint under ``ckpt_dir`` by
        walking the ``latest`` → ``latest.prev`` pointer chain. Returns
        ``(path, pyvals)``, or None when no checkpoint was ever committed
        there. A candidate that fails CRC/manifest verification falls back
        to the next (``train.restore_fallbacks_total``); wrong-tree user
        errors (missing key, shape mismatch) raise immediately."""
        from ..distributed.checkpoint import CheckpointCorruptError
        failures: List[str] = []
        for name in self._pointer_chain(ckpt_dir):
            path = os.path.join(ckpt_dir, name)
            if not os.path.isdir(path):
                continue
            try:
                py = self.restore(path)
            except CheckpointCorruptError as e:
                failures.append(f"{path}: {e}")
                _obs.inc("train.restore_fallbacks_total")
                _log.error("train: checkpoint %s failed verification (%s)"
                           "; trying the next pointer", path, e)
                continue
            return path, py
        if failures:
            raise CheckpointCorruptError(
                "no loadable TrainState: " + "; ".join(failures))
        return None

    @staticmethod
    def _pointer_chain(ckpt_dir: str) -> List[str]:
        names: List[str] = []
        for ptr in ("latest", "latest.prev"):
            try:
                with open(os.path.join(ckpt_dir, ptr)) as f:
                    name = f.read().strip()
            except OSError:
                continue
            if name and name not in names:
                names.append(name)
        return names


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------

@dataclass
class FaultTolerance:
    """Knobs of one supervised training run (also the ``fault_tolerance=``
    argument of ``hapi.Model.fit``).

    ``watchdog_s`` defaults from ``PADDLE_TPU_TRAIN_WATCHDOG_S`` (unset or
    <= 0 disables the watchdog); ``max_restarts`` from
    ``PADDLE_TPU_TRAIN_MAX_RESTARTS`` (default 2). ``save_every`` counts
    APPLIED optimizer steps between TrainState saves (0 = never — the
    supervisor then has nothing to roll back to and unrecoverable steps
    abort typed). ``nan_policy``: ``"skip"`` withholds the update and
    counts (rollback past ``max_skipped`` consecutive), ``"raise"``
    surfaces :class:`NonFiniteLossError` on the first non-finite loss.
    """

    ckpt_dir: Optional[str] = None
    save_every: int = 0
    watchdog_s: Optional[float] = None
    max_restarts: Optional[int] = None
    nan_policy: str = "skip"
    max_skipped: int = 3
    resume: bool = False

    def __post_init__(self):
        if self.watchdog_s is None:
            self.watchdog_s = env_float("PADDLE_TPU_TRAIN_WATCHDOG_S")
        if self.watchdog_s is not None and self.watchdog_s <= 0:
            self.watchdog_s = None
        if self.max_restarts is None:
            self.max_restarts = env_int("PADDLE_TPU_TRAIN_MAX_RESTARTS", 2)
        self.max_restarts = max(0, int(self.max_restarts))
        if self.nan_policy not in ("skip", "raise"):
            raise ValueError(
                f"nan_policy must be 'skip' or 'raise', got "
                f"{self.nan_policy!r}")
        if self.save_every < 0:
            raise ValueError("save_every must be >= 0")


@dataclass
class TrainReport:
    """What one :meth:`TrainingSupervisor.run` call did."""

    losses: List[float] = field(default_factory=list)
    steps: int = 0
    retries: int = 0
    restarts: int = 0
    skipped_batches: int = 0
    resumed_from: Optional[str] = None
    last_checkpoint: Optional[str] = None


class TrainingSupervisor:
    """Drive a step closure under retry/watchdog/NaN supervision with
    restart-from-last-good (module docstring has the full contract).

    ``step_fn(batch) -> loss`` runs the forward/backward (and, when no
    ``update_fn`` is given, the optimizer update too). Supplying
    ``update_fn`` (and optionally ``clear_fn``) splits the step so a
    non-finite loss can SKIP the update entirely — the hapi integration
    does this via ``train_batch(update=False)``. The loss trajectory of a
    faulted-and-recovered run is bitwise identical to an uninterrupted
    one as long as ``step_fn`` is deterministic given (params, RNG,
    batch) — everything else (RNG, moments, LR position, data cursor) is
    the supervisor's job.
    """

    def __init__(self, network=None, optimizer=None, loader=None,
                 config: Optional[FaultTolerance] = None, **knobs):
        if config is not None and knobs:
            raise ValueError("pass config= or knob kwargs, not both")
        self.config = config if config is not None else FaultTolerance(**knobs)
        self.state = TrainState(network, optimizer, loader)
        self._watchdog: Optional[StepWatchdog] = (
            StepWatchdog(self.config.watchdog_s,
                         name="paddle-tpu-train-watchdog",
                         metric="train.watchdog_trips_total", label="train")
            if self.config.watchdog_s else None)
        self._global_step = 0
        self._epoch = 0
        self._nan_streak = 0
        self._retries = 0
        self._skipped = 0
        self._losses: List[float] = []
        self._last_save: Optional[str] = None

    # -- public --------------------------------------------------------------
    def run(self, step_fn: Callable[[Any], Any], data=None, *,
            epochs: int = 1, steps_per_epoch: Optional[int] = None,
            update_fn: Optional[Callable[[], None]] = None,
            clear_fn: Optional[Callable[[], None]] = None,
            resume: Optional[bool] = None,
            on_epoch_begin: Optional[Callable[[int], None]] = None,
            on_epoch_end: Optional[Callable[[int], None]] = None,
            on_batch_begin: Optional[Callable[[int], None]] = None,
            on_batch_end: Optional[Callable[[int, float], None]] = None,
            should_stop: Optional[Callable[[], bool]] = None) -> TrainReport:
        """Train for ``epochs`` passes over ``data`` (re-iterable; with a
        stateful DataLoader a resumed run continues mid-epoch). ``data``
        may be None when ``steps_per_epoch`` is given and ``step_fn``
        sources its own batches. ``resume`` (default: the config flag)
        restores the newest verified TrainState before the first step —
        the cross-process half of crash recovery."""
        cfg = self.config
        if data is None and steps_per_epoch is None:
            raise ValueError("data=None requires steps_per_epoch")
        step_fn, update_fn = self._route_step_capture(step_fn, update_fn,
                                                      data)
        report = TrainReport()
        self._global_step = 0
        self._epoch = 0
        self._nan_streak = 0
        self._retries = 0
        self._skipped = 0
        self._losses = []
        do_resume = cfg.resume if resume is None else resume
        if do_resume and cfg.ckpt_dir:
            got = self.state.restore_latest(cfg.ckpt_dir)
            if got is not None:
                path, py = got
                self._global_step = int(py.get("step", 0))
                self._epoch = int(py.get("epoch", 0))
                report.resumed_from = path
                self._warn_unpositioned_data(data, py)
                _log.info("train: resumed from %s (step %d, epoch %d)",
                          path, self._global_step, self._epoch)
        base_step = self._global_step
        restarts = 0
        # opt-in scrape endpoint (ISSUE 12): /metrics + /healthz +
        # /debug/flight behind PADDLE_TPU_OBS_HTTP_PORT; unset costs one
        # env read
        _obs_http.maybe_serve_from_env()
        try:
            with _trace.span("train.run", epochs=epochs):
                while True:
                    try:
                        self._run_epochs(step_fn, data, epochs,
                                         steps_per_epoch, update_fn,
                                         clear_fn, on_epoch_begin,
                                         on_epoch_end, on_batch_begin,
                                         on_batch_end, should_stop)
                        break
                    except _StepUnrecoverable as exc:
                        cause = exc.cause
                        if not cfg.ckpt_dir:
                            raise TrainAborted(
                                "unrecoverable train step and no ckpt_dir "
                                "to roll back to") from cause
                        if restarts >= cfg.max_restarts:
                            raise TrainAborted(
                                f"restart budget exhausted "
                                f"({cfg.max_restarts} restarts)") from cause
                        got = self.state.restore_latest(cfg.ckpt_dir)
                        if got is None:
                            raise TrainAborted(
                                "unrecoverable train step before the first "
                                "TrainState save") from cause
                        restarts += 1
                        _obs.inc("train.restarts_total")
                        path, py = got
                        self._global_step = int(py.get("step", 0))
                        self._epoch = int(py.get("epoch", 0))
                        self._nan_streak = 0
                        _trace.instant("train.restore", path=path,
                                       step=self._global_step,
                                       restart=restarts,
                                       cause=type(cause).__name__)
                        self._warn_unpositioned_data(data, py)
                        # grads are not part of TrainState; whatever the
                        # failed step left accumulated must not leak into
                        # the resumed trajectory
                        if clear_fn is not None:
                            try:
                                clear_fn()
                            except Exception:
                                _log.exception(
                                    "train: clear_fn failed after a restore")
                        # the rolled-back steps re-run; they must not appear
                        # twice in the trajectory
                        del self._losses[max(0,
                                             self._global_step - base_step):]
                        _log.warning(
                            "train: restored last-good %s (step %d) after "
                            "%r — restart %d/%d", path, self._global_step,
                            cause, restarts, cfg.max_restarts)
        except TrainAborted as exc:
            # the abort carries its own post-mortem: the flight ring's
            # tail names the fault site that exhausted the budget
            exc.flight_dump = _trace.flight_dump(
                "train_aborted", error=str(exc),
                cause=type(exc.__cause__).__name__ if exc.__cause__
                else None)
            raise
        except BaseException as exc:
            # unhandled supervisor exit — a KillPoint (simulated process
            # death), KeyboardInterrupt, or an unexpected user error: the
            # dump is the part of the post-mortem that survives the
            # process
            _trace.flight_dump("supervisor_exit",
                               error=type(exc).__name__)
            raise
        finally:
            if self._watchdog is not None:
                self._watchdog.stop()
            _trace.heartbeat_clear("train.supervisor")
            _trace.maybe_export_chrome("train")
        report.losses = list(self._losses)
        report.steps = self._global_step - base_step
        report.retries = self._retries
        report.restarts = restarts
        report.skipped_batches = self._skipped
        report.last_checkpoint = self._last_save
        return report

    def _route_step_capture(self, step_fn, update_fn, data):
        """ISSUE 11: run the step over whole-step static capture
        (``PADDLE_TPU_STEP_CAPTURE=auto``, the default) — forward +
        backward compiled as ONE donated-buffer XLA program per
        signature, with the eager tier as the ``off`` debug escape.

        A caller-supplied :class:`~paddle_tpu.core.step_capture.
        CapturedStep` (what ``hapi.Model.fit`` builds: the optimizer
        update folded in, NaN-gated in-program) is used as-is; a plain
        closure is wrapped so its fwd+bwd compiles while ``update_fn``
        stays an eager per-step call (an opaque update may legally do
        per-step host work — ``scheduler.step()`` — that must never bake
        into a replayed program). ``data=None`` (steps_per_epoch mode)
        never wraps: a step that sources its own batches would consume
        one during a failed speculative trace."""
        from ..core.step_capture import CapturedStep, mode as _cap_mode
        if isinstance(step_fn, CapturedStep):
            if step_fn.applies_update and update_fn is not None:
                raise ValueError(
                    "the captured step already folds the optimizer update "
                    "in-program; do not pass update_fn as well")
            return step_fn, update_fn
        if _cap_mode() == "off" or data is None:
            return step_fn, update_fn
        if getattr(step_fn, "__step_capture__", True) is False:
            # opt-out marker: a closure with per-step host effects beyond
            # tensors (hapi's metric-updating split step) must not even be
            # speculatively traced — a failed trace re-runs the step
            # eagerly, which would double-apply non-tensor side effects
            return step_fn, update_fn
        return CapturedStep(step_fn, label="train"), update_fn

    def _warn_unpositioned_data(self, data, py) -> None:
        """A restore repositions ``self.state.loader``; when ``data`` is a
        different object (or carries no cursor state in the checkpoint),
        ``iter(data)`` restarts the interrupted epoch from its FIRST batch
        — batches whose updates are already baked into the restored state
        repeat, and the trajectory silently diverges from a crash-free
        run. That must at least be loud."""
        if data is None:
            return   # steps_per_epoch mode: step_fn owns data positioning
        if "loader" in py and self.state.loader is not None \
                and data is self.state.loader:
            return
        _log.warning(
            "train: restored to step %d but the data source cannot be "
            "repositioned (checkpoint has no DataLoader cursor, or run() "
            "was given a different iterable than the supervisor's loader): "
            "the interrupted epoch restarts from its first batch and "
            "already-applied batches will REPEAT — pass the same stateful "
            "paddle.io.DataLoader to both the supervisor and run() for "
            "exact mid-epoch resume", self._global_step)

    # -- loop ----------------------------------------------------------------
    def _run_epochs(self, step_fn, data, epochs, steps_per_epoch, update_fn,
                    clear_fn, on_epoch_begin, on_epoch_end, on_batch_begin,
                    on_batch_end, should_stop) -> None:
        cfg = self.config
        while self._epoch < epochs:
            ep = self._epoch
            if on_epoch_begin is not None:
                on_epoch_begin(ep)
            it = iter(data) if data is not None else None
            step_in_epoch = 0
            while True:
                if steps_per_epoch is not None \
                        and step_in_epoch >= steps_per_epoch:
                    break
                _trace.heartbeat("train.supervisor")
                # ONE span covers the whole step — fetch, forward/backward
                # (child spans), update, checkpoint — so a training step's
                # trace is a connected tree with the retry/restore/NaN
                # events attached inside it
                with _trace.span("train.step", step=self._global_step,
                                 epoch=ep):
                    if it is not None:
                        try:
                            batch = self._fetch(it)
                        except StopIteration:
                            break
                    else:
                        batch = None
                    if on_batch_begin is not None:
                        on_batch_begin(step_in_epoch)
                    loss = self._run_step(step_fn, update_fn, clear_fn,
                                          batch)
                    idx = step_in_epoch
                    step_in_epoch += 1
                    if loss is None:   # skipped batch (non-finite loss)
                        continue
                    self._global_step += 1
                    self._losses.append(loss)
                    _obs.inc("train.steps_total")
                    if on_batch_end is not None:
                        on_batch_end(idx, loss)
                    if cfg.ckpt_dir and cfg.save_every \
                            and self._global_step % cfg.save_every == 0:
                        self._save_state()
                    if should_stop is not None and should_stop():
                        return
            self._epoch += 1
            if on_epoch_end is not None:
                on_epoch_end(ep)
            if should_stop is not None and should_stop():
                return

    def _fetch(self, it):
        with _trace.span("train.fetch"):
            return self._fetch_traced(it)

    def _fetch_traced(self, it):
        pol = get_policy("train.data", base_delay=0.05, max_delay=1.0,
                         max_attempts=3)
        for attempt in pol.start():
            try:
                _faults.fault_point("train.data")
            except Exception as e:
                try:
                    attempt.fail(e)     # re-raises when the budget is spent
                except Exception as final:
                    raise _StepUnrecoverable(final) from final
                self._retries += 1
                _obs.inc("train.retries_total", site="train.data")
                _trace.instant("train.retry", site="train.data",
                               error=type(e).__name__)
                continue
            try:
                return next(it)
            except StopIteration:
                raise
            except Exception as e:
                # a generator that RAISED is closed: retrying next() on it
                # would read StopIteration and silently truncate the epoch.
                # The only honest recovery is restore-last-good, which
                # rebuilds the iterator from the checkpointed loader cursor.
                raise _StepUnrecoverable(e) from e

    def _run_step(self, step_fn, update_fn, clear_fn, batch
                  ) -> Optional[float]:
        pol = get_policy("train.step", base_delay=0.05, max_delay=0.5,
                         max_attempts=3)
        for attempt in pol.start():
            gen = self._watchdog.arm() if self._watchdog is not None else None
            try:
                _faults.fault_point("train.step")
                with _obs.scoped_timer("train.step_seconds"), \
                        _trace.span("train.fwd_bwd"):
                    loss = step_fn(batch)
            except BaseException as e:
                if gen is not None:
                    self._watchdog.disarm(gen)
                if not isinstance(e, Exception):
                    raise    # KillPoint / KeyboardInterrupt: simulated or
                    #          real process death, not a retryable fault
                if clear_fn is not None:
                    try:
                        clear_fn()
                    except Exception:
                        _log.exception(
                            "train: clear_fn failed after a faulted step")
                try:
                    attempt.fail(e)     # re-raises when the budget is spent
                except Exception as final:
                    raise _StepUnrecoverable(final) from final
                self._retries += 1
                _obs.inc("train.retries_total", site="train.step")
                _trace.instant("train.retry", site="train.step",
                               error=type(e).__name__)
                continue
            verdict = self._watchdog.disarm(gen) if gen is not None else None
            if verdict is not None:
                # the step DID return but blew the budget: its device state
                # is suspect (partial collectives, a wedged-then-revived
                # link) — eager updates may already be applied, so the only
                # trustworthy recovery is the last verified TrainState. Its
                # backward already accumulated grads; drop them so the
                # restored params don't inherit a poisoned gradient.
                if clear_fn is not None:
                    try:
                        clear_fn()
                    except Exception:
                        _log.exception(
                            "train: clear_fn failed after a watchdog trip")
                _trace.instant("train.watchdog", kind=verdict)
                raise _StepUnrecoverable(WatchdogTimeout(
                    f"train step exceeded the watchdog budget "
                    f"({self._watchdog.timeout_s:.3f}s, classified "
                    f"{verdict})"))
            return self._after_step(loss, update_fn, clear_fn)
        raise AssertionError("unreachable: retry loop exited without raise")

    def _after_step(self, loss, update_fn, clear_fn) -> Optional[float]:
        cfg = self.config
        lossf = _loss_value(loss)
        if not math.isfinite(lossf):
            if cfg.nan_policy == "raise":
                raise NonFiniteLossError(
                    f"non-finite loss {lossf!r} at step "
                    f"{self._global_step} (nan_policy='raise')")
            self._nan_streak += 1
            self._skipped += 1
            _obs.inc("train.skipped_batches_total")
            _trace.instant("train.nan_skip", loss=repr(lossf),
                           streak=self._nan_streak)
            if clear_fn is not None:
                clear_fn()
            if self._nan_streak >= cfg.max_skipped:
                # past the threshold the params themselves are suspect
                # (without update_fn the poisoned update already landed):
                # roll back to the last verified state
                raise _StepUnrecoverable(NonFiniteLossError(
                    f"{self._nan_streak} consecutive non-finite losses "
                    f"(threshold {cfg.max_skipped})"))
            _log.warning(
                "train: non-finite loss at step %d — batch skipped "
                "(%d consecutive, rollback at %d)", self._global_step,
                self._nan_streak, cfg.max_skipped)
            return None
        self._nan_streak = 0
        if update_fn is not None:
            with _trace.span("train.update"):
                update_fn()
        return lossf

    def _save_state(self) -> None:
        cfg = self.config
        path = os.path.join(cfg.ckpt_dir, f"step-{self._global_step}")
        pol = get_policy("train.save", base_delay=0.05, max_delay=1.0,
                         max_attempts=3)
        for attempt in pol.start():
            try:
                self.state.save(path, self._global_step, epoch=self._epoch)
            except Exception as e:
                # a save that cannot land erodes the rollback guarantee:
                # retry on the policy, then SURFACE (the caller must know
                # checkpoints stopped flowing)
                attempt.fail(e)
                self._retries += 1
                _obs.inc("train.retries_total", site="train.save")
                continue
            self._last_save = path
            _obs.inc("train.saves_total")
            return
