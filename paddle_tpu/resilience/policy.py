"""Retry policies: jittered exponential backoff, caps, deadlines.

One policy object describes HOW a subsystem retries (base delay, growth,
jitter, attempt cap); the budget of a concrete call site comes from three
clamping sources — the policy's own default deadline, the per-call
``deadline=`` argument, and the ambient thread-local deadline installed by
:class:`deadline_scope` — whichever is tightest wins. The ambient scope is
what makes deadlines PROPAGATE through nested calls: ``PsClient._call``
opens a scope for its failover budget, and the rpc dial policy three
frames down clamps its own backoff to the same monotonic instant instead
of compounding timeouts.

Policies are named and registered (:func:`get_policy`), and every knob has
an env override so an operator can retune a live job without code:
``PADDLE_TPU_RETRY_<NAME>_<KNOB>`` where ``<NAME>`` is the policy name
upper-cased with ``.``/``-`` mapped to ``_`` and ``<KNOB>`` is one of
``BASE_DELAY``, ``MAX_DELAY``, ``MULTIPLIER``, ``JITTER``,
``MAX_ATTEMPTS``, ``DEADLINE`` (e.g.
``PADDLE_TPU_RETRY_PS_RPC_MAX_DELAY=5``).

Call-site shape (the loop owns the verb, the policy owns the schedule)::

    for attempt in get_policy("ps.rpc").start(deadline=60.0):
        try:
            return transport()
        except TransportError as e:
            attempt.fail(e)        # backoff-sleeps, or re-raises e when
                                   # the attempt/deadline budget is spent

Every backoff is counted (``resilience.retries_total{policy=...}``) and
every exhausted budget too (``resilience.giveups_total{policy=...}``).
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, Optional

from .. import observability as _obs

__all__ = ["RetryPolicy", "DeadlineExceeded", "deadline_scope",
           "current_deadline", "get_policy", "register_policy",
           "reset_policies", "jitter_sleep", "env_float", "env_int"]


def env_float(name: str) -> Optional[float]:
    """Float env knob; unset/blank/non-numeric -> None (logged)."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        import logging
        logging.getLogger(__name__).warning(
            "ignoring non-numeric %s=%r", name, raw)
        return None


def env_int(name: str, default: int) -> int:
    """Int env knob; unset/blank/non-numeric -> ``default`` (logged)."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        import logging
        logging.getLogger(__name__).warning(
            "ignoring non-numeric %s=%r", name, raw)
        return default


class DeadlineExceeded(TimeoutError):
    """A deadline budget expired before the guarded work could run.

    The typed surface for deadline-driven shedding: callers that gate work
    on a per-request or ambient deadline (``deadline_scope``, the serving
    admission boundary) resolve the work's future — or raise — with THIS
    type, so "too late" is distinguishable from "failed" at every layer
    above."""

_TLS = threading.local()
# module RNG for jitter: desynchronization noise, not reproducibility
# surface (fault determinism lives in FaultSchedule's own seeded RNG)
_RNG = random.Random()


def current_deadline() -> Optional[float]:
    """Innermost ambient MONOTONIC deadline (None = unbounded)."""
    return getattr(_TLS, "deadline", None)


class deadline_scope:
    """Install an ambient monotonic deadline for the current thread.

    ``with deadline_scope(30.0): ...`` bounds every policy-driven retry
    loop entered inside the block (however deeply nested) to
    ``time.monotonic() + 30``. Nested scopes clamp to the TIGHTER
    deadline; they can never extend an outer budget.
    """

    def __init__(self, seconds: Optional[float] = None, *,
                 until: Optional[float] = None):
        if seconds is not None and until is not None:
            raise ValueError("pass seconds or until, not both")
        self._until = until if seconds is None \
            else time.monotonic() + float(seconds)
        self._outer: Optional[float] = None

    def __enter__(self) -> Optional[float]:
        outer = current_deadline()
        self._outer = outer
        eff = self._until
        if outer is not None:
            eff = outer if eff is None else min(eff, outer)
        _TLS.deadline = eff
        return eff

    def __exit__(self, *exc) -> None:
        _TLS.deadline = self._outer


class _Attempts:
    """Iterator/handle hybrid: yields itself once per attempt; ``fail``
    either backoff-sleeps (budget remains) or re-raises (budget spent)."""

    __slots__ = ("policy", "deadline", "attempt", "_delay")

    def __init__(self, policy: "RetryPolicy", deadline: Optional[float]):
        self.policy = policy
        self.deadline = deadline
        self.attempt = 0
        self._delay = policy.base_delay

    def __iter__(self) -> "_Attempts":
        return self

    def __next__(self) -> "_Attempts":
        self.attempt += 1
        return self

    def remaining(self) -> Optional[float]:
        """Seconds left in the deadline budget (None = unbounded)."""
        if self.deadline is None:
            return None
        return self.deadline - time.monotonic()

    def fail(self, exc: BaseException) -> None:
        """Record a failed attempt.

        Re-raises ``exc`` when the attempt cap or deadline is spent;
        otherwise sleeps the next (jittered, deadline-clamped) backoff and
        returns so the loop may try again.
        """
        pol = self.policy
        left = self.remaining()
        if (pol.max_attempts is not None and self.attempt >= pol.max_attempts) \
                or (left is not None and left <= 0):
            _obs.inc("resilience.giveups_total", policy=pol.name)
            raise exc
        delay = self._delay
        if pol.jitter:
            delay *= 1.0 + pol.jitter * (2.0 * pol._rng.random() - 1.0)
        if left is not None:
            delay = min(delay, max(0.0, left))
        _obs.inc("resilience.retries_total", policy=pol.name)
        pol._sleep(delay)
        self._delay = min(self._delay * pol.multiplier, pol.max_delay)


class RetryPolicy:
    """Jittered exponential backoff schedule with attempt/deadline caps.

    ``jitter`` is a symmetric fraction: each sleep is drawn uniformly from
    ``delay * [1 - jitter, 1 + jitter]`` so simultaneously-failing workers
    decorrelate instead of re-dialing a respawned server in lockstep.
    ``sleep``/``rng`` are injection seams for tests.
    """

    def __init__(self, name: str, *, base_delay: float = 0.2,
                 multiplier: float = 2.0, max_delay: float = 2.0,
                 jitter: float = 0.25, max_attempts: Optional[int] = None,
                 deadline: Optional[float] = None,
                 sleep=time.sleep, rng: Optional[random.Random] = None):
        if base_delay < 0 or multiplier < 1.0:
            raise ValueError("base_delay >= 0 and multiplier >= 1 required")
        if not 0.0 <= jitter < 1.0:
            raise ValueError("jitter must be in [0, 1)")
        self.name = name
        self.base_delay = float(base_delay)
        self.multiplier = float(multiplier)
        self.max_delay = float(max_delay)
        self.jitter = float(jitter)
        self.max_attempts = None if max_attempts is None else int(max_attempts)
        self.deadline = None if deadline is None else float(deadline)
        self._sleep = sleep
        self._rng = rng if rng is not None else _RNG

    def start(self, deadline: Optional[float] = None) -> _Attempts:
        """Open one retry budget: the tightest of the policy default, the
        per-call ``deadline`` (seconds from now), and the ambient
        :class:`deadline_scope` governs."""
        now = time.monotonic()
        candidates = [now + d for d in (self.deadline, deadline)
                      if d is not None]
        ambient = current_deadline()
        if ambient is not None:
            candidates.append(ambient)
        return _Attempts(self, min(candidates) if candidates else None)

    def __repr__(self) -> str:
        return (f"RetryPolicy({self.name!r}, base_delay={self.base_delay}, "
                f"multiplier={self.multiplier}, max_delay={self.max_delay}, "
                f"jitter={self.jitter}, max_attempts={self.max_attempts}, "
                f"deadline={self.deadline})")


# ---------------------------------------------------------------------------
# named registry with env overrides
# ---------------------------------------------------------------------------

_POLICIES: Dict[str, RetryPolicy] = {}
_LOCK = threading.Lock()

_ENV_PREFIX = "PADDLE_TPU_RETRY_"
_FLOAT_KNOBS = ("base_delay", "max_delay", "multiplier", "jitter", "deadline")


def _env_name(policy_name: str) -> str:
    return policy_name.upper().replace(".", "_").replace("-", "_")


def _apply_env_overrides(name: str, kw: Dict) -> Dict:
    prefix = _ENV_PREFIX + _env_name(name) + "_"
    for knob in _FLOAT_KNOBS:
        raw = os.environ.get(prefix + knob.upper())
        if raw is not None:
            kw[knob] = float(raw)
    raw = os.environ.get(prefix + "MAX_ATTEMPTS")
    if raw is not None:
        kw["max_attempts"] = int(raw) if int(raw) > 0 else None
    return kw


def register_policy(policy: RetryPolicy) -> RetryPolicy:
    """Install (or replace) a policy under its name."""
    with _LOCK:
        _POLICIES[policy.name] = policy
    return policy


def get_policy(name: str, **defaults) -> RetryPolicy:
    """Get-or-create the named policy.

    ``defaults`` seed the knobs on first creation; env overrides
    (``PADDLE_TPU_RETRY_<NAME>_<KNOB>``) are applied on top, once, at
    creation time. Subsequent calls return the cached instance (call-site
    defaults of later callers do NOT reconfigure it).
    """
    with _LOCK:
        pol = _POLICIES.get(name)
        if pol is None:
            pol = RetryPolicy(name, **_apply_env_overrides(name, defaults))
            _POLICIES[name] = pol
        return pol


def reset_policies() -> None:
    """Drop every cached policy (tests: re-read env overrides)."""
    with _LOCK:
        _POLICIES.clear()


def jitter_sleep(seconds: float, *, frac: float = 0.25,
                 rng: Optional[random.Random] = None,
                 sleep=time.sleep) -> float:
    """Sleep ``seconds`` scaled by a uniform ``1 ± frac`` draw.

    The poll-loop primitive: a fleet of workers respawned at the same
    instant (elastic restart) would otherwise hit the rendezvous store in
    phase forever. Returns the duration actually slept (test seam).
    """
    r = (rng if rng is not None else _RNG).random()
    d = max(0.0, float(seconds) * (1.0 + float(frac) * (2.0 * r - 1.0)))
    sleep(d)
    return d
