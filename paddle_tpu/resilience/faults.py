"""Deterministic fault injection: scripted/seeded failures at named sites.

The failure-handling layer is only trustworthy if every path through it
can be DRIVEN: a lost PS reply, a store socket reset mid-request, a kill
halfway through a checkpoint commit. Each such seam in the framework is a
``fault_point("<site>")`` call — a module-global ``None`` probe when no
schedule is installed (the production state: zero work, zero allocation)
— and a test/harness installs a :class:`FaultSchedule` that decides, per
site and per call index, whether to delay, raise, or "kill".

Determinism contract: a schedule is driven only by (a) the per-site call
counter and (b) its own seeded RNG for probabilistic specs. Re-running the
same workload against an identical schedule therefore produces the same
``trace`` — the acceptance surface for "the same schedule yields the same
retry/failover trace twice".

Sites threaded through the framework (exact-match tags):

====================  =====================================================
``store.connect``     ``_PyClient`` dial (per attempt)
``store.request``     ``_PyClient.request`` wire round-trip (per attempt)
``rpc.call``          ``distributed.rpc._call`` entry (before dialing)
``rpc.reply``         after the rpc reply was received (lost-reply seam)
``ps.call``           ``PsClient._call`` attempt entry
``ps.reply``          after a successful PS rpc (lost-REPLY: the server
                      executed, the client must retry → seq dedup)
``ps.handler``        PS server handler entry (server-side error seam)
``checkpoint.save``   ``save_state_dict`` entry
``checkpoint.write``  after metadata, before the array payload
``checkpoint.commit`` after the array payload, before the manifest commit
``dispatch.lower``    ``core.tensor._dispatch_execute`` before the op's
                      trace/execution — inject ``NotImplementedError``
                      here to simulate a missing TPU lowering and drive
                      the backend-fallback path (core/fallback.py)
``dispatch.execute``  after the op executed, before results are consumed
                      (first-execution compile failure seam)
``serving.admit``     ``serving.engine`` admission attempt, before the
                      prefill program runs (retried once; a second fault
                      fails the request and frees its pages)
``serving.step``      once per (decode step, included slot), in admission
                      order — call index N deterministically targets one
                      slot; a faulted slot sits the step out, a second
                      fault fails it ALONE (batchmates unaffected)
``serving.watchdog``  once per batched-decode ATTEMPT, inside the armed
                      watchdog window, before the compiled step runs — a
                      ``delay`` here simulates a hung device step (the
                      watchdog trips and the step's outputs are
                      abandoned), an ``error`` a whole-batch device
                      fault; either way the affected slots recover via
                      bounded prefill replay (``max_replays``)
``serving.drain``     ``Engine.stop(drain=True)`` entry — an injected
                      error degrades the graceful drain to an immediate
                      stop (stragglers still resolve; the no-stranded-
                      futures invariant outranks graceful finish)
``router.pick``       ``serving.router`` placement attempt, before the
                      pick-2 sample — an injected error burns one of the
                      request's bounded placement attempts
``router.forward``    before a replica ``submit`` attempt — an injected
                      error is a transport failure BEFORE admission
                      (never admitted, so trying another replica keeps
                      the at-most-once contract), counted against the
                      replica's circuit breaker
``http.write``        ``serving.http`` before every streamed write — an
                      injected error is retried once with the identical
                      payload (the bytes never left the process); a
                      second consecutive fault is a client disconnect
                      (the request is cancelled upstream, its pages
                      free)
``train.step``        ``resilience.trainer`` step attempt entry, inside
                      the armed train watchdog window, before the step
                      closure runs — ``error`` drives the per-step retry
                      policy (exhaustion → restore-last-good), ``delay``
                      past ``PADDLE_TPU_TRAIN_WATCHDOG_S`` a watchdog
                      trip, ``kill`` a simulated process death (resume
                      with a fresh supervisor, bit-identically)
``train.data``        batch fetch from the training iterator, before
                      ``next()`` — retried on the ``train.data`` policy,
                      then restore-last-good
``train.save``        ``TrainState.save`` entry, before the verified
                      writer runs (compose with ``checkpoint.write`` /
                      ``checkpoint.commit`` to kill deeper); a killed
                      save leaves the previous checkpoint loadable
``fleet.spawn``       ``serving.fleet`` supervisor, before each worker
                      ``Popen`` (first spawn and every respawn) — an
                      injected error burns one respawn attempt against
                      the ``PADDLE_TPU_FLEET_MAX_RESPAWNS`` cap
``fleet.heartbeat``   before each monitor-thread heartbeat RPC — an
                      injected error is a missed beat; enough
                      consecutive misses cross the staleness threshold
                      and latch the replica out of rotation (a later
                      good beat restores it). Separate from
                      ``fleet.rpc`` so background beats never perturb
                      the data-plane call indices (determinism)
``fleet.rpc``         before each data-plane RPC to a worker (submit /
                      cancel / withdraw / drain / prefix_summary) — an
                      injected error before admission is a transport
                      failure the router fails over (never admitted)
====================  =====================================================

Kinds: ``delay`` sleeps; ``error`` raises a fresh instance of the
configured exception type; ``kill`` raises :class:`KillPoint` — a
``BaseException`` so ordinary ``except Exception`` recovery code cannot
swallow the simulated process death.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple, Type

from .. import observability as _obs
from ..observability import trace as _trace

__all__ = ["FaultInjected", "KillPoint", "FaultSchedule", "fault_point",
           "install", "uninstall", "installed"]


class FaultInjected(ConnectionError):
    """Default exception for injected ``error``/``drop`` faults."""


class KillPoint(BaseException):
    """Simulated process death at a fault point. Deliberately NOT an
    ``Exception``: recovery code that catches ``Exception`` must behave as
    if the process vanished, exactly like a real SIGKILL."""


class _Spec:
    __slots__ = ("kind", "on", "prob", "times", "error", "message",
                 "seconds", "fired")

    def __init__(self, kind: str, on, prob, times, error, message, seconds):
        self.kind = kind
        self.on = frozenset(int(i) for i in on) if on else None
        self.prob = None if prob is None else float(prob)
        self.times = None if times is None else int(times)
        self.error = error
        self.message = message
        self.seconds = float(seconds)
        self.fired = 0

    def make_error(self, site: str, call_index: int) -> BaseException:
        if isinstance(self.error, BaseException):
            return self.error  # caller supplied an instance: use as-is
        msg = self.message or f"injected {self.kind} at {site} " \
                              f"(call {call_index})"
        return self.error(msg)


class FaultSchedule:
    """A set of per-site fault specs plus the trace of what fired.

    ``seed`` drives the probabilistic specs; scripted specs (``on=``) need
    no RNG at all. ``trace`` is the ordered list of
    ``(site, call_index, kind)`` tuples of every fired fault — compare two
    runs' traces to prove determinism.
    """

    def __init__(self, seed: Optional[int] = None):
        self._rng = random.Random(seed)
        self._specs: Dict[str, List[_Spec]] = {}
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()
        self.trace: List[Tuple[str, int, str]] = []

    # -- authoring ----------------------------------------------------------
    def inject(self, site: str, kind: str, *, on=None,
               prob: Optional[float] = None, times: Optional[int] = None,
               error: Any = FaultInjected, message: Optional[str] = None,
               seconds: float = 0.0) -> "FaultSchedule":
        """Add one spec for ``site``.

        ``on`` — 1-based call indices that fire (scripted); ``prob`` —
        seeded per-call probability (ignored when ``on`` given); ``times``
        — cap on total fires; ``error`` — exception type (or instance) for
        ``error`` kind; ``seconds`` — sleep for ``delay`` kind.
        """
        if kind not in ("delay", "error", "kill"):
            raise ValueError(f"unknown fault kind {kind!r}")
        with self._lock:
            self._specs.setdefault(site, []).append(
                _Spec(kind, on, prob, times, error, message, seconds))
        return self

    def error(self, site: str, **kw) -> "FaultSchedule":
        return self.inject(site, "error", **kw)

    # "drop" reads better at transport seams; the mechanics are identical
    # (raise a transport-shaped error the caller's retry loop handles)
    drop = error

    def delay(self, site: str, *, seconds: float, **kw) -> "FaultSchedule":
        return self.inject(site, "delay", seconds=seconds, **kw)

    def kill(self, site: str, **kw) -> "FaultSchedule":
        return self.inject(site, "kill", **kw)

    # -- execution ----------------------------------------------------------
    def calls(self, site: str) -> int:
        with self._lock:
            return self._counts.get(site, 0)

    def sites(self) -> frozenset:
        """Sites this schedule has specs for (bypass probes — e.g. step
        capture stays eager while ``dispatch.*`` faults are scripted, so
        per-op injections keep firing per op instead of once at trace)."""
        with self._lock:
            return frozenset(self._specs)

    def check(self, site: str) -> None:
        """One pass through ``site``: bump the counter, fire at most one
        matching spec (first match wins, in authoring order)."""
        with self._lock:
            n = self._counts.get(site, 0) + 1
            self._counts[site] = n
            hit: Optional[_Spec] = None
            for spec in self._specs.get(site, ()):
                if spec.times is not None and spec.fired >= spec.times:
                    continue
                if spec.on is not None:
                    fire = n in spec.on
                elif spec.prob is not None:
                    fire = self._rng.random() < spec.prob
                else:
                    fire = True
                if fire:
                    spec.fired += 1
                    hit = spec
                    break
            if hit is not None:
                self.trace.append((site, n, hit.kind))
        if hit is None:
            return
        _obs.inc("resilience.injected_faults_total", site=site, kind=hit.kind)
        # the flight recorder's post-mortem tail names the fault site: a
        # killed/aborted run's dump ends at the seam that took it down
        _trace.record("fault", site=site, injected=hit.kind, call=n)
        if hit.kind == "delay":
            time.sleep(hit.seconds)
            return
        if hit.kind == "kill":
            raise KillPoint(f"injected kill at {site} (call {n})")
        raise hit.make_error(site, n)


# ---------------------------------------------------------------------------
# global install seam
# ---------------------------------------------------------------------------

_SCHEDULE: Optional[FaultSchedule] = None


def install(schedule: FaultSchedule) -> FaultSchedule:
    """Make ``schedule`` the process-wide active schedule (test/harness
    only; there is deliberately no way to enable this per-call on a hot
    path)."""
    global _SCHEDULE
    _SCHEDULE = schedule
    return schedule


def uninstall() -> None:
    global _SCHEDULE
    _SCHEDULE = None


class installed:
    """``with installed(schedule): ...`` — scoped install for tests."""

    def __init__(self, schedule: FaultSchedule):
        self.schedule = schedule

    def __enter__(self) -> FaultSchedule:
        install(self.schedule)
        return self.schedule

    def __exit__(self, *exc) -> None:
        uninstall()


def fault_point(site: str) -> None:
    """Zero-overhead when no schedule is installed: one global load and a
    ``None`` test."""
    s = _SCHEDULE
    if s is not None:
        s.check(site)
