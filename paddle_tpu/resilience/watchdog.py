"""Step watchdog: a monotonic-clock guard around one compiled call.

Born as the serving engine's step watchdog (PR 8) and generalized here
(PR 10) so the training supervisor can arm the SAME guard around each
compiled train step. A driver loop that issues one compiled call and one
host sync per step has exactly one failure mode an in-process observer
can still see: the call never comes back (a wedged transfer, a runaway
collective, a relay link gone quiet). The watchdog is the observer that
cannot be wedged:

* the driving thread ``arm()``s the watchdog immediately before the
  compiled call and ``disarm()``s after — two lock-guarded scalar
  writes, nothing else on the hot path;
* a daemon thread polls the armed window off the hot path (cadence via
  :func:`resilience.jitter_sleep` — the poll-loop primitive, so a fleet
  of engines/trainers never beats in phase) and, when the window exceeds
  ``timeout_s``, classifies the step:

  - ``"hung"`` — armed past ``timeout_s``: the step is overdue. One trip
    per armed window; ``<metric>{kind="hung"}``.
  - ``"zombie"`` — the SAME window still armed past ``2 * timeout_s``
    after tripping: the call may never return. Logged + counted
    (``kind="zombie"``) so an operator sees the difference between
    "slow" and "gone" — an in-process observer cannot preempt a thread
    blocked inside a compiled call, so past this point recovery is
    external (restart the process; crash-safe checkpointing and the
    caller's bounded replay/resume make that survivable).

* ``disarm()`` returns the window's classification (or None). What the
  caller does with a tripped-but-returned step is its own recovery
  contract: the serving engine abandons the step's outputs and replays
  the affected slots (functional pool state — nothing was committed);
  the training supervisor treats the step as unrecoverable and restores
  the last verified :class:`~paddle_tpu.resilience.trainer.TrainState`.

``metric``/``label`` default to the serving names so the extraction is
behavior-preserving for existing users; the trainer passes
``metric="train.watchdog_trips_total", label="train"``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from .. import observability as _obs
from ..observability import trace as _trace
from . import policy as _policy

__all__ = ["StepWatchdog", "WatchdogTimeout"]

_log = logging.getLogger(__name__)


class WatchdogTimeout(RuntimeError):
    """A compiled step exceeded the watchdog budget; its outputs were
    abandoned (serving) or its run rolled back to the last verified state
    (training). Requests/runs that exhaust their replay or restart budget
    recovering from this see it as their terminal error."""


class StepWatchdog:
    """Arm/disarm guard around one in-flight compiled step.

    ``arm()`` opens a window and returns its generation token; ``disarm``
    closes it and returns the classification the poll thread assigned
    (``"hung"`` / ``"zombie"``) or None if the step came back in time.
    The poll thread is started lazily on first arm and is restartable
    after :meth:`stop` (owners stop it on shutdown).
    Thread-safe; one window at a time (drivers are single-consumer).
    """

    def __init__(self, timeout_s: float, name: str = "paddle-tpu-watchdog",
                 *, metric: str = "serving.watchdog_trips_total",
                 label: str = "serving"):
        if timeout_s <= 0:
            raise ValueError(f"watchdog timeout must be > 0, got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self._name = name
        self._metric = metric
        self._label = label
        self._lock = threading.Lock()
        self._armed_at: Optional[float] = None
        self._gen = 0
        self._verdicts = {}          # gen -> "hung" | "zombie"
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # poll a few times per window; jitter_sleep decorrelates engines
        self._poll_s = max(0.002, self.timeout_s / 4.0)

    # -- hot path (step thread) ---------------------------------------------
    def arm(self) -> int:
        with self._lock:
            self._gen += 1
            self._armed_at = time.monotonic()
            gen = self._gen
            need_thread = self._thread is None or not self._thread.is_alive()
        if need_thread:
            self._start_thread()
        return gen

    def disarm(self, gen: int) -> Optional[str]:
        with self._lock:
            if self._gen == gen:
                self._armed_at = None
            return self._verdicts.pop(gen, None)

    # -- lifecycle ----------------------------------------------------------
    def _start_thread(self) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name=self._name, daemon=True)
            self._thread.start()

    def stop(self) -> None:
        """Stop the poll thread (idempotent; a later arm() restarts it)."""
        self._stop.set()
        # read under the lock: a concurrent arm() may be mid-restart in
        # _start_thread, and the unlocked read could join a thread object
        # already replaced (ISSUE 14: shared-state-race)
        with self._lock:
            t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0 * self._poll_s + 1.0)
        _trace.heartbeat_clear(f"{self._label}.watchdog")

    # -- poll loop (watchdog thread) ----------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                armed_at, gen = self._armed_at, self._gen
                verdict = self._verdicts.get(gen)
            # the /healthz beacon: the watchdog thread itself cannot be
            # wedged by a compiled call, so its beat going stale means the
            # PROCESS is in trouble; ok=False while a window is tripped
            _trace.heartbeat(f"{self._label}.watchdog",
                             ttl_s=max(1.0, 8.0 * self._poll_s),
                             ok=verdict is None)
            if armed_at is not None:
                waited = time.monotonic() - armed_at
                if verdict is None and waited > self.timeout_s:
                    self._trip(gen, armed_at, "hung", waited)
                elif verdict == "hung" and waited > 2.0 * self.timeout_s:
                    self._trip(gen, armed_at, "zombie", waited)
            _policy.jitter_sleep(self._poll_s)

    def _trip(self, gen: int, armed_at: float, kind: str,
              waited: float) -> None:
        with self._lock:
            # the window may have closed between the unlocked read and now
            if self._gen != gen or self._armed_at != armed_at:
                return
            self._verdicts[gen] = kind
        _obs.inc(self._metric, kind=kind)
        # ISSUE 12: a trip is a post-mortem moment — put the event in the
        # flight ring and snapshot it to disk while the process still can
        _trace.record("watchdog_trip", label=self._label, kind=kind,
                      waited_s=round(waited, 3),
                      budget_s=self.timeout_s)
        _trace.flight_dump(f"watchdog_{kind}", label=self._label,
                           waited_s=round(waited, 3))
        if kind == "hung":
            _log.warning(
                "%s watchdog: compiled step armed %.3fs > budget %.3fs "
                "— step classified hung; the owner's recovery path "
                "(abandon-and-replay / restore-last-good) takes over",
                self._label, waited, self.timeout_s)
        else:
            _log.warning(
                "%s watchdog: compiled step still running after %.3fs "
                "(> 2x budget %.3fs) — step classified ZOMBIE; in-process "
                "recovery is impossible if it never returns (restart the "
                "process; crash-safe checkpointing/replay makes the "
                "restart survivable)",
                self._label, waited, self.timeout_s)
