"""Native (C++) runtime components, loaded via ctypes.

The reference keeps its runtime seams native (TCPStore rendezvous, DataLoader
BlockingQueue feed, HostTracer — SURVEY.md §2.1/§2.3/§5); this package holds
our TPU-native equivalents, compiled from ``csrc/*.cc`` with the system g++ on
first import and cached by source hash. Everything has a pure-Python fallback
(``available() == False`` never breaks the framework).

Exposes:
    lib          — the loaded ctypes CDLL, or None
    available()  — whether the native library is usable
    BlockingQueue — token queue over the native library (Python objects are
                   kept alive in a side table; the native queue carries ids)
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Any, Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_CSRC = os.path.join(_HERE, "csrc")
_BUILD = os.path.join(_HERE, "_build")

_lib: Optional[ctypes.CDLL] = None
_lib_loaded = False
_load_lock = threading.Lock()
_build_error: Optional[str] = None


def _sources():
    return sorted(
        os.path.join(_CSRC, f) for f in os.listdir(_CSRC) if f.endswith(".cc")
    )


def _source_hash() -> str:
    h = hashlib.sha256()
    for src in _sources():
        with open(src, "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def _compile() -> Optional[str]:
    """Build (or reuse) the shared library; returns its path or None."""
    global _build_error
    try:
        tag = _source_hash()
    except OSError as e:
        _build_error = str(e)
        return None
    so_path = os.path.join(_BUILD, f"libptnative_{tag}.so")
    if os.path.exists(so_path):
        return so_path
    os.makedirs(_BUILD, exist_ok=True)
    tmp = so_path + f".tmp{os.getpid()}"
    cmd = ["g++", "-std=c++17", "-O2", "-fPIC", "-shared", "-pthread",
           "-o", tmp] + _sources()
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        _build_error = str(e)
        return None
    if r.returncode != 0:
        _build_error = r.stderr[-2000:]
        return None
    os.replace(tmp, so_path)  # atomic under concurrent builders
    return so_path


def _bind(l: ctypes.CDLL) -> None:
    c = ctypes
    # tcp_store
    l.pt_store_server_start.argtypes = [c.c_uint16]
    l.pt_store_server_start.restype = c.c_void_p
    l.pt_store_server_port.argtypes = [c.c_void_p]
    l.pt_store_server_port.restype = c.c_int
    l.pt_store_server_stop.argtypes = [c.c_void_p]
    l.pt_store_client_new.argtypes = [c.c_char_p, c.c_uint16, c.c_double]
    l.pt_store_client_new.restype = c.c_void_p
    l.pt_store_client_free.argtypes = [c.c_void_p]
    l.pt_store_set.argtypes = [c.c_void_p, c.c_char_p,
                               c.POINTER(c.c_uint8), c.c_uint64]
    l.pt_store_set.restype = c.c_int
    l.pt_store_get.argtypes = [c.c_void_p, c.c_char_p, c.c_double,
                               c.POINTER(c.POINTER(c.c_uint8))]
    l.pt_store_get.restype = c.c_int64
    l.pt_store_buf_free.argtypes = [c.POINTER(c.c_uint8)]
    l.pt_store_add.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
    l.pt_store_add.restype = c.c_int64
    l.pt_store_wait.argtypes = [c.c_void_p, c.c_char_p, c.c_double]
    l.pt_store_wait.restype = c.c_int
    l.pt_store_check.argtypes = [c.c_void_p, c.c_char_p]
    l.pt_store_check.restype = c.c_int
    l.pt_store_del.argtypes = [c.c_void_p, c.c_char_p]
    l.pt_store_del.restype = c.c_int
    l.pt_store_num_keys.argtypes = [c.c_void_p]
    l.pt_store_num_keys.restype = c.c_int64
    # blocking_queue
    l.pt_bq_new.argtypes = [c.c_uint64]
    l.pt_bq_new.restype = c.c_void_p
    l.pt_bq_free.argtypes = [c.c_void_p]
    l.pt_bq_push.argtypes = [c.c_void_p, c.c_uint64, c.c_double]
    l.pt_bq_push.restype = c.c_int
    l.pt_bq_pop.argtypes = [c.c_void_p, c.POINTER(c.c_uint64), c.c_double]
    l.pt_bq_pop.restype = c.c_int
    l.pt_bq_close.argtypes = [c.c_void_p]
    l.pt_bq_closed.argtypes = [c.c_void_p]
    l.pt_bq_closed.restype = c.c_int
    l.pt_bq_size.argtypes = [c.c_void_p]
    l.pt_bq_size.restype = c.c_uint64
    l.pt_bq_capacity.argtypes = [c.c_void_p]
    l.pt_bq_capacity.restype = c.c_uint64
    # host_tracer
    l.pt_trace_enable.argtypes = [c.c_uint64]
    l.pt_trace_disable.argtypes = []
    l.pt_trace_enabled.restype = c.c_int
    l.pt_trace_now_ns.restype = c.c_uint64
    l.pt_trace_emit.argtypes = [c.c_char_p, c.c_uint64, c.c_uint64,
                                c.c_uint32, c.c_uint64]
    l.pt_trace_count.restype = c.c_uint64
    l.pt_trace_clear.argtypes = []
    l.pt_trace_dump.argtypes = [c.c_char_p, c.c_uint64]
    l.pt_trace_dump.restype = c.c_uint64


def _load() -> Optional[ctypes.CDLL]:
    if os.environ.get("PADDLE_TPU_DISABLE_NATIVE"):
        return None
    so = _compile()
    if so is None:
        return None
    try:
        l = ctypes.CDLL(so)
        _bind(l)
        return l
    except OSError as e:
        global _build_error
        _build_error = str(e)
        return None


def _ensure_loaded() -> Optional[ctypes.CDLL]:
    """Compile+load on first use, not at import ('import paddle_tpu' must not
    block on a g++ subprocess when no native feature is exercised)."""
    global _lib, _lib_loaded
    if not _lib_loaded:
        with _load_lock:
            if not _lib_loaded:
                _lib = _load()
                _lib_loaded = True
    return _lib


def __getattr__(name: str):  # PEP 562: lazy `_native.lib`
    if name == "lib":
        return _ensure_loaded()
    raise AttributeError(name)


def available() -> bool:
    return _ensure_loaded() is not None


def build_error() -> Optional[str]:
    return _build_error


class BlockingQueue:
    """Bounded producer/consumer queue backed by the native library.

    Python objects are parked in a side table keyed by a monotonically
    increasing token; the native queue provides the blocking/backpressure
    semantics (reference: C++ BlockingQueue DataLoader feed).
    """

    CLOSED = object()
    TIMEOUT = object()

    def __init__(self, capacity: int):
        self._lib = _ensure_loaded()
        if self._lib is None:
            raise RuntimeError("native library unavailable: %s" % _build_error)
        self._h = self._lib.pt_bq_new(capacity)
        self._objs: dict[int, Any] = {}
        self._next = 0
        self._mu = threading.Lock()

    def push(self, obj: Any, timeout: float = -1.0) -> bool:
        with self._mu:
            token = self._next
            self._next += 1
            self._objs[token] = obj
        rc = self._lib.pt_bq_push(self._h, token, timeout)
        if rc != 0:
            with self._mu:
                self._objs.pop(token, None)
            return False
        return True

    def pop(self, timeout: float = -1.0) -> Any:
        out = ctypes.c_uint64()
        rc = self._lib.pt_bq_pop(self._h, ctypes.byref(out), timeout)
        if rc == -1:
            return self.TIMEOUT
        if rc == -2:
            return self.CLOSED
        with self._mu:
            return self._objs.pop(out.value)

    def close(self) -> None:
        self._lib.pt_bq_close(self._h)

    def __len__(self) -> int:
        return int(self._lib.pt_bq_size(self._h))

    def __del__(self):
        try:
            if self._lib is not None and self._h:
                self._lib.pt_bq_close(self._h)
                self._lib.pt_bq_free(self._h)
                self._h = None
        except Exception:
            pass  # interpreter teardown: ctypes lib/handle may already be
            #       unloaded; nothing to release into
