// TCPStore: rank-0-hosted key-value rendezvous store.
//
// TPU-native equivalent of the reference's C++ TCPStore
// (paddle/phi/core/distributed/store/ — no line cites: reference mount was
// empty, see SURVEY.md provenance). Same role: bootstrap KV + barrier
// counters for multi-process jobs. Wire protocol (little-endian):
//   request:  u8 op | u32 klen | key bytes | u64 vlen | value bytes
//   response: u8 status | u64 vlen | value bytes        (status 0=ok 1=miss)
// ops: 1=SET 2=GET(value=8B timeout_ms) 3=ADD(value=8B i64 delta)
//      4=WAIT(value=8B timeout_ms) 5=CHECK 6=DEL 7=NUMKEYS
// The Python fallback (paddle_tpu/distributed/store.py) speaks the same
// protocol, so native and pure-Python ends interoperate.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

enum Op : uint8_t {
  kSet = 1, kGet = 2, kAdd = 3, kWait = 4, kCheck = 5, kDel = 6, kNumKeys = 7,
};

bool ReadFull(int fd, void* buf, size_t n) {
  auto* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool WriteFull(int fd, const void* buf, size_t n) {
  const auto* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

struct Server {
  int listen_fd = -1;
  uint16_t port = 0;
  std::atomic<bool> stop{false};
  std::thread accept_thread;
  std::vector<std::thread> conn_threads;
  std::vector<int> conn_fds;
  std::mutex conn_mu;

  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::string> kv;

  ~Server() { Stop(); }

  void Stop() {
    bool expected = false;
    if (!stop.compare_exchange_strong(expected, true)) return;
    if (listen_fd >= 0) {
      ::shutdown(listen_fd, SHUT_RDWR);
      ::close(listen_fd);
      listen_fd = -1;
    }
    cv.notify_all();
    {
      // unblock Serve threads sitting in recv() on live connections;
      // without this, Stop() would join() forever while any client
      // (e.g. a straggler rank) still holds its connection open
      std::lock_guard<std::mutex> g(conn_mu);
      for (int fd : conn_fds) ::shutdown(fd, SHUT_RDWR);
    }
    if (accept_thread.joinable()) accept_thread.join();
    std::vector<std::thread> conns;
    {
      std::lock_guard<std::mutex> g(conn_mu);
      conns.swap(conn_threads);
    }
    for (auto& t : conns)
      if (t.joinable()) t.join();
  }

  void Serve(int fd) {
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    while (!stop.load()) {
      uint8_t op;
      uint32_t klen;
      uint64_t vlen;
      if (!ReadFull(fd, &op, 1) || !ReadFull(fd, &klen, 4)) break;
      if (klen > (1u << 20)) break;
      std::string key(klen, '\0');
      if (klen && !ReadFull(fd, &key[0], klen)) break;
      if (!ReadFull(fd, &vlen, 8)) break;
      if (vlen > (1ull << 32)) break;
      std::string val(vlen, '\0');
      if (vlen && !ReadFull(fd, &val[0], vlen)) break;

      uint8_t status = 0;
      std::string out;
      switch (op) {
        case kSet: {
          std::lock_guard<std::mutex> g(mu);
          kv[key] = val;
          cv.notify_all();
          break;
        }
        case kGet:
        case kWait: {
          uint64_t timeout_ms = 0;
          if (val.size() == 8) std::memcpy(&timeout_ms, val.data(), 8);
          std::unique_lock<std::mutex> lk(mu);
          bool ok = cv.wait_for(
              lk, std::chrono::milliseconds(timeout_ms),
              [&] { return stop.load() || kv.count(key) != 0; });
          if (!ok || stop.load() || kv.count(key) == 0) {
            status = 1;
          } else if (op == kGet) {
            out = kv[key];
          }
          break;
        }
        case kAdd: {
          int64_t delta = 0;
          if (val.size() == 8) std::memcpy(&delta, val.data(), 8);
          std::lock_guard<std::mutex> g(mu);
          int64_t cur = 0;
          auto it = kv.find(key);
          if (it != kv.end() && it->second.size() == 8)
            std::memcpy(&cur, it->second.data(), 8);
          cur += delta;
          std::string enc(8, '\0');
          std::memcpy(&enc[0], &cur, 8);
          kv[key] = enc;
          out = enc;
          cv.notify_all();
          break;
        }
        case kCheck: {
          std::lock_guard<std::mutex> g(mu);
          status = kv.count(key) ? 0 : 1;
          break;
        }
        case kDel: {
          std::lock_guard<std::mutex> g(mu);
          status = kv.erase(key) ? 0 : 1;
          cv.notify_all();
          break;
        }
        case kNumKeys: {
          std::lock_guard<std::mutex> g(mu);
          int64_t n = static_cast<int64_t>(kv.size());
          out.assign(8, '\0');
          std::memcpy(&out[0], &n, 8);
          break;
        }
        default:
          status = 1;
      }
      uint64_t olen = out.size();
      if (!WriteFull(fd, &status, 1) || !WriteFull(fd, &olen, 8)) break;
      if (olen && !WriteFull(fd, out.data(), olen)) break;
    }
    {
      // deregister before closing so Stop() never shutdown()s a recycled
      // descriptor belonging to an unrelated connection
      std::lock_guard<std::mutex> g(conn_mu);
      for (auto it = conn_fds.begin(); it != conn_fds.end(); ++it) {
        if (*it == fd) {
          conn_fds.erase(it);
          break;
        }
      }
    }
    ::close(fd);
  }

  void AcceptLoop() {
    while (!stop.load()) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (stop.load()) break;
        continue;
      }
      std::lock_guard<std::mutex> g(conn_mu);
      conn_fds.push_back(fd);
      conn_threads.emplace_back([this, fd] { Serve(fd); });
    }
  }
};

struct Client {
  int fd = -1;
  std::mutex mu;

  ~Client() {
    if (fd >= 0) ::close(fd);
  }

  // Returns status byte, or -1 on transport error; response value in *out.
  int Request(uint8_t op, const char* key, const void* val, uint64_t vlen,
              std::string* out) {
    std::lock_guard<std::mutex> g(mu);
    uint32_t klen = static_cast<uint32_t>(std::strlen(key));
    if (!WriteFull(fd, &op, 1) || !WriteFull(fd, &klen, 4) ||
        (klen && !WriteFull(fd, key, klen)) || !WriteFull(fd, &vlen, 8) ||
        (vlen && !WriteFull(fd, val, vlen)))
      return -1;
    uint8_t status;
    uint64_t olen;
    if (!ReadFull(fd, &status, 1) || !ReadFull(fd, &olen, 8)) return -1;
    out->assign(olen, '\0');
    if (olen && !ReadFull(fd, &(*out)[0], olen)) return -1;
    return status;
  }
};

}  // namespace

extern "C" {

void* pt_store_server_start(uint16_t port) {
  auto* s = new Server();
  s->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) {
    delete s;
    return nullptr;
  }
  int one = 1;
  ::setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(port);
  if (::bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
          0 ||
      ::listen(s->listen_fd, 64) < 0) {
    ::close(s->listen_fd);
    delete s;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  s->port = ntohs(addr.sin_port);
  s->accept_thread = std::thread([s] { s->AcceptLoop(); });
  return s;
}

int pt_store_server_port(void* h) {
  return h ? static_cast<Server*>(h)->port : -1;
}

void pt_store_server_stop(void* h) {
  if (!h) return;
  auto* s = static_cast<Server*>(h);
  s->Stop();
  delete s;
}

void* pt_store_client_new(const char* host, uint16_t port, double timeout_s) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration<double>(timeout_s);
  while (true) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return nullptr;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(port);
    if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
      // caller must resolve hostnames; a silent loopback fallback would
      // rendezvous with the wrong store on multi-host jobs
      ::close(fd);
      return nullptr;
    }
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      auto* c = new Client();
      c->fd = fd;
      return c;
    }
    ::close(fd);
    if (std::chrono::steady_clock::now() >= deadline) return nullptr;
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
}

void pt_store_client_free(void* h) { delete static_cast<Client*>(h); }

int pt_store_set(void* h, const char* key, const uint8_t* val, uint64_t len) {
  std::string out;
  return static_cast<Client*>(h)->Request(kSet, key, val, len, &out);
}

// Returns value length with *out a malloc'd copy the caller must release via
// pt_store_buf_free (a per-call buffer: concurrent get()s on one client must
// not share storage). -1 on timeout/miss, -2 on transport error.
int64_t pt_store_get(void* h, const char* key, double timeout_s,
                     uint8_t** out) {
  auto* c = static_cast<Client*>(h);
  uint64_t ms = timeout_s <= 0 ? 0 : static_cast<uint64_t>(timeout_s * 1e3);
  std::string res;
  int st = c->Request(kGet, key, &ms, 8, &res);
  if (st < 0) return -2;
  if (st != 0) return -1;
  auto* buf = static_cast<uint8_t*>(::malloc(res.size() ? res.size() : 1));
  if (!buf) return -2;
  std::memcpy(buf, res.data(), res.size());
  *out = buf;
  return static_cast<int64_t>(res.size());
}

void pt_store_buf_free(uint8_t* p) { ::free(p); }

int64_t pt_store_add(void* h, const char* key, int64_t delta) {
  std::string out;
  int st = static_cast<Client*>(h)->Request(kAdd, key, &delta, 8, &out);
  if (st != 0 || out.size() != 8) return INT64_MIN;
  int64_t v;
  std::memcpy(&v, out.data(), 8);
  return v;
}

int pt_store_wait(void* h, const char* key, double timeout_s) {
  uint64_t ms = timeout_s <= 0 ? 0 : static_cast<uint64_t>(timeout_s * 1e3);
  std::string out;
  int st = static_cast<Client*>(h)->Request(kWait, key, &ms, 8, &out);
  return st == 0 ? 0 : -1;
}

int pt_store_check(void* h, const char* key) {
  std::string out;
  return static_cast<Client*>(h)->Request(kCheck, key, nullptr, 0, &out) == 0
             ? 1
             : 0;
}

int pt_store_del(void* h, const char* key) {
  std::string out;
  return static_cast<Client*>(h)->Request(kDel, key, nullptr, 0, &out) == 0 ? 1
                                                                            : 0;
}

int64_t pt_store_num_keys(void* h) {
  std::string out;
  int st = static_cast<Client*>(h)->Request(kNumKeys, "", nullptr, 0, &out);
  if (st != 0 || out.size() != 8) return -1;
  int64_t v;
  std::memcpy(&v, out.data(), 8);
  return v;
}

}  // extern "C"
