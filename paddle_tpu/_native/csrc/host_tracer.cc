// Host tracer: lock-light ring buffer of completed host ranges.
//
// TPU-native equivalent of the reference's C++ HostTracer/RecordEvent
// (paddle/fluid/platform/profiler/ — no line cites: reference mount was
// empty, see SURVEY.md provenance). Device-side tracing is libtpu/XProf via
// jax.profiler; this covers the host ranges the reference instruments with
// RecordEvent RAII markers. Events are dumped as chrome-trace JSON fragments.

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

constexpr int kNameLen = 64;

struct Event {
  char name[kNameLen];
  uint64_t t0_ns;
  uint64_t t1_ns;
  uint64_t tid;
  uint32_t cat;
};

struct Tracer {
  std::mutex mu;
  std::vector<Event> ring;
  uint64_t head = 0;   // next write slot
  uint64_t count = 0;  // total written (may exceed ring size)
  std::atomic<bool> enabled{false};
};

Tracer g_tracer;

uint64_t NowNs() {
  return static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::nanoseconds>(
          std::chrono::steady_clock::now().time_since_epoch())
          .count());
}

uint64_t Tid() {
  return std::hash<std::thread::id>{}(std::this_thread::get_id()) & 0xffffff;
}

// JSON string escaping for event names: quotes, backslashes, control bytes.
// Bytes >= 0x80 pass through untouched — the emitter guarantees valid UTF-8
// (Python truncates on codepoint boundaries), and per-byte \u00XX escapes
// would turn multi-byte characters into mojibake after json.loads.
std::string JsonEscape(const char* s) {
  std::string out;
  for (const char* p = s; *p; p++) {
    unsigned char c = static_cast<unsigned char>(*p);
    if (c == '"' || c == '\\') {
      out += '\\';
      out += static_cast<char>(c);
    } else if (c < 0x20) {
      char esc[8];
      std::snprintf(esc, sizeof(esc), "\\u%04x", c);
      out += esc;
    } else {
      out += static_cast<char>(c);
    }
  }
  return out;
}

}  // namespace

extern "C" {

void pt_trace_enable(uint64_t capacity) {
  std::lock_guard<std::mutex> g(g_tracer.mu);
  if (capacity == 0) capacity = 1 << 16;
  g_tracer.ring.assign(capacity, Event{});
  g_tracer.head = 0;
  g_tracer.count = 0;
  g_tracer.enabled.store(true);
}

void pt_trace_disable() { g_tracer.enabled.store(false); }

int pt_trace_enabled() { return g_tracer.enabled.load() ? 1 : 0; }

uint64_t pt_trace_now_ns() { return NowNs(); }

// Record a completed range. Timestamps are steady-clock ns (pt_trace_now_ns).
void pt_trace_emit(const char* name, uint64_t t0_ns, uint64_t t1_ns,
                   uint32_t cat, uint64_t tid) {
  if (!g_tracer.enabled.load()) return;
  std::lock_guard<std::mutex> g(g_tracer.mu);
  if (g_tracer.ring.empty()) return;
  Event& e = g_tracer.ring[g_tracer.head];
  std::strncpy(e.name, name, kNameLen - 1);
  e.name[kNameLen - 1] = '\0';
  e.t0_ns = t0_ns;
  e.t1_ns = t1_ns;
  e.cat = cat;
  e.tid = tid ? tid : Tid();
  g_tracer.head = (g_tracer.head + 1) % g_tracer.ring.size();
  g_tracer.count++;
}

uint64_t pt_trace_count() {
  std::lock_guard<std::mutex> g(g_tracer.mu);
  return g_tracer.count;
}

void pt_trace_clear() {
  std::lock_guard<std::mutex> g(g_tracer.mu);
  g_tracer.head = 0;
  g_tracer.count = 0;
}

// Serialize buffered events as a JSON array of
// {"name":..,"ts":us,"dur":us,"tid":..,"cat":N} and clear the buffer.
// Returns bytes needed (including NUL); writes up to buflen bytes into buf.
// Call with buf=NULL to size, then again with a buffer.
uint64_t pt_trace_dump(char* buf, uint64_t buflen) {
  std::lock_guard<std::mutex> g(g_tracer.mu);
  uint64_t n = g_tracer.count < g_tracer.ring.size() ? g_tracer.count
                                                     : g_tracer.ring.size();
  uint64_t start =
      g_tracer.count <= g_tracer.ring.size()
          ? 0
          : g_tracer.head;  // oldest surviving slot when wrapped
  std::string out = "[";
  char tmp[128];  // numeric fields only — the name is appended unbounded
  for (uint64_t i = 0; i < n; i++) {
    const Event& e = g_tracer.ring[(start + i) % g_tracer.ring.size()];
    if (i) out += ",";
    out += "{\"name\":\"";
    out += JsonEscape(e.name);
    std::snprintf(tmp, sizeof(tmp),
                  "\",\"ts\":%.3f,\"dur\":%.3f,\"tid\":%llu,\"cat\":%u}",
                  e.t0_ns / 1e3, (e.t1_ns - e.t0_ns) / 1e3,
                  static_cast<unsigned long long>(e.tid), e.cat);
    out += tmp;
  }
  out += "]";
  uint64_t need = out.size() + 1;
  if (buf && buflen) {
    uint64_t c = need <= buflen ? need : buflen;
    std::memcpy(buf, out.data(), c - 1);
    buf[c - 1] = '\0';
    if (need <= buflen) {  // only clear when the caller got everything
      g_tracer.head = 0;
      g_tracer.count = 0;
    }
  }
  return need;
}

}  // extern "C"
