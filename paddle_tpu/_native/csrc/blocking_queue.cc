// Bounded blocking queue of opaque 64-bit tokens.
//
// TPU-native equivalent of the reference's C++ BlockingQueue feed used by its
// DataLoader (paddle/fluid/operators/reader/ — no line cites: reference mount
// was empty, see SURVEY.md provenance). The queue carries tokens (Python-side
// object handles) so producer/consumer handoff and backpressure happen in
// native code without the GIL; payload ownership stays with the caller.

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <mutex>

namespace {

struct Queue {
  explicit Queue(uint64_t cap) : capacity(cap ? cap : 1) {}
  uint64_t capacity;
  std::deque<uint64_t> items;
  bool closed = false;
  std::mutex mu;
  std::condition_variable not_full;
  std::condition_variable not_empty;
};

}  // namespace

extern "C" {

void* pt_bq_new(uint64_t capacity) { return new Queue(capacity); }

void pt_bq_free(void* h) { delete static_cast<Queue*>(h); }

// 0 = ok, -1 = timeout, -2 = closed.
int pt_bq_push(void* h, uint64_t token, double timeout_s) {
  auto* q = static_cast<Queue*>(h);
  std::unique_lock<std::mutex> lk(q->mu);
  auto pred = [&] { return q->closed || q->items.size() < q->capacity; };
  if (timeout_s < 0) {
    q->not_full.wait(lk, pred);
  } else if (!q->not_full.wait_for(lk, std::chrono::duration<double>(timeout_s),
                                   pred)) {
    return -1;
  }
  if (q->closed) return -2;
  q->items.push_back(token);
  q->not_empty.notify_one();
  return 0;
}

// 0 = ok, -1 = timeout, -2 = closed-and-drained.
int pt_bq_pop(void* h, uint64_t* token, double timeout_s) {
  auto* q = static_cast<Queue*>(h);
  std::unique_lock<std::mutex> lk(q->mu);
  auto pred = [&] { return q->closed || !q->items.empty(); };
  if (timeout_s < 0) {
    q->not_empty.wait(lk, pred);
  } else if (!q->not_empty.wait_for(
                 lk, std::chrono::duration<double>(timeout_s), pred)) {
    return -1;
  }
  if (q->items.empty()) return -2;  // closed and drained
  *token = q->items.front();
  q->items.pop_front();
  q->not_full.notify_one();
  return 0;
}

void pt_bq_close(void* h) {
  auto* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> g(q->mu);
  q->closed = true;
  q->not_full.notify_all();
  q->not_empty.notify_all();
}

int pt_bq_closed(void* h) {
  auto* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> g(q->mu);
  return q->closed ? 1 : 0;
}

uint64_t pt_bq_size(void* h) {
  auto* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> g(q->mu);
  return q->items.size();
}

uint64_t pt_bq_capacity(void* h) { return static_cast<Queue*>(h)->capacity; }

}  // extern "C"
