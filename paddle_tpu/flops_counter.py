"""``paddle.flops``: per-layer FLOPs profiler (reference:
python/paddle/hapi/dynamic_flops.py — forward hooks count multiply-adds per
registered layer type, summed over a dummy forward).

Since ISSUE 16 this analytic estimate is unified with the program cost
registry (:mod:`paddle_tpu.observability.cost`): each ``flops()`` call
files its per-network total as a ``model_source="analytic"`` record, and
the registry uses the same analytic figure as the fallback when XLA
returns no cost model for a compiled program
(``StaticFunction.cost_analytic_flops``)."""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from . import nn
from .core.tensor import Tensor

__all__ = ["flops"]


def _prod(xs):
    out = 1
    for x in xs:
        out *= int(x)
    return out


def _count_conv(layer, x, y):
    kernel_ops = _prod(layer.weight.shape[2:]) * int(layer.weight.shape[1])
    bias_ops = 1 if getattr(layer, "bias", None) is not None else 0
    out_elems = _prod(y.shape)
    return out_elems * (kernel_ops + bias_ops)


def _count_linear(layer, x, y):
    in_f = int(layer.weight.shape[0])
    bias_ops = 1 if getattr(layer, "bias", None) is not None else 0
    return _prod(y.shape) * (in_f + bias_ops)


def _count_norm(layer, x, y):
    return 2 * _prod(x.shape)


def _count_act(layer, x, y):
    return _prod(y.shape)


def _count_pool(layer, x, y):
    return _prod(y.shape)


_COUNTERS = {
    nn.Conv1D: _count_conv, nn.Conv2D: _count_conv, nn.Conv3D: _count_conv,
    nn.Linear: _count_linear,
    nn.BatchNorm1D: _count_norm, nn.BatchNorm2D: _count_norm,
    nn.BatchNorm3D: _count_norm, nn.LayerNorm: _count_norm,
    nn.ReLU: _count_act, nn.ReLU6: _count_act, nn.Sigmoid: _count_act,
    nn.Hardswish: _count_act, nn.Hardsigmoid: _count_act,
    nn.AvgPool2D: _count_pool, nn.MaxPool2D: _count_pool,
    nn.AdaptiveAvgPool2D: _count_pool, nn.AdaptiveMaxPool2D: _count_pool,
}


def flops(net: "nn.Layer", input_size: List[int], custom_ops: Optional[Dict] = None,
          print_detail: bool = False) -> int:
    """Total FLOPs of one forward at ``input_size`` (paddle.flops parity:
    counts multiply-adds for conv/linear, elementwise for act/norm/pool)."""
    counters = dict(_COUNTERS)
    if custom_ops:
        counters.update(custom_ops)

    totals: Dict[int, int] = {}
    names: Dict[int, str] = {}
    handles = []

    def make_hook(layer, fn, name):
        def hook(lyr, inputs, output):
            x = inputs[0] if isinstance(inputs, (tuple, list)) else inputs
            y = output[0] if isinstance(output, (tuple, list)) else output
            totals[id(lyr)] = totals.get(id(lyr), 0) + int(fn(lyr, x, y))
            names[id(lyr)] = name
        return hook

    for name, sub in net.named_sublayers():
        fn = counters.get(type(sub))
        if fn is not None:
            handles.append(sub.register_forward_post_hook(
                make_hook(sub, fn, name)))

    was_training = net.training
    net.eval()
    try:
        import paddle_tpu as paddle
        x = paddle.to_tensor(np.zeros(input_size, np.float32))
        net(x)
    finally:
        for h in handles:
            h.remove()
        if was_training:
            net.train()

    total = sum(totals.values())
    from .observability import cost as _cost
    if _cost.installed():
        # the cost registry's analytic leg: the same number XLA-less
        # programs fall back to, labeled model_source="analytic"
        _cost.record_analytic(type(net).__name__, total)
    if print_detail:
        print(f"{'Layer':<40}{'FLOPs':>16}")
        for lid, v in totals.items():
            print(f"{names[lid]:<40}{v:>16,}")
        print(f"{'Total':<40}{total:>16,}")
    else:
        print(f"Total Flops: {total}     Total Params: {_num_params(net)}")
    return total


def _num_params(net) -> int:
    return sum(_prod(p.shape) for p in net.parameters())
