"""``paddle.sysconfig`` (reference: python/paddle/sysconfig.py)."""

from __future__ import annotations

import os.path as osp

__all__ = ["get_include", "get_lib"]

_ROOT = osp.dirname(osp.abspath(__file__))


def get_include() -> str:
    """Directory of C/C++ headers shipped with the framework (the native
    runtime's csrc tree)."""
    return osp.join(_ROOT, "_native", "csrc")


def get_lib() -> str:
    """Directory of the built native shared libraries."""
    return osp.join(_ROOT, "_native", "lib")
