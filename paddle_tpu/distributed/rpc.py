"""``paddle.distributed.rpc`` — worker-to-worker remote procedure calls.

Parity: python/paddle/distributed/rpc/ (init_rpc, rpc_sync, rpc_async,
shutdown, get_worker_info) — upstream rides brpc; here each worker runs a
pickle-over-TCP listener thread and workers discover each other through the
rendezvous store (the same seam the collective stack bootstraps with).
Device tensors serialize through host numpy (PJRT buffers cannot cross
process boundaries).

Trust model: RPC payloads are pickles, i.e. arbitrary code at the receiver —
acceptable only between the job's own trainers (upstream brpc makes the same
assumption inside the trainer transport). Mitigations, not guarantees: the
listener binds to the job's interface (loopback for single-host runs), and
every message carries an HMAC keyed by a per-job secret, so stray/broken
peers and port-scanners can't trigger deserialization. LIMIT: by default
the secret is distributed through the rendezvous TCPStore, so anyone who
can reach the store port can fetch it — on untrusted networks set
``PADDLE_RPC_SECRET`` (same value on every worker) to move the secret
out-of-band, and keep the store/RPC ports firewalled to the job.
"""

from __future__ import annotations

import hmac as _hmac
import hashlib
import pickle
import secrets as _secrets
import socket
import socketserver
import struct
import threading
import time
from collections import namedtuple
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, Optional

from .. import resilience as _resil
from ..resilience import faults as _faults

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "get_all_worker_infos",
           "get_current_worker_info", "RpcTransportError",
           "send_msg", "recv_msg"]


class RpcTransportError(ConnectionError):
    """The REQUEST never completed at the transport layer (dial/read
    failure). Distinct from a server-side exception (re-raised as its
    original type), so failover retry loops can retry ONLY transport
    failures instead of re-executing calls the server already ran and
    answered with an error."""

WorkerInfo = namedtuple("WorkerInfo", ["name", "rank", "ip", "port"])

_state: Dict[str, object] = {}


def _routable_host() -> str:
    """Address other nodes can dial: PADDLE_RPC_HOST overrides; otherwise
    the interface a UDP connect to a public address would use; loopback as
    the single-host fallback."""
    import os

    env = os.environ.get("PADDLE_RPC_HOST")
    if env:
        return env
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))  # no packet is actually sent
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


class FutureWrapper:
    """Parity with paddle's rpc future: ``wait()`` blocks for the result."""

    def __init__(self, fut: Future):
        self._fut = fut

    def wait(self, timeout=None):
        return self._fut.result(timeout)

    def result(self, timeout=None):
        return self._fut.result(timeout)

    def done(self) -> bool:
        return self._fut.done()


_MAC_LEN = 32  # sha256 digest


def _mac(payload: bytes, secret: Optional[bytes] = None) -> bytes:
    if secret is None:
        secret = _state.get("secret")
    if not secret:
        raise RuntimeError("rpc not initialized (no job secret)")
    return _hmac.new(secret, payload, hashlib.sha256).digest()


def send_msg(sock: socket.socket, payload: bytes,
             secret: Optional[bytes] = None) -> None:
    """One length-prefixed, MAC'd frame. ``secret=None`` uses the job
    secret ``init_rpc`` installed; an explicit ``secret`` lets transports
    that distribute their key out-of-band (the serving fleet tier) reuse
    this framing without the rendezvous store."""
    payload = _mac(payload, secret) + payload
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def recv_msg(sock: socket.socket, secret: Optional[bytes] = None) -> bytes:
    """Inverse of :func:`send_msg`: reads one frame, verifies its MAC.
    A peer hanging up mid-frame raises ``ConnectionError`` as soon as the
    kernel reports the closed stream — never a silent short read."""
    header = b""
    while len(header) < 8:
        chunk = sock.recv(8 - len(header))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        header += chunk
    (n,) = struct.unpack("<Q", header)
    if n < _MAC_LEN:
        raise ConnectionError("rpc message too short to be authenticated")
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("rpc peer closed mid-message")
        buf.extend(chunk)
    mac, payload = bytes(buf[:_MAC_LEN]), bytes(buf[_MAC_LEN:])
    if not _hmac.compare_digest(mac, _mac(payload, secret)):
        raise ConnectionError("rpc message failed authentication")
    return payload


# job-secret shorthands (the in-package callers)
_send_msg = send_msg
_recv_msg = recv_msg


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            fn, args, kwargs = pickle.loads(_recv_msg(self.request))
            try:
                result = (True, fn(*args, **kwargs))
            except Exception as exc:  # ship the exception back
                result = (False, exc)
            _send_msg(self.request, pickle.dumps(result))
        except ConnectionError:
            pass  # peer hung up mid-reply: client-side retry owns recovery


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None,
             master_endpoint: Optional[str] = None) -> None:
    """Start this worker's RPC listener and register it in the store."""
    from .env import get_rank, get_world_size
    from .store import TCPStore

    rank = get_rank() if rank is None else int(rank)
    world_size = get_world_size() if world_size is None else int(world_size)
    host = _routable_host()

    if master_endpoint is None:
        master_endpoint = "127.0.0.1:29530"
    mhost, _, mport = master_endpoint.partition(":")
    store = TCPStore(mhost, int(mport), is_master=(rank == 0),
                     world_size=world_size)
    # per-job shared secret: PADDLE_RPC_SECRET (out-of-band) wins; else
    # rank 0 mints one and distributes via the store. Fetched BEFORE the
    # listener publishes its endpoint so every request it serves is
    # authenticated.
    import os
    env_secret = os.environ.get("PADDLE_RPC_SECRET")
    if env_secret:
        _state["secret"] = env_secret.encode()
    else:
        if rank == 0:
            store.set("rpc/secret", _secrets.token_bytes(32))
        _state["secret"] = bytes(store.get("rpc/secret"))

    # bind to the interface peers will actually dial; PADDLE_RPC_HOST may
    # be an external NAT address that is not locally bindable — fall back
    # to all interfaces in that case (the HMAC still gates every message)
    try:
        server = _Server((host, 0), _Handler)
    except OSError:
        server = _Server(("0.0.0.0", 0), _Handler)
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()

    store.set(f"rpc/{rank}", f"{name},{host},{port}".encode())
    infos = {}
    for r in range(world_size):
        raw = store.get(f"rpc/{r}").decode()
        wname, whost, wport = raw.split(",")
        infos[wname] = WorkerInfo(wname, r, whost, int(wport))
    _state.update(server=server, thread=thread, store=store, name=name,
                  rank=rank, infos=infos,
                  pool=ThreadPoolExecutor(max_workers=8))


def get_worker_info(name: str) -> WorkerInfo:
    return _state["infos"][name]


def refresh_worker_info(name: str) -> WorkerInfo:
    """Re-resolve ``name``'s endpoint from the rendezvous store.

    A respawned peer (PS failover) re-registers under the same name with a
    NEW port; callers that cached the old endpoint re-resolve on
    connection failure instead of failing the job."""
    info = _state["infos"][name]
    raw = _state["store"].get(f"rpc/{info.rank}").decode()
    wname, whost, wport = raw.split(",")
    fresh = WorkerInfo(wname, info.rank, whost, int(wport))
    _state["infos"][wname] = fresh
    return fresh


def get_all_worker_infos():
    return list(_state["infos"].values())


def get_current_worker_info() -> WorkerInfo:
    return _state["infos"][_state["name"]]


def _dial(info, timeout):
    """Connect to a peer under the ``rpc.dial`` policy: a couple of quick
    jittered re-dials absorb transient SYN drops / listen-backlog races
    without re-executing anything (nothing was sent yet). The caller's
    ``timeout`` is the TOTAL dial budget — each attempt's connect timeout
    is clamped to what remains, so ``rpc_sync(timeout=T)`` still fails by
    ~T against a blackholed host instead of 3×T. The policy also clamps
    to any ambient ``deadline_scope`` (e.g. the PS failover budget), so
    dial retries never extend a caller's deadline."""
    policy = _resil.get_policy("rpc.dial", base_delay=0.05, multiplier=2.0,
                               max_delay=0.4, jitter=0.25, max_attempts=3)
    total = timeout if timeout and timeout > 0 else None
    for attempt in policy.start(deadline=total):
        left = attempt.remaining()
        try:
            return socket.create_connection(
                (info.ip, info.port),
                timeout=None if left is None else max(0.01, left))
        except OSError as e:
            attempt.fail(e)  # re-raises the OSError once the budget is spent


def _effective_timeout(timeout) -> Optional[float]:
    """The call's TOTAL budget in seconds: an explicit positive ``timeout``
    wins; the paddle ``-1``/``None`` sentinel inherits what remains of the
    ambient :class:`resilience.deadline_scope` (None = unbounded). A fleet/
    serving call made under a request deadline is therefore bounded end to
    end without every call site re-plumbing the number."""
    if timeout is not None and timeout > 0:
        return float(timeout)
    ambient = _resil.current_deadline()
    if ambient is None:
        return None
    return max(1e-3, ambient - time.monotonic())


def _call(to: str, fn, args, kwargs, timeout):
    info = get_worker_info(to)
    _faults.fault_point("rpc.call")
    total = _effective_timeout(timeout)
    deadline = None if total is None else time.monotonic() + total
    try:
        with _dial(info, total) as sock:
            # bound the wire phase by what remains of the budget: a peer
            # that dies mid-reply surfaces ECONNRESET/EOF promptly through
            # recv_msg, and a peer that WEDGES (accepts, never answers)
            # trips socket.timeout instead of hanging the caller forever
            if deadline is not None:
                sock.settimeout(max(1e-3, deadline - time.monotonic()))
            _send_msg(sock, pickle.dumps((fn, args or (), kwargs or {})))
            ok, payload = pickle.loads(_recv_msg(sock))
        # lost-reply seam: the peer EXECUTED the call but the reply
        # "never arrived" — retrying callers must tolerate re-execution
        # (the PS plane does, via its seq dedup watermark)
        _faults.fault_point("rpc.reply")
    except (ConnectionError, OSError, EOFError) as e:
        raise RpcTransportError(f"rpc to {to} failed in transport: {e}") \
            from e
    if not ok:
        raise payload
    return payload


def rpc_sync(to: str, fn, args=None, kwargs=None, timeout=-1):
    """Run ``fn`` on worker ``to``; block for the result. ``timeout=-1``
    (the paddle sentinel) bounds the call by the ambient
    ``resilience.deadline_scope`` when one is installed."""
    return _call(to, fn, args, kwargs, timeout)


def rpc_async(to: str, fn, args=None, kwargs=None, timeout=-1) -> FutureWrapper:
    """Run ``fn`` on worker ``to``; returns a future with ``wait()``."""
    return FutureWrapper(
        _state["pool"].submit(_call, to, fn, args, kwargs, timeout))


def shutdown() -> None:
    server = _state.pop("server", None)
    if server is not None:
        server.shutdown()
        server.server_close()
    pool = _state.pop("pool", None)
    if pool is not None:
        pool.shutdown(wait=False)
    _state.clear()
