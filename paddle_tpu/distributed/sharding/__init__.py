"""ZeRO sharding stages 1/2/3.

Parity surface: python/paddle/distributed/sharding/ (``group_sharded_parallel``,
GroupShardedOptimizerStage2, GroupShardedStage3) and the fleet
DygraphShardingOptimizer (upstream
python/paddle/distributed/fleet/meta_optimizers/dygraph_optimizer/).

TPU-native design (SURVEY.md §7.4): stages are STORAGE SHARDINGS over the
``sharding`` mesh axis, enforced with NamedSharding on the relevant arrays —
stage 1 shards optimizer state, stage 2 additionally keeps grads sharded
through the update, stage 3 shards parameter storage so XLA gathers weights
just-in-time per layer and reduce-scatters their grads (the DeepSpeed
gather/release dance becomes GSPMD's job).
"""

from .sharding_optimizer import (DygraphShardingOptimizer,  # noqa: F401
                                 group_sharded_parallel, shard_model_params)

__all__ = ["DygraphShardingOptimizer", "group_sharded_parallel",
           "shard_model_params"]
