"""Sharding (ZeRO) optimizer wrappers."""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from ...optimizer import Optimizer
from ..topology import get_hybrid_communicate_group, global_mesh

__all__ = ["DygraphShardingOptimizer", "group_sharded_parallel",
           "shard_model_params"]


def _sharding_axis(hcg=None):
    hcg = hcg or get_hybrid_communicate_group()
    if hcg is not None and hcg.get_sharding_parallel_world_size() > 1:
        return hcg.mesh, "sharding"
    if hcg is not None and hcg.get_data_parallel_world_size() > 1:
        # paddle's group_sharded uses the dp group when no dedicated axis
        return hcg.mesh, "dp"
    mesh = global_mesh()
    return mesh, mesh.axis_names[0]


def _shard_spec_for(arr, mesh, axis) -> Optional[P]:
    """Shard dim 0 when it divides the axis size (XLA pads otherwise; for
    odd shapes we keep replication — same fallback the reference uses for
    tiny tensors)."""
    if arr.ndim == 0:
        return None
    g = int(mesh.shape[axis])
    if arr.shape[0] % g != 0:
        return None
    return P(axis, *([None] * (arr.ndim - 1)))


def _place(arr, mesh, spec):
    if spec is None:
        return jax.device_put(arr, NamedSharding(mesh, P()))
    return jax.device_put(arr, NamedSharding(mesh, spec))


class DygraphShardingOptimizer:
    """Wraps an inner optimizer; optimizer state (stage>=1), grads (stage>=2)
    and parameter storage (stage 3) live sharded over the sharding axis.

    Parity: DygraphShardingOptimizer / GroupShardedOptimizerStage2/3.
    """

    def __init__(self, inner_optimizer: Optimizer, hcg=None, stage: int = 1):
        self._inner = inner_optimizer
        self._mesh, self._axis = _sharding_axis(hcg)
        self.stage = int(stage)
        # intercept accumulator creation so every new slot is born sharded
        orig_acc = inner_optimizer._acc

        @functools.wraps(orig_acc)
        def sharded_acc(name, p, init=None, dtype=None):
            t = orig_acc(name, p, init=init, dtype=dtype)
            if not getattr(t, "_zero_sharded", False):
                from ...core.tensor import _is_tracer
                if not _is_tracer(t._data):
                    spec = _shard_spec_for(t._data, self._mesh, self._axis)
                    t._data = _place(t._data, self._mesh, spec)
                t._zero_sharded = True
            return t

        inner_optimizer._acc = sharded_acc
        if self.stage >= 3:
            shard_model_params(self._params(), self._mesh, self._axis)

    def _params(self):
        return self._inner._param_groups

    # --- optimizer surface ---------------------------------------------------
    def step(self) -> None:
        if self.stage >= 2:
            # keep grads sharded through the elementwise update; XLA then
            # reduce-scatters dp-grads instead of all-reducing (ZeRO-2)
            for p in self._params():
                if p.grad is not None:
                    spec = _shard_spec_for(p.grad._data, self._mesh, self._axis)
                    if spec is not None:
                        p.grad._set_data(jax.lax.with_sharding_constraint(
                            p.grad._data, NamedSharding(self._mesh, spec)))
        self._inner.step()
        # re-assert accumulator layout INSIDE the traced step: without this
        # the compiled program is free to write fresh accumulator values
        # back fully replicated, silently undoing ZeRO (the §7 hard-part-3
        # failure mode — pinned by tests/test_zero_sharding_proof.py)
        from ...core.tensor import _is_tracer
        for slots in self._inner._accumulators.values():
            for acc in slots.values():
                arr = acc._data
                if not _is_tracer(arr):
                    continue  # eager: birth-sharding already holds
                spec = _shard_spec_for(arr, self._mesh, self._axis)
                if spec is not None:
                    acc._set_data(jax.lax.with_sharding_constraint(
                        arr, NamedSharding(self._mesh, spec)))
        # re-assert the parameter layout after the update
        for p in self._params():
            if self.stage >= 3:
                spec = _shard_spec_for(p._data, self._mesh, self._axis)
                p._set_data(jax.lax.with_sharding_constraint(
                    p._data, NamedSharding(self._mesh, spec if spec else P())))
            else:
                p._set_data(jax.lax.with_sharding_constraint(
                    p._data, NamedSharding(self._mesh, P())))

    def clear_grad(self, *a, **k):
        self._inner.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def get_lr(self):
        return self._inner.get_lr()

    def set_lr(self, v):
        self._inner.set_lr(v)

    def state_dict(self):
        return self._inner.state_dict()

    def set_state_dict(self, sd):
        return self._inner.set_state_dict(sd)

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()
        return None, None

    def __getattr__(self, item):
        return getattr(self._inner, item)


def shard_model_params(params, mesh=None, axis=None) -> None:
    """Stage-3 parameter storage sharding (gather-on-use via GSPMD)."""
    if mesh is None:
        mesh, axis = _sharding_axis()
    for p in params:
        spec = _shard_spec_for(p._data, mesh, axis)
        p._set_data(_place(p._data, mesh, spec))


def group_sharded_parallel(model, optimizer, level: str = "os", scaler=None,
                           group=None, offload: bool = False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False):
    """Parity: paddle.distributed.sharding.group_sharded_parallel.

    level: 'os' = stage 1 (optimizer state), 'os_g' = stage 2 (+grads),
    'p_g_os' = stage 3 (+params).
    """
    stage = {"os": 1, "os_g": 2, "p_g_os": 3}[level]
    hcg = get_hybrid_communicate_group()
    wrapped_opt = DygraphShardingOptimizer(optimizer, hcg, stage=stage)
    if scaler is not None:
        return model, wrapped_opt, scaler
    return model, wrapped_opt
