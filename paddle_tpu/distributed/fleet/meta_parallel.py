"""``paddle.distributed.fleet.meta_parallel`` namespace (reference:
python/paddle/distributed/fleet/meta_parallel/) — re-exports the parallel
wrappers from their implementation modules."""

from .mp_layers import (  # noqa: F401
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy, get_rng_state_tracker,
)
from .pipeline_parallel import (  # noqa: F401
    LayerDesc, PipelineLayer, PipelineParallel, SharedLayerDesc,
)
from .tpu_pipeline import pipelined_forward, stack_stage_params  # noqa: F401
